"""Wave-4 parity tests: fused incubate functionals, distribution
transforms (torch oracles), amp.debugging module, nn.quant, dlpack
interop, unique_name, hub, sysconfig, cpp_extension setup surface."""
import os

import numpy as np
import pytest
import torch

import paddle_tpu as paddle

t = paddle.to_tensor
rng = np.random.RandomState(5)


class TestFusedFunctionals:
    F = None

    @classmethod
    def setup_class(cls):
        cls.F = paddle.incubate.nn.functional

    def test_fused_matmul_bias(self):
        x = rng.randn(2, 3).astype(np.float32)
        y = rng.randn(3, 4).astype(np.float32)
        b = rng.randn(4).astype(np.float32)
        out = self.F.fused_matmul_bias(t(x), t(y), t(b))
        np.testing.assert_allclose(out.numpy(), x @ y + b, atol=1e-5)

    def test_fused_linear_activation(self):
        x = rng.randn(2, 3).astype(np.float32)
        y = rng.randn(3, 4).astype(np.float32)
        b = np.zeros(4, np.float32)
        out = self.F.fused_linear_activation(t(x), t(y), t(b),
                                             activation="relu")
        np.testing.assert_allclose(out.numpy(),
                                   np.maximum(x @ y, 0), atol=1e-5)

    def test_fused_mha_shapes_and_grads(self):
        x = t(rng.randn(2, 6, 16).astype(np.float32), stop_gradient=False)
        qkvw = t(rng.randn(3, 4, 4, 16).astype(np.float32) * 0.1,
                 stop_gradient=False)
        lw = t(rng.randn(16, 16).astype(np.float32) * 0.1)
        out = self.F.fused_multi_head_attention(
            x, qkvw, lw, pre_layer_norm=True,
            pre_ln_scale=t(np.ones(16, np.float32)),
            pre_ln_bias=t(np.zeros(16, np.float32)),
            ln_scale=t(np.ones(16, np.float32)),
            ln_bias=t(np.zeros(16, np.float32)),
            dropout_rate=0.0, attn_dropout_rate=0.0, training=False)
        assert out.shape == [2, 6, 16]
        (out ** 2).mean().backward()
        assert np.isfinite(qkvw.grad.numpy()).all()

    def test_fused_mha_transpose_qkv_wb_matches_4d(self):
        """2-D (E, 3HD) qkv layout == the (3, H, D, E) layout it reshapes
        into (r3: transpose_qkv_wb was NotImplementedError)."""
        x = t(rng.randn(2, 6, 16).astype(np.float32))
        w4 = rng.randn(3, 4, 4, 16).astype(np.float32) * 0.1
        b4 = rng.randn(3, 4, 4).astype(np.float32) * 0.1
        lw = t(rng.randn(16, 16).astype(np.float32) * 0.1)
        kw = dict(pre_layer_norm=True,
                  pre_ln_scale=t(np.ones(16, np.float32)),
                  pre_ln_bias=t(np.zeros(16, np.float32)),
                  ln_scale=t(np.ones(16, np.float32)),
                  ln_bias=t(np.zeros(16, np.float32)),
                  dropout_rate=0.0, attn_dropout_rate=0.0, training=False)
        ref = self.F.fused_multi_head_attention(
            x, t(w4), lw, qkv_bias=t(b4), **kw)
        # (3, H, D, E) -> (E, 3HD); bias (3, H, D) -> (3HD,)
        w2d = w4.reshape(3 * 4 * 4, 16).T.copy()
        out = self.F.fused_multi_head_attention(
            x, t(w2d), lw, qkv_bias=t(b4.reshape(-1)), num_heads=4,
            transpose_qkv_wb=True, **kw)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-5)
        with pytest.raises(ValueError, match="num_heads"):
            self.F.fused_multi_head_attention(
                x, t(w2d), lw, transpose_qkv_wb=True, **kw)

    def test_fused_feedforward(self):
        x = t(rng.randn(2, 4, 8).astype(np.float32))
        w1 = t(rng.randn(8, 16).astype(np.float32) * 0.1)
        w2 = t(rng.randn(16, 8).astype(np.float32) * 0.1)
        out = self.F.fused_feedforward(
            x, w1, w2, dropout1_rate=0.0, dropout2_rate=0.0,
            ln2_scale=t(np.ones(8, np.float32)),
            ln2_bias=t(np.zeros(8, np.float32)), training=False)
        assert out.shape == [2, 4, 8]

    def test_varlen_attention_masks(self):
        q = t(rng.randn(2, 2, 6, 8).astype(np.float32))
        out = self.F.variable_length_memory_efficient_attention(
            q, q, q, t(np.array([6, 3], np.int32)),
            t(np.array([6, 3], np.int32)))
        assert np.abs(out.numpy()[1, :, 3:]).max() == 0.0
        assert np.abs(out.numpy()[0]).max() > 0.0

    def test_fused_multi_transformer_cached_decode(self):
        """Per-layer cache_kvs decode == the full causal pass (reference
        fused_transformer decode contract)."""
        import paddle_tpu as paddle
        from paddle_tpu.incubate.nn import FusedMultiTransformer
        paddle.seed(3)
        E, H, S, L = 16, 4, 4, 2
        net = FusedMultiTransformer(E, H, 32, dropout_rate=0.0,
                                    normalize_before=True, num_layers=L)
        net.eval()
        x = t(rng.randn(2, S, E).astype(np.float32))
        mask = np.where(np.tril(np.ones((S, S))), 0.0,
                        -1e9).astype(np.float32)
        full = net(x, attn_mask=t(mask[None, None]))
        caches = [t(np.zeros((2, 2, H, 0, E // H), np.float32))
                  for _ in range(L)]
        outs = []
        for step in range(S):
            o, caches = net(x[:, step:step + 1], caches=caches)
            outs.append(o.numpy())
        np.testing.assert_allclose(np.concatenate(outs, axis=1),
                                   full.numpy(), atol=1e-5)
        assert all(list(c.shape) == [2, 2, H, S, E // H] for c in caches)

    def test_fused_mha_cached_decode_matches_full_pass(self):
        """cache_kv decode (reference fused_transformer.py:592,841):
        feeding tokens one at a time through the growing (2,B,H,T,D)
        cache must reproduce the full causal pass exactly."""
        E, H, D, S = 16, 4, 4, 5
        w4 = t(rng.randn(3, H, D, E).astype(np.float32) * 0.1)
        lw = t(rng.randn(E, E).astype(np.float32) * 0.1)
        kw = dict(pre_layer_norm=True,
                  pre_ln_scale=t(np.ones(E, np.float32)),
                  pre_ln_bias=t(np.zeros(E, np.float32)),
                  dropout_rate=0.0, attn_dropout_rate=0.0, training=False)
        x = t(rng.randn(2, S, E).astype(np.float32))
        mask = np.where(np.tril(np.ones((S, S))), 0.0,
                        -1e9).astype(np.float32)
        full = self.F.fused_multi_head_attention(
            x, w4, lw, attn_mask=t(mask[None, None]), **kw)
        cache = t(np.zeros((2, 2, H, 0, D), np.float32))
        outs = []
        for step in range(S):
            o, cache = self.F.fused_multi_head_attention(
                x[:, step:step + 1], w4, lw, cache_kv=cache, **kw)
            outs.append(o.numpy())
        np.testing.assert_allclose(np.concatenate(outs, axis=1),
                                   full.numpy(), atol=1e-5)
        assert list(cache.shape) == [2, 2, H, S, D]


class TestDistributionTransforms:
    def test_stickbreaking_matches_torch(self):
        x = rng.randn(5).astype(np.float32)
        sb = paddle.distribution.StickBreakingTransform()
        y = sb.forward(t(x))
        ty = torch.distributions.StickBreakingTransform()(torch.tensor(x))
        np.testing.assert_allclose(y.numpy(), ty.numpy(), atol=1e-5)
        back = sb.inverse(y)
        np.testing.assert_allclose(back.numpy(), x, atol=1e-4)

    def test_softmax_and_reshape(self):
        x = rng.randn(4).astype(np.float32)
        st = paddle.distribution.SoftmaxTransform()
        np.testing.assert_allclose(float(st.forward(t(x)).numpy().sum()),
                                   1.0, atol=1e-5)
        rt = paddle.distribution.ReshapeTransform((6,), (2, 3))
        assert rt.forward(t(np.zeros(6, np.float32))).shape == [2, 3]
        assert rt.inverse(
            t(np.zeros((2, 3), np.float32))).shape == [6]
        with pytest.raises(ValueError):
            paddle.distribution.ReshapeTransform((6,), (2, 2))

    def test_stack_and_abs(self):
        stk = paddle.distribution.StackTransform(
            [paddle.distribution.ExpTransform(),
             paddle.distribution.ExpTransform()], axis=0)
        out = stk.forward(t(np.array([0.0, 1.0], np.float32)))
        np.testing.assert_allclose(out.numpy(), np.exp([0.0, 1.0]),
                                   atol=1e-5)
        ab = paddle.distribution.AbsTransform()
        np.testing.assert_allclose(
            ab.forward(t(np.array([-2.0], np.float32))).numpy(), [2.0])


class TestAmpDebugging:
    def test_check_numerics_counts(self):
        n, i, z = paddle.amp.debugging.check_numerics(
            t(np.array([np.nan, np.inf, 0.0, 1.0], np.float32)),
            "op", "v",
            debug_mode=paddle.amp.debugging.DebugMode.CHECK_NAN_INF)
        assert int(n.numpy()) == 1
        assert int(i.numpy()) == 1
        assert int(z.numpy()) == 1

    def test_check_numerics_aborts(self):
        with pytest.raises(FloatingPointError):
            paddle.amp.debugging.check_numerics(
                t(np.array([np.nan], np.float32)), "op", "v")

    def test_collect_operator_stats(self, capsys):
        with paddle.amp.debugging.collect_operator_stats():
            x = t(np.ones((2, 2), np.float32))
            (x @ x).sum()
        out = capsys.readouterr().out
        assert "matmul" in out

    def test_tensor_checker_flags(self):
        cfg = paddle.amp.debugging.TensorCheckerConfig(enable=True)
        paddle.amp.debugging.enable_tensor_checker(cfg)
        assert paddle.get_flags(["check_nan_inf"])["check_nan_inf"]
        paddle.amp.debugging.disable_tensor_checker()
        assert not paddle.get_flags(["check_nan_inf"])["check_nan_inf"]

    def test_compare_accuracy(self, tmp_path):
        a_dir, b_dir = tmp_path / "a", tmp_path / "b"
        os.makedirs(a_dir)
        os.makedirs(b_dir)
        np.save(a_dir / "t0.npy", np.ones(4))
        np.save(b_dir / "t0.npy", np.ones(4) + 1e-6)
        out_csv = str(tmp_path / "cmp.csv")
        rows = paddle.amp.debugging.compare_accuracy(
            str(a_dir), str(b_dir), out_csv)
        assert rows and rows[0][1] == "ok"
        assert os.path.exists(out_csv)


class TestNNQuant:
    def test_weight_only_linear(self):
        x = np.ones((2, 4), np.float32)
        w = (np.ones((3, 4)) * 2).astype(np.int8)
        scale = np.full(3, 0.5, np.float32)
        out = paddle.nn.quant.weight_only_linear(
            t(x), t(w), weight_scale=t(scale))
        np.testing.assert_allclose(out.numpy(), np.full((2, 3), 4.0))

    def test_llm_int8_linear_runs(self):
        x = rng.randn(2, 4).astype(np.float32)
        w = rng.randint(-127, 127, (3, 4)).astype(np.int8)
        scale = np.full(3, 0.01, np.float32)
        out = paddle.nn.quant.llm_int8_linear(t(x), t(w),
                                              weight_scale=t(scale))
        ref = x @ (w.astype(np.float32) * scale[:, None]).T
        np.testing.assert_allclose(out.numpy(), ref, atol=1e-4)

    def test_stub(self):
        s = paddle.nn.quant.Stub()
        x = t(np.ones(3, np.float32))
        assert s(x) is x


class TestInteropUtils:
    def test_dlpack_roundtrip_and_torch(self):
        x = t(np.arange(6.0, dtype=np.float32))
        y = paddle.utils.dlpack.from_dlpack(paddle.utils.dlpack.to_dlpack(x))
        np.testing.assert_allclose(y.numpy(), x.numpy())
        tt = torch.from_dlpack(paddle.utils.dlpack.to_dlpack(x))
        np.testing.assert_allclose(tt.numpy(), x.numpy())
        back = paddle.utils.dlpack.from_dlpack(tt)
        np.testing.assert_allclose(back.numpy(), x.numpy())

    def test_unique_name(self):
        with paddle.utils.unique_name.guard():
            a = paddle.utils.unique_name.generate("fc")
            b = paddle.utils.unique_name.generate("fc")
        assert a != b
        assert a.startswith("fc_")

    def test_hub(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "def tiny(n=4):\n"
            "    'Builds a tiny Linear'\n"
            "    import paddle_tpu as p\n"
            "    return p.nn.Linear(n, n)\n")
        assert paddle.hub.list(str(tmp_path)) == ["tiny"]
        assert "tiny" in paddle.hub.help(str(tmp_path), "tiny")
        net = paddle.hub.load(str(tmp_path), "tiny", 3)
        assert net.weight.shape == [3, 3]
        with pytest.raises(RuntimeError):
            paddle.hub.load("org/repo", "x", source="github")

    def test_sysconfig(self):
        assert os.path.isdir(paddle.sysconfig.get_include())
        assert isinstance(paddle.sysconfig.get_lib(), str)

    def test_cuda_extension_rejects_cu(self):
        with pytest.raises(RuntimeError):
            paddle.utils.cpp_extension.CUDAExtension(["kernel.cu"])

    def test_download_cache_miss_raises(self):
        with pytest.raises(RuntimeError):
            paddle.utils.download.get_weights_path_from_url(
                "https://example.com/nonexistent_weights_xyz.pdparams")


class TestIncubateAutogradASP:
    def test_vjp_jvp(self):
        IA = paddle.incubate.autograd

        def f(x):
            return (x * x).sum()
        out, g = IA.vjp(f, t(np.array([1.0, 2.0], np.float32)))
        assert float(out.numpy()) == 5.0
        np.testing.assert_allclose(g.numpy(), [2.0, 4.0])
        _, tangent = IA.jvp(f, t(np.array([1.0, 2.0], np.float32)))
        assert float(tangent.numpy()) == 6.0

    def test_jacobian_hessian(self):
        IA = paddle.incubate.autograd
        J = IA.Jacobian(lambda x: x * 3,
                        t(np.array([1.0, 2.0], np.float32)))
        np.testing.assert_allclose(np.asarray(J[:].numpy()),
                                   np.eye(2) * 3)
        H = IA.Hessian(lambda x: (x ** 2).sum(),
                       t(np.array([1.0, 2.0], np.float32)))
        np.testing.assert_allclose(np.asarray(H[:].numpy()),
                                   np.eye(2) * 2)

    def test_asp_prune_and_decorate(self):
        paddle.seed(0)
        lin = paddle.nn.Linear(8, 4)
        paddle.incubate.asp.prune_model(lin)
        assert abs(paddle.incubate.asp.calculate_density(lin.weight)
                   - 0.5) < 1e-6
        opt = paddle.incubate.asp.decorate(
            paddle.optimizer.SGD(0.1, parameters=lin.parameters()))
        x = t(np.ones((2, 8), np.float32))
        loss = (lin(x) ** 2).mean()
        loss.backward()
        opt.step()
        # mask survives the optimizer step
        assert abs(paddle.incubate.asp.calculate_density(lin.weight)
                   - 0.5) < 1e-6

    def test_tensor_mp_pickle(self):
        import pickle
        x = t(np.arange(3.0, dtype=np.float32))
        y = pickle.loads(pickle.dumps(x))
        np.testing.assert_allclose(y.numpy(), x.numpy())

    def test_autotune_set_config(self):
        from paddle_tpu.core import autotune as core_at
        paddle.incubate.autotune.set_config({"kernel": {"enable": True}})
        assert core_at.autotune_status()["use_autotune"]
        paddle.incubate.autotune.set_config({"kernel": {"enable": False}})
        assert not core_at.autotune_status()["use_autotune"]


class TestIncubateLayers:
    """paddle.incubate.layers generic subset (reference
    incubate/layers/nn.py — shuffle_batch:447, partial_concat:511,
    partial_sum:589, batch_fc:1028, fused_bn_add_act:1297,
    pow2_decay_with_linear_warmup:1502, fused_embedding_seq_pool:37)."""

    def test_shuffle_batch_permutes_rows(self):
        from paddle_tpu.incubate import layers as L
        x = t(np.arange(8, dtype=np.float32).reshape(4, 2))
        s = L.shuffle_batch(x, seed=7)
        assert sorted(map(tuple, s.numpy().tolist())) == \
            sorted(map(tuple, x.numpy().tolist()))

    def test_partial_concat_and_sum(self):
        from paddle_tpu.incubate import layers as L
        a = t(np.arange(6, dtype=np.float32).reshape(2, 3))
        b = t(np.arange(6, 12, dtype=np.float32).reshape(2, 3))
        pc = L.partial_concat([a, b], start_index=1, length=2)
        np.testing.assert_array_equal(
            pc.numpy(), np.concatenate([a.numpy()[:, 1:3],
                                        b.numpy()[:, 1:3]], 1))
        ps = L.partial_sum([a, b], start_index=0, length=2)
        np.testing.assert_array_equal(
            ps.numpy(), a.numpy()[:, :2] + b.numpy()[:, :2])

    def test_batch_fc_shapes_and_grad(self):
        import paddle_tpu as paddle
        from paddle_tpu.incubate import layers as L
        paddle.seed(0)
        x = t(np.ones((2, 3, 4), np.float32), stop_gradient=False)
        out = L.batch_fc(x, [2, 4, 5], None, [2, 5], None, act="relu")
        assert out.shape == [2, 3, 5]
        (out ** 2).mean().backward()
        assert np.isfinite(x.grad.numpy()).all()

    def test_pow2_decay_with_linear_warmup(self):
        from paddle_tpu.incubate import layers as L
        sched = L.pow2_decay_with_linear_warmup(10, 100, 0.1, 0.001)
        lrs = []
        for _ in range(100):
            lrs.append(sched.get_lr())
            sched.step()
        assert abs(lrs[9] - 0.1) < 1e-9          # warmup tops out at base
        assert lrs[0] < lrs[5] < lrs[9]          # linear ramp
        assert lrs[10] > lrs[50] > lrs[-1] >= 0.001  # pow2 decay to end

    def test_fused_embedding_seq_pool_padding(self):
        import paddle_tpu as paddle
        from paddle_tpu.incubate import layers as L
        paddle.seed(1)
        ids = t(np.array([[1, 2, 0], [3, 0, 0]], np.int64))
        pooled = L.fused_embedding_seq_pool(ids, (10, 4), padding_idx=0)
        assert pooled.shape == [2, 4]
        # named attr -> ONE shared table: padded row [3,0,0] pools to
        # exactly the same vector as [3] alone
        attr = paddle.ParamAttr(name="fesp_shared")
        mixed = L.fused_embedding_seq_pool(
            t(np.array([[3, 0, 0]], np.int64)), (10, 4), padding_idx=0,
            param_attr=attr)
        only3 = L.fused_embedding_seq_pool(
            t(np.array([[3]], np.int64)), (10, 4), param_attr=attr)
        np.testing.assert_allclose(mixed.numpy(), only3.numpy(), rtol=1e-6)
        # all-padding pools to exactly zero; OOB ids raise; negative
        # padding_idx normalizes to size+padding_idx
        allpad = L.fused_embedding_seq_pool(
            t(np.array([[0, 0]], np.int64)), (10, 4), padding_idx=0)
        np.testing.assert_array_equal(allpad.numpy(), 0.0)
        with pytest.raises(ValueError, match="out of range"):
            L.fused_embedding_seq_pool(t(np.array([[10]], np.int64)),
                                       (10, 4))
        neg = L.fused_embedding_seq_pool(
            t(np.array([[9, 9]], np.int64)), (10, 4), padding_idx=-1)
        np.testing.assert_array_equal(neg.numpy(), 0.0)

    def test_fused_bn_add_act(self):
        from paddle_tpu.incubate import layers as L
        x = t(np.random.RandomState(0).randn(4, 8).astype("float32"))
        y = t(np.zeros((4, 8), np.float32))
        out = L.fused_bn_add_act(x, y)
        assert out.shape == [4, 8] and float(out.min()) >= 0

    def test_multiclass_nms2(self):
        from paddle_tpu.incubate import layers as L
        bb = np.array([[[0, 0, 10, 10], [1, 1, 11, 11],
                        [50, 50, 60, 60]]], np.float32)
        sc = np.zeros((1, 2, 3), np.float32)
        sc[0, 1] = [0.9, 0.8, 0.7]
        out, idx, rn = L.multiclass_nms2(
            t(bb), t(sc), score_threshold=0.1, nms_top_k=10,
            keep_top_k=10, nms_threshold=0.5, return_index=True,
            return_rois_num=True)
        o = np.asarray(out._data)
        assert o.shape == (2, 6) and int(rn.numpy()[0]) == 2
        np.testing.assert_allclose(sorted(o[:, 1]), [0.7, 0.9])
        assert set(np.asarray(idx._data).tolist()) == {0, 2}
        # reference arity: bare call returns the tensor alone
        out_only = L.multiclass_nms2(
            t(bb), t(sc), score_threshold=0.1, nms_top_k=10,
            keep_top_k=1, nms_threshold=0.5)
        assert np.asarray(out_only._data).shape == (1, 6)
        assert float(np.asarray(out_only._data)[0, 1]) == np.float32(0.9)
        # nms_top_k=-1 keeps every candidate above threshold
        sc3 = np.zeros((1, 2, 3), np.float32)
        sc3[0, 1] = [0.9, 0.8, 0.7]
        bb3 = np.array([[[0, 0, 1, 1], [10, 10, 11, 11],
                         [20, 20, 21, 21]]], np.float32)
        all3 = L.multiclass_nms2(t(bb3), t(sc3), score_threshold=0.1,
                                 nms_top_k=-1, keep_top_k=-1,
                                 nms_threshold=0.5)
        assert np.asarray(all3._data).shape == (3, 6)
        # adaptive nms_eta: threshold shrinks AFTER the first kept box,
        # so a 0.6-IoU pair is suppressed at eta<1 but kept at eta=1
        bbA = np.array([[[0, 0, 10, 4.0], [0, 0, 10, 6.65],
                         [50, 50, 60, 60]]], np.float32)
        scA = np.zeros((1, 2, 3), np.float32)
        scA[0, 1] = [0.9, 0.8, 0.7]
        keep_eta1 = L.multiclass_nms2(t(bbA), t(scA), 0.1, -1, -1,
                                      nms_threshold=0.7, nms_eta=1.0)
        keep_eta = L.multiclass_nms2(t(bbA), t(scA), 0.1, -1, -1,
                                     nms_threshold=0.7, nms_eta=0.8)
        assert np.asarray(keep_eta1._data).shape[0] == 3
        assert np.asarray(keep_eta._data).shape[0] == 2


class TestTopPSamplingThreshold:
    def test_threshold_floors_low_prob_tokens(self):
        """(x, ps, threshold, seed) contract (reference search.py:1235):
        threshold is an absolute per-row probability floor applied
        simultaneously with ps."""
        import paddle_tpu as paddle
        paddle.seed(0)
        x = t(np.array([[5.0, 3.0, -2.0, -2.0]], np.float32))
        ps = t(np.array([0.99], np.float32))
        thr = t(np.array([0.5], np.float32))
        seen = set()
        for _ in range(20):
            _, idx = paddle.tensor.top_p_sampling(x, ps, threshold=thr)
            seen.add(int(idx.numpy()[0, 0]))
        assert seen == {0}
        seen2 = set()
        for _ in range(50):
            _, idx = paddle.tensor.top_p_sampling(x, ps)
            seen2.add(int(idx.numpy()[0, 0]))
        assert {0, 1} <= seen2

    def test_per_row_topp_seed(self):
        """topp_seed is a [B, 1] PER-ROW seed tensor: same seed -> same
        draw per row; changing one row's seed leaves other rows fixed."""
        import paddle_tpu as paddle
        x = t(np.random.RandomState(2).randn(3, 32).astype(np.float32))
        ps = t(np.full(3, 0.95, np.float32))
        s1 = t(np.array([[1], [2], [3]], np.int64))
        s2 = t(np.array([[1], [999], [3]], np.int64))
        _, a = paddle.tensor.top_p_sampling(x, ps, topp_seed=s1)
        _, b = paddle.tensor.top_p_sampling(x, ps, topp_seed=s1)
        np.testing.assert_array_equal(a.numpy(), b.numpy())
        _, c = paddle.tensor.top_p_sampling(x, ps, topp_seed=s2)
        assert a.numpy()[0, 0] == c.numpy()[0, 0]
        assert a.numpy()[2, 0] == c.numpy()[2, 0]
        diffs = 0
        for v in range(5):
            xs = t(np.random.RandomState(10 + v)
                   .randn(3, 32).astype(np.float32))
            _, d1 = paddle.tensor.top_p_sampling(xs, ps, topp_seed=s1)
            _, d2 = paddle.tensor.top_p_sampling(xs, ps, topp_seed=s2)
            diffs += int(d1.numpy()[1, 0] != d2.numpy()[1, 0])
        assert diffs > 0, "row-1 seed has no effect"
