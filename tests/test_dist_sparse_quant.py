"""distribution / sparse / quantization tests (reference test models:
test/distribution/, test/legacy_test/test_sparse_*.py,
test/quantization/)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse
from paddle_tpu.distribution import (Bernoulli, Categorical, Exponential,
                                     Normal, Uniform, kl_divergence)
from paddle_tpu.quantization import (QAT, FakeQuanterWithAbsMax,
                                     QuantConfig, WeightOnlyLinear,
                                     dequantize_linear, quantize_linear,
                                     abs_max_scale, weight_quantize)


@pytest.fixture(autouse=True)
def _seed():
    paddle.seed(0)


class TestDistributions:
    def test_normal_sample_moments(self):
        d = Normal(loc=2.0, scale=3.0)
        s = d.sample([20000]).numpy()
        assert abs(s.mean() - 2.0) < 0.1
        assert abs(s.std() - 3.0) < 0.1

    def test_normal_log_prob_matches_closed_form(self):
        d = Normal(0.0, 1.0)
        x = paddle.to_tensor(np.array([0.0, 1.0, -2.0], np.float32))
        lp = d.log_prob(x).numpy()
        ref = -0.5 * np.array([0.0, 1.0, 4.0]) - 0.5 * np.log(2 * np.pi)
        np.testing.assert_allclose(lp, ref, rtol=1e-5)

    def test_normal_kl_zero_for_same(self):
        p = Normal(1.0, 2.0)
        np.testing.assert_allclose(float(kl_divergence(p, Normal(1.0, 2.0))),
                                   0.0, atol=1e-7)
        assert float(kl_divergence(p, Normal(3.0, 1.0))) > 0

    def test_uniform(self):
        d = Uniform(1.0, 3.0)
        s = d.sample([5000]).numpy()
        assert s.min() >= 1.0 and s.max() < 3.0
        np.testing.assert_allclose(float(d.entropy()), np.log(2.0),
                                   rtol=1e-6)
        lp = d.log_prob(paddle.to_tensor(np.array([2.0, 5.0], np.float32)))
        assert np.isneginf(lp.numpy()[1])

    def test_bernoulli(self):
        d = Bernoulli(0.7)
        s = d.sample([10000]).numpy()
        assert abs(s.mean() - 0.7) < 0.05
        assert float(d.variance) == pytest.approx(0.21, abs=1e-6)

    def test_categorical(self):
        logits = np.log(np.array([0.2, 0.3, 0.5], np.float32))
        d = Categorical(logits)
        s = d.sample([20000]).numpy()
        freq = np.bincount(s, minlength=3) / len(s)
        np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.03)
        lp = d.log_prob(paddle.to_tensor(np.array([2], np.int64)))
        np.testing.assert_allclose(lp.numpy(), [np.log(0.5)], rtol=1e-5)

    def test_exponential_and_kl(self):
        d = Exponential(2.0)
        s = d.sample([20000]).numpy()
        assert abs(s.mean() - 0.5) < 0.05
        assert float(kl_divergence(d, Exponential(2.0))) == \
            pytest.approx(0.0, abs=1e-7)

    def test_kl_unregistered_raises(self):
        with pytest.raises(NotImplementedError):
            kl_divergence(Normal(0, 1), Uniform(0, 1))

    def test_log_prob_differentiable(self):
        d = Normal(0.0, 1.0)
        x = paddle.to_tensor(np.array([0.5], np.float32))
        x.stop_gradient = False
        lp = d.log_prob(x).sum()
        lp.backward()
        np.testing.assert_allclose(x.grad.numpy(), [-0.5], rtol=1e-5)


class TestSparse:
    def _coo(self):
        idx = [[0, 1, 2], [1, 0, 2]]
        vals = [1.0, 2.0, 3.0]
        return sparse.sparse_coo_tensor(idx, vals, [3, 3])

    def test_to_dense(self):
        dense = self._coo().to_dense().numpy()
        ref = np.zeros((3, 3), np.float32)
        ref[0, 1], ref[1, 0], ref[2, 2] = 1, 2, 3
        np.testing.assert_array_equal(dense, ref)

    def test_duplicate_indices_coalesce(self):
        t = sparse.sparse_coo_tensor([[0, 0], [1, 1]], [1.0, 5.0], [2, 2])
        c = t.coalesce()
        assert c.nnz() == 1
        np.testing.assert_allclose(np.asarray(c.values), [6.0])
        np.testing.assert_array_equal(t.to_dense().numpy(),
                                      [[0, 6], [0, 0]])

    def test_add(self):
        a = self._coo()
        b = sparse.sparse_coo_tensor([[0], [1]], [10.0], [3, 3])
        out = sparse.add(a, b)
        np.testing.assert_array_equal(
            out.to_dense().numpy(),
            a.to_dense().numpy() + b.to_dense().numpy())

    def test_matmul_matches_dense(self):
        a = self._coo()
        y = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        out = sparse.matmul(a, paddle.to_tensor(y)).numpy()
        np.testing.assert_allclose(out, a.to_dense().numpy() @ y,
                                   rtol=1e-5)

    def test_matmul_grad_flows_to_dense(self):
        a = self._coo()
        y = paddle.to_tensor(np.ones((3, 2), np.float32))
        y.stop_gradient = False
        out = sparse.matmul(a, y).sum()
        out.backward()
        # d(sum)/dy[k, n] = sum of column k of the sparse matrix
        col_sums = a.to_dense().numpy().sum(axis=0)
        np.testing.assert_allclose(y.grad.numpy(),
                                   np.stack([col_sums] * 2, 1), rtol=1e-5)

    def test_csr_roundtrip(self):
        csr = self._coo().to_sparse_csr()
        np.testing.assert_array_equal(np.asarray(csr.crows), [0, 1, 2, 3])
        back = csr.to_sparse_coo()
        np.testing.assert_array_equal(back.to_dense().numpy(),
                                      self._coo().to_dense().numpy())

    def test_masked_matmul(self):
        rng = np.random.RandomState(0)
        x = rng.randn(3, 4).astype(np.float32)
        y = rng.randn(4, 3).astype(np.float32)
        mask = self._coo()
        out = sparse.masked_matmul(paddle.to_tensor(x), paddle.to_tensor(y),
                                   mask)
        full = x @ y
        for k in range(mask.nnz()):
            i, j = int(mask.indices[0][k]), int(mask.indices[1][k])
            np.testing.assert_allclose(float(out.values[k]), full[i, j],
                                       rtol=1e-5)

    def test_relu(self):
        t = sparse.sparse_coo_tensor([[0, 1], [0, 1]], [-1.0, 2.0], [2, 2])
        np.testing.assert_array_equal(
            sparse.relu(t).to_dense().numpy(), [[0, 0], [0, 2]])


class TestQuantization:
    def test_quantize_dequantize_roundtrip(self):
        x = paddle.to_tensor(np.linspace(-1, 1, 11).astype(np.float32))
        scale = abs_max_scale(x)
        q = quantize_linear(x, scale)
        assert str(q.dtype) == "int8"
        back = dequantize_linear(q, scale).numpy()
        np.testing.assert_allclose(back, x.numpy(), atol=float(scale))

    def test_fake_quant_straight_through_grad(self):
        fq = FakeQuanterWithAbsMax()
        fq.train()
        x = paddle.to_tensor(np.array([0.5, -0.3], np.float32))
        x.stop_gradient = False
        out = fq(x).sum()
        out.backward()
        np.testing.assert_allclose(x.grad.numpy(), [1.0, 1.0])

    def test_qat_converts_linears(self):
        net = paddle.nn.Sequential(paddle.nn.Linear(4, 8),
                                   paddle.nn.ReLU(),
                                   paddle.nn.Linear(8, 2))
        cfg = QuantConfig(activation=FakeQuanterWithAbsMax,
                          weight=FakeQuanterWithAbsMax)
        qnet = QAT(cfg).quantize(net)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 4).astype(np.float32))
        out = qnet(x)
        assert out.shape == [2, 2]
        # original float net untouched (inplace=False)
        assert isinstance(net[0], paddle.nn.Linear)

    def test_weight_only_linear_close_to_float(self):
        lin = paddle.nn.Linear(16, 8)
        wo = WeightOnlyLinear(lin)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 16).astype(np.float32))
        ref = lin(x).numpy()
        got = wo(x).numpy()
        assert np.abs(got - ref).max() < 0.05
        qw, scales = weight_quantize(lin.weight)
        assert str(qw.dtype) == "int8"
        assert scales.shape == [8]


class TestSparseWave2:
    """Deepened sparse surface (VERDICT r1 #10): grads through
    matmul/sddmm, unary value ops, transpose/sum/softmax/mv."""

    def _coo(self, seed=0):
        rng = np.random.RandomState(seed)
        idx = np.array([[0, 0, 1, 3], [1, 3, 2, 0]])
        vals = rng.randn(4).astype(np.float32)
        return paddle.sparse.sparse_coo_tensor(idx, vals, [4, 4]), idx, vals

    def test_spmm_grads_flow_to_dense(self):
        sp, idx, vals = self._coo()
        y = paddle.to_tensor(np.random.RandomState(1)
                             .randn(4, 3).astype(np.float32))
        y.stop_gradient = False
        out = paddle.sparse.matmul(sp, y)
        out.sum().backward()
        assert y.grad is not None
        # oracle: dense matmul grad
        dense = sp.to_dense().numpy()
        np.testing.assert_allclose(y.grad.numpy(),
                                   dense.T @ np.ones((4, 3), np.float32),
                                   rtol=1e-5)

    def test_sddmm_values_and_grads(self):
        """SDDMM: values match dense a@b at the mask, and grads flow to
        both dense operands through the taped op."""
        import jax.numpy as jnp
        from paddle_tpu.core.dispatch import run_op
        sp, idx, vals = self._coo()
        rng = np.random.RandomState(2)
        a = paddle.to_tensor(rng.randn(4, 5).astype(np.float32))
        b = paddle.to_tensor(rng.randn(5, 4).astype(np.float32))
        a.stop_gradient = False
        b.stop_gradient = False
        out = paddle.sparse.masked_matmul(a, b, sp)
        ref = (a.numpy() @ b.numpy())[tuple(idx)]
        np.testing.assert_allclose(np.asarray(out.values), ref, rtol=1e-5)
        # grads: rerun the op keeping the Tensor head (masked_matmul stores
        # raw values; the taped intermediate drives backward)
        rows, cols = idx[0], idx[1]
        vals_t = run_op(
            "sparse_sddmm",
            lambda x, y: jnp.einsum("nk,nk->n", x[rows], y[:, cols].T),
            (a, b))
        vals_t.sum().backward()
        assert a.grad is not None and b.grad is not None
        # oracle: d(sum of masked products)/da = sum_j mask_ij * b.T
        mask = np.zeros((4, 4), np.float32)
        mask[tuple(idx)] = 1.0
        np.testing.assert_allclose(a.grad.numpy(), mask @ b.numpy().T,
                                   rtol=1e-5)

    def test_unary_ops_match_dense_oracle(self):
        sp, idx, vals = self._coo(5)
        for name in ("sin", "tanh", "square", "abs", "neg", "expm1",
                     "log1p"):
            if name in ("log1p",):
                sp_pos = paddle.sparse.sparse_coo_tensor(
                    idx, np.abs(vals), [4, 4])
                out = getattr(paddle.sparse, name)(sp_pos)
                ref = getattr(np, name)(np.abs(vals))
            else:
                out = getattr(paddle.sparse, name)(sp)
                ref = {"neg": lambda v: -v}.get(
                    name, getattr(np, name, None))
                ref = ref(vals) if callable(ref) else None
            if ref is not None:
                np.testing.assert_allclose(np.asarray(out.values), ref,
                                           rtol=1e-5)
            assert np.array_equal(np.asarray(out.indices), idx)

    def test_transpose(self):
        sp, idx, vals = self._coo(6)
        tr = paddle.sparse.transpose(sp, [1, 0])
        np.testing.assert_allclose(np.asarray(tr.to_dense()._data),
                                   sp.to_dense().numpy().T, rtol=1e-6)

    def test_sum(self):
        sp, idx, vals = self._coo(7)
        total = paddle.sparse.sum(sp)
        np.testing.assert_allclose(float(total), vals.sum(), rtol=1e-5)
        by_row = paddle.sparse.sum(sp, axis=1)
        np.testing.assert_allclose(np.asarray(by_row.to_dense()._data),
                                   sp.to_dense().numpy().sum(1), rtol=1e-5)

    def test_softmax_matches_masked_dense(self):
        sp, idx, vals = self._coo(8)
        out = paddle.sparse.softmax(sp)
        dense = sp.to_dense().numpy()
        mask = np.zeros_like(dense, bool)
        mask[tuple(idx)] = True
        masked = np.where(mask, dense, -np.inf)
        ref = np.exp(masked - masked.max(1, keepdims=True))
        ref = np.nan_to_num(ref / np.maximum(ref.sum(1, keepdims=True),
                                             1e-30))
        np.testing.assert_allclose(np.asarray(out.to_dense()._data)[mask],
                                   ref[mask], rtol=1e-5)

    def test_mv(self):
        sp, idx, vals = self._coo(9)
        v = paddle.to_tensor(np.random.RandomState(4)
                             .randn(4).astype(np.float32))
        out = paddle.sparse.mv(sp, v)
        np.testing.assert_allclose(out.numpy(),
                                   sp.to_dense().numpy() @ v.numpy(),
                                   rtol=1e-5)

    def test_subtract_divide(self):
        sp1, idx, vals = self._coo(10)
        sp2 = paddle.sparse.sparse_coo_tensor(idx, np.ones(4, np.float32),
                                              [4, 4])
        sub = paddle.sparse.subtract(sp1, sp2)
        np.testing.assert_allclose(np.asarray(sub.to_dense()._data),
                                   sp1.to_dense().numpy()
                                   - sp2.to_dense().numpy(), rtol=1e-5)


class TestQuantWave2:
    def test_per_channel_beats_per_tensor_on_skewed_channels(self):
        from paddle_tpu.quantization import (FakeQuanterChannelWiseAbsMax,
                                             FakeQuanterWithAbsMax)
        rng = np.random.RandomState(0)
        w = np.concatenate([rng.randn(16, 8) * 0.01,
                            rng.randn(16, 8) * 10.0], axis=1
                           ).astype(np.float32)
        wt = paddle.to_tensor(w)
        pc = FakeQuanterChannelWiseAbsMax(quant_axis=1)(wt)
        pt_q = FakeQuanterWithAbsMax()
        pt_q.train()
        pt = pt_q(wt)
        # the small-range channels are where per-tensor scales destroy
        # precision: per-channel must recover them
        err_pc_small = np.abs(pc.numpy()[:, :8] - w[:, :8]).mean()
        err_pt_small = np.abs(pt.numpy()[:, :8] - w[:, :8]).mean()
        assert err_pc_small < err_pt_small / 50
        assert np.abs(pc.numpy() - w).mean() < np.abs(pt.numpy() - w).mean()

    def test_hist_observer_clips_outliers(self):
        from paddle_tpu.quantization import AbsmaxObserver, HistObserver
        rng = np.random.RandomState(1)
        data = rng.randn(10000).astype(np.float32)
        data[0] = 1000.0  # one absurd outlier
        h = HistObserver(percent=0.999)
        a = AbsmaxObserver()
        h(paddle.to_tensor(data))
        a(paddle.to_tensor(data))
        assert h.scale() < a.scale() / 10  # percentile ignores the outlier
        assert h.scale() * 127 > 2.0      # but keeps the gaussian body

    def test_ptq_calibrate_convert_close_to_fp32(self):
        from paddle_tpu.quantization import PTQ, QuantConfig
        paddle.seed(3)
        model = paddle.nn.Sequential(paddle.nn.Linear(8, 16),
                                     paddle.nn.ReLU(),
                                     paddle.nn.Linear(16, 4))
        ptq = PTQ(QuantConfig())
        observed = ptq.quantize(model)
        rng = np.random.RandomState(2)
        for _ in range(4):
            observed(paddle.to_tensor(rng.randn(16, 8).astype(np.float32)))
        frozen = ptq.convert(observed)
        x = paddle.to_tensor(rng.randn(16, 8).astype(np.float32))
        ref = model(x).numpy()
        got = frozen(x).numpy()
        assert np.abs(got - ref).mean() < 0.05 * np.abs(ref).mean() + 0.05


class TestMemoryStats:
    def test_memory_stats_surface(self):
        import paddle_tpu.device as device
        stats = device.memory_stats()
        assert isinstance(stats, dict)
        # the numeric shims never raise regardless of platform support
        assert device.cuda.memory_allocated() >= 0
        assert device.cuda.max_memory_allocated() >= 0


class TestReviewRegressionsWave2:
    def test_divide_no_nan_fill(self):
        idx = np.array([[0, 1], [1, 2]])
        x = paddle.sparse.sparse_coo_tensor(idx, np.array([2.0, 4.0],
                                                          np.float32), [4, 4])
        y = paddle.sparse.sparse_coo_tensor(idx, np.array([1.0, 2.0],
                                                          np.float32), [4, 4])
        out = paddle.sparse.divide(x, y)
        assert out.nnz() == 2  # pattern preserved, no numel explosion
        vals = np.asarray(out.to_dense()._data)
        assert np.isfinite(vals).all()
        np.testing.assert_allclose(vals[0, 1], 2.0)
        np.testing.assert_allclose(vals[1, 2], 2.0)

    def test_scale_bias_order(self):
        idx = np.array([[0], [0]])
        x = paddle.sparse.sparse_coo_tensor(idx, np.array([3.0], np.float32),
                                            [2, 2])
        after = paddle.sparse.scale(x, 2.0, 1.0, bias_after_scale=True)
        before = paddle.sparse.scale(x, 2.0, 1.0, bias_after_scale=False)
        assert float(np.asarray(after.values)[0]) == 7.0   # 3*2+1
        assert float(np.asarray(before.values)[0]) == 8.0  # (3+1)*2

    def test_channel_scale_negative_axis(self):
        from paddle_tpu.quantization import channel_wise_abs_max_scale
        w = paddle.to_tensor(np.array([[0.01, 1.0], [0.02, 2.0]],
                                      np.float32))
        neg = np.asarray(channel_wise_abs_max_scale(w, -1))
        pos_ = np.asarray(channel_wise_abs_max_scale(w, 1))
        np.testing.assert_allclose(neg, pos_)
        assert neg.shape == (2,)

    def test_ptq_rejects_qat_quanter(self):
        from paddle_tpu.quantization import PTQ, QuantConfig
        m = paddle.nn.Sequential(paddle.nn.Linear(4, 4))
        ptq = PTQ(QuantConfig(activation=FakeQuanterWithAbsMax))
        with pytest.raises(TypeError, match="observer with a scale"):
            ptq.quantize(m)
