"""distribution / sparse / quantization tests (reference test models:
test/distribution/, test/legacy_test/test_sparse_*.py,
test/quantization/)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse
from paddle_tpu.distribution import (Bernoulli, Categorical, Exponential,
                                     Normal, Uniform, kl_divergence)
from paddle_tpu.quantization import (QAT, FakeQuanterWithAbsMax,
                                     QuantConfig, WeightOnlyLinear,
                                     dequantize_linear, quantize_linear,
                                     abs_max_scale, weight_quantize)


@pytest.fixture(autouse=True)
def _seed():
    paddle.seed(0)


class TestDistributions:
    def test_normal_sample_moments(self):
        d = Normal(loc=2.0, scale=3.0)
        s = d.sample([20000]).numpy()
        assert abs(s.mean() - 2.0) < 0.1
        assert abs(s.std() - 3.0) < 0.1

    def test_normal_log_prob_matches_closed_form(self):
        d = Normal(0.0, 1.0)
        x = paddle.to_tensor(np.array([0.0, 1.0, -2.0], np.float32))
        lp = d.log_prob(x).numpy()
        ref = -0.5 * np.array([0.0, 1.0, 4.0]) - 0.5 * np.log(2 * np.pi)
        np.testing.assert_allclose(lp, ref, rtol=1e-5)

    def test_normal_kl_zero_for_same(self):
        p = Normal(1.0, 2.0)
        np.testing.assert_allclose(float(kl_divergence(p, Normal(1.0, 2.0))),
                                   0.0, atol=1e-7)
        assert float(kl_divergence(p, Normal(3.0, 1.0))) > 0

    def test_uniform(self):
        d = Uniform(1.0, 3.0)
        s = d.sample([5000]).numpy()
        assert s.min() >= 1.0 and s.max() < 3.0
        np.testing.assert_allclose(float(d.entropy()), np.log(2.0),
                                   rtol=1e-6)
        lp = d.log_prob(paddle.to_tensor(np.array([2.0, 5.0], np.float32)))
        assert np.isneginf(lp.numpy()[1])

    def test_bernoulli(self):
        d = Bernoulli(0.7)
        s = d.sample([10000]).numpy()
        assert abs(s.mean() - 0.7) < 0.05
        assert float(d.variance) == pytest.approx(0.21, abs=1e-6)

    def test_categorical(self):
        logits = np.log(np.array([0.2, 0.3, 0.5], np.float32))
        d = Categorical(logits)
        s = d.sample([20000]).numpy()
        freq = np.bincount(s, minlength=3) / len(s)
        np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.03)
        lp = d.log_prob(paddle.to_tensor(np.array([2], np.int64)))
        np.testing.assert_allclose(lp.numpy(), [np.log(0.5)], rtol=1e-5)

    def test_exponential_and_kl(self):
        d = Exponential(2.0)
        s = d.sample([20000]).numpy()
        assert abs(s.mean() - 0.5) < 0.05
        assert float(kl_divergence(d, Exponential(2.0))) == \
            pytest.approx(0.0, abs=1e-7)

    def test_kl_unregistered_raises(self):
        with pytest.raises(NotImplementedError):
            kl_divergence(Normal(0, 1), Uniform(0, 1))

    def test_log_prob_differentiable(self):
        d = Normal(0.0, 1.0)
        x = paddle.to_tensor(np.array([0.5], np.float32))
        x.stop_gradient = False
        lp = d.log_prob(x).sum()
        lp.backward()
        np.testing.assert_allclose(x.grad.numpy(), [-0.5], rtol=1e-5)


class TestSparse:
    def _coo(self):
        idx = [[0, 1, 2], [1, 0, 2]]
        vals = [1.0, 2.0, 3.0]
        return sparse.sparse_coo_tensor(idx, vals, [3, 3])

    def test_to_dense(self):
        dense = self._coo().to_dense().numpy()
        ref = np.zeros((3, 3), np.float32)
        ref[0, 1], ref[1, 0], ref[2, 2] = 1, 2, 3
        np.testing.assert_array_equal(dense, ref)

    def test_duplicate_indices_coalesce(self):
        t = sparse.sparse_coo_tensor([[0, 0], [1, 1]], [1.0, 5.0], [2, 2])
        c = t.coalesce()
        assert c.nnz() == 1
        np.testing.assert_allclose(np.asarray(c.values), [6.0])
        np.testing.assert_array_equal(t.to_dense().numpy(),
                                      [[0, 6], [0, 0]])

    def test_add(self):
        a = self._coo()
        b = sparse.sparse_coo_tensor([[0], [1]], [10.0], [3, 3])
        out = sparse.add(a, b)
        np.testing.assert_array_equal(
            out.to_dense().numpy(),
            a.to_dense().numpy() + b.to_dense().numpy())

    def test_matmul_matches_dense(self):
        a = self._coo()
        y = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        out = sparse.matmul(a, paddle.to_tensor(y)).numpy()
        np.testing.assert_allclose(out, a.to_dense().numpy() @ y,
                                   rtol=1e-5)

    def test_matmul_grad_flows_to_dense(self):
        a = self._coo()
        y = paddle.to_tensor(np.ones((3, 2), np.float32))
        y.stop_gradient = False
        out = sparse.matmul(a, y).sum()
        out.backward()
        # d(sum)/dy[k, n] = sum of column k of the sparse matrix
        col_sums = a.to_dense().numpy().sum(axis=0)
        np.testing.assert_allclose(y.grad.numpy(),
                                   np.stack([col_sums] * 2, 1), rtol=1e-5)

    def test_csr_roundtrip(self):
        csr = self._coo().to_sparse_csr()
        np.testing.assert_array_equal(np.asarray(csr.crows), [0, 1, 2, 3])
        back = csr.to_sparse_coo()
        np.testing.assert_array_equal(back.to_dense().numpy(),
                                      self._coo().to_dense().numpy())

    def test_masked_matmul(self):
        rng = np.random.RandomState(0)
        x = rng.randn(3, 4).astype(np.float32)
        y = rng.randn(4, 3).astype(np.float32)
        mask = self._coo()
        out = sparse.masked_matmul(paddle.to_tensor(x), paddle.to_tensor(y),
                                   mask)
        full = x @ y
        for k in range(mask.nnz()):
            i, j = int(mask.indices[0][k]), int(mask.indices[1][k])
            np.testing.assert_allclose(float(out.values[k]), full[i, j],
                                       rtol=1e-5)

    def test_relu(self):
        t = sparse.sparse_coo_tensor([[0, 1], [0, 1]], [-1.0, 2.0], [2, 2])
        np.testing.assert_array_equal(
            sparse.relu(t).to_dense().numpy(), [[0, 0], [0, 2]])


class TestQuantization:
    def test_quantize_dequantize_roundtrip(self):
        x = paddle.to_tensor(np.linspace(-1, 1, 11).astype(np.float32))
        scale = abs_max_scale(x)
        q = quantize_linear(x, scale)
        assert str(q.dtype) == "int8"
        back = dequantize_linear(q, scale).numpy()
        np.testing.assert_allclose(back, x.numpy(), atol=float(scale))

    def test_fake_quant_straight_through_grad(self):
        fq = FakeQuanterWithAbsMax()
        fq.train()
        x = paddle.to_tensor(np.array([0.5, -0.3], np.float32))
        x.stop_gradient = False
        out = fq(x).sum()
        out.backward()
        np.testing.assert_allclose(x.grad.numpy(), [1.0, 1.0])

    def test_qat_converts_linears(self):
        net = paddle.nn.Sequential(paddle.nn.Linear(4, 8),
                                   paddle.nn.ReLU(),
                                   paddle.nn.Linear(8, 2))
        cfg = QuantConfig(activation=FakeQuanterWithAbsMax,
                          weight=FakeQuanterWithAbsMax)
        qnet = QAT(cfg).quantize(net)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 4).astype(np.float32))
        out = qnet(x)
        assert out.shape == [2, 2]
        # original float net untouched (inplace=False)
        assert isinstance(net[0], paddle.nn.Linear)

    def test_weight_only_linear_close_to_float(self):
        lin = paddle.nn.Linear(16, 8)
        wo = WeightOnlyLinear(lin)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 16).astype(np.float32))
        ref = lin(x).numpy()
        got = wo(x).numpy()
        assert np.abs(got - ref).max() < 0.05
        qw, scales = weight_quantize(lin.weight)
        assert str(qw.dtype) == "int8"
        assert scales.shape == [8]
