"""Tier-1 gate (ISSUE 4 satellite): graft_lint over paddle_tpu/,
tools/, and tests/ must report zero unsuppressed/unbaselined findings,
so any new trace-purity / lock-discipline / thread-hygiene / slow-marker
violation fails CI here. One in-process AST walk over the tree (~15 s),
shared by every test in this file via the lru_cache below.

Growing the baseline (tools/graft_lint/baseline.json) is an explicit,
reviewable act: run ``python -m tools.graft_lint --write-baseline`` and
justify the new entries in the PR. Prefer fixing, or an inline
``# graft-lint: disable=RULE -- reason``."""
import functools
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.graft_lint import Baseline, lint_paths  # noqa: E402
from tools.graft_lint.cli import DEFAULT_BASELINE  # noqa: E402

PATHS = [os.path.join(REPO, "paddle_tpu"), os.path.join(REPO, "tools"),
         os.path.join(REPO, "tests")]


@functools.lru_cache(maxsize=1)
def _result():
    baseline = Baseline.load(DEFAULT_BASELINE) \
        if os.path.exists(DEFAULT_BASELINE) else None
    return lint_paths(PATHS, baseline=baseline)


def test_all_passes_registered():
    passes = set(_result().passes)
    assert {"trace-purity", "lock-discipline", "thread-hygiene",
            "slow-marker", "device-placement", "recompile-hazard",
            "wait-discipline", "resource-lifecycle",
            "kernel-hygiene", "sharding-discipline"} <= passes


def test_wave2_rules_are_in_the_gate():
    """The device-placement (GL5xx) and recompile-hazard (GL6xx) rule
    families must be live in this gate — zero unbaselined findings for
    them is an acceptance criterion, not an accident of the pass not
    running."""
    from tools.graft_lint.core import all_rules
    rules = all_rules()
    assert {"GL501", "GL502", "GL503", "GL504", "GL505",
            "GL601", "GL602", "GL603", "GL604"} <= set(rules)
    res = _result()
    gl5_gl6 = [f for f in res.findings
               if f.rule.startswith(("GL5", "GL6"))]
    assert gl5_gl6 == [], "\n".join(f.render() for f in gl5_gl6)


def _repro_commands(findings):
    """The exact --select invocations that reproduce these findings one
    rule family at a time — printed on failure so the fix loop is
    copy-paste, not archaeology."""
    # family id = rule id minus its two-digit suffix: GL503 -> GL5,
    # GL1004 -> GL10 (slicing a fixed [:3] would alias GL10xx onto GL1)
    families = sorted({f.rule[:-2] for f in findings})
    return "\n".join(
        f"    python -m tools.graft_lint paddle_tpu tools tests "
        f"--select {fam}" for fam in families)


def _render_failure(findings):
    return "\n" + "\n".join(f.render() for f in findings) + (
        "\n^ new graft_lint finding(s): fix them, suppress inline with "
        "a reason, or (last resort) extend tools/graft_lint/baseline.json"
        " via --write-baseline\nreproduce one family locally with:\n"
        + _repro_commands(findings))


def test_wave3_rules_are_in_the_gate():
    """The wait-discipline (GL7xx) and resource-lifecycle (GL8xx)
    families must be live in this gate: zero unbaselined findings over
    paddle_tpu + tools is an ISSUE 13 acceptance criterion, not an
    accident of the passes not running. (Both passes skip test files
    by design — tests park on events deliberately.)"""
    from tools.graft_lint.core import all_rules
    rules = all_rules()
    assert {"GL701", "GL702", "GL703", "GL704", "GL705", "GL706",
            "GL801", "GL802", "GL803", "GL804"} <= set(rules)
    res = _result()
    gl7_gl8 = [f for f in res.findings
               if f.rule.startswith(("GL7", "GL8"))]
    assert gl7_gl8 == [], _render_failure(gl7_gl8)


def test_wave4_rules_are_in_the_gate():
    """The kernel-hygiene (GL9xx) family must be live in this gate:
    zero unbaselined findings over the Pallas kernels is an ISSUE 16
    acceptance criterion — tiling legality (the r01 rank-1 failure
    class), grid coverage, padded-tail masks, fp32 accumulation, VMEM
    budget, and interpret-mode drift are pinned here, before a TPU run
    can trip them."""
    from tools.graft_lint.core import all_rules
    rules = all_rules()
    assert {"GL901", "GL902", "GL903", "GL904", "GL905",
            "GL906"} <= set(rules)
    res = _result()
    gl9 = [f for f in res.findings if f.rule.startswith("GL9")]
    assert gl9 == [], _render_failure(gl9)


def test_wave5_rules_are_in_the_gate():
    """The sharding-discipline (GL10xx) family must be live in this
    gate: zero unbaselined findings over the SPMD surface is an ISSUE 19
    acceptance criterion — unknown mesh axes, unscoped collectives,
    shard_map spec arity, non-bijective ppermute rings, rank-divergent
    collectives, the SpecLayout vocabulary, and over-long device_put
    specs are pinned here, before an 8-device run can trip them."""
    from tools.graft_lint.core import all_rules
    rules = all_rules()
    assert {"GL1001", "GL1002", "GL1003", "GL1004", "GL1005",
            "GL1006", "GL1007"} <= set(rules)
    res = _result()
    gl10 = [f for f in res.findings if f.rule.startswith("GL10")]
    assert gl10 == [], _render_failure(gl10)


def test_framework_and_tools_are_lint_clean():
    res = _result()
    assert res.errors == [], res.errors
    assert res.findings == [], _render_failure(res.findings)


def test_every_suppression_carries_a_reason():
    # reason-less suppressions surface as GL002 findings, which the
    # zero-findings assertion above would catch — this documents the
    # contract explicitly and keeps it even if GL002 is ever baselined
    res = _result()
    assert all(f.rule != "GL002" for f in res.findings + res.baselined)


def test_baseline_entries_are_not_stale():
    """Every baseline entry must still match a real finding — fixed
    findings must leave the baseline, or it quietly absorbs future
    regressions of the same fingerprint."""
    if not os.path.exists(DEFAULT_BASELINE):
        return
    res = _result()
    baseline = Baseline.load(DEFAULT_BASELINE)
    total_entries = sum(baseline._counts.values())
    assert len(res.baselined) == total_entries, (
        f"baseline holds {total_entries} entries but only "
        f"{len(res.baselined)} matched a live finding — drop the stale "
        "entries with:\n    python -m tools.graft_lint --prune-baseline")
