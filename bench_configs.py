"""Reduced-scale harnesses for BASELINE.md configs 2-5 (VERDICT r2 weak #2:
bench.py covered only config 1). One JSON line with a per-config entry.

Single-chip honesty: the environment exposes ONE v5e via a flaky tunnel, so
each config is measured at a scale that fits it while exercising the same
code path the full-scale config uses:

- llama_tp (config 2, Llama-2 7B TP >=45% MFU on a v5p-64 slice): a
  ~0.7 B-param llama with the same per-chip arithmetic (bf16 matmuls,
  flash attention at seq 2048, fused norms) — per-chip MFU is the quantity
  TP preserves when the collectives ride ICI; the TP collectives themselves
  are validated in the multichip dryrun.
- llama_zero3 (config 3, 13B semi-auto + stage-3): the same train step
  jitted through the sharding stage-3 (FSDP) parameter layout; loss parity
  vs config-2 strategy is asserted in the dryrun, here we record that the
  sharded-layout program compiles and its single-chip throughput.
- bert_1f1b (config 4, ERNIE/BERT 1F1B): host-driven 1F1B on stage
  sub-meshes; on serial hardware the pipeline cannot beat the unpipelined
  step, so the honest measurable is scheduler overhead = T_1f1b /
  T_unpipelined (1.0 = free schedule), reported next to the theoretical
  bubble fraction (pp-1)/(acc+pp-1) the schedule is designed to hit on
  parallel stages.
- resnet50 (config 5, conv/batch_norm -> XLA fusion path): images/sec on
  a reduced batch, loss must drop.

Run directly or let tools/tpu_watch.py capture it when the tunnel is up.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def _mfu_llama(cfg, seq, tokens_per_sec, peak):
    H, L, I, V = (cfg.hidden_size, cfg.num_layers, cfg.intermediate_size,
                  cfg.vocab_size)
    kv = cfg.num_kv_heads / cfg.num_heads
    matmul_params = L * ((2 + 2 * kv) * H * H + 3 * H * I) + V * H
    flops_per_tok = 6 * matmul_params + 3 * L * seq * H
    return tokens_per_sec * flops_per_tok / peak


def _measure_steps(step, params, opt_state, key, xs, ys, lr, iters,
                   windows, scan_k):
    """Warmup + best-of-windows timing for a train step, in both shapes:
    ``scan_k=True`` — ``step`` is a scan-of-iters program, one execute
    per window (xs/ys carry the stacked [iters, ...] batches);
    ``scan_k=False`` — a single-step program looped ``iters`` times.
    Every window is closed by a device_get that data-depends on the
    window's full chain. Returns (best_window_s, loss0, loss_end)."""
    import jax

    def once(k):
        nonlocal params, opt_state
        if scan_k:
            losses, params, opt_state = step(params, opt_state, k, xs, ys,
                                             lr)
            return float(jax.device_get(losses)[0]), \
                float(jax.device_get(losses)[-1])
        first = loss = None
        for i in range(iters):
            loss, params, opt_state = step(
                params, opt_state, jax.random.fold_in(k, i), xs, ys, lr)
            if first is None:
                first = loss
        return (float(jax.device_get(first)),
                float(jax.device_get(loss)))

    loss0, _ = once(key)
    best, loss_end = float("inf"), loss0
    for w in range(windows):
        t0 = time.perf_counter()
        _, loss_end = once(jax.random.fold_in(key, 1000 + w))
        best = min(best, time.perf_counter() - t0)
    return best, loss0, loss_end


def bench_llama(dev, on_tpu, zero3=False):
    import dataclasses
    import gc

    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from bench import peak_flops_per_chip
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   create_sharded_train_step,
                                   create_train_step, llama_fsdp_spec,
                                   write_back)

    if on_tpu:
        # lm_ce="blockwise": the full-logits CE block pushed the 0.7B
        # config past v5e HBM even with donated buffers (runtime
        # ResourceExhausted, r3) — the streamed LM-head+CE caps it
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=5504, num_layers=12,
                          num_heads=16, num_kv_heads=16,
                          max_position_embeddings=2048, dropout=0.0,
                          lm_ce="blockwise")
        seq, iters, windows = 2048, 10, 2
        # (batch, remat, bf16_moments): b4/f32 is the known-fitting r3
        # config and is measured FIRST (a later candidate's OOM can then
        # only lose itself); bf16 moment storage frees ~2.75 GB of the
        # 5.5 GB AdamW state at 0.7B — on the ~7.5 GB grant that is what
        # lets b8/b16 fit. An OOM is recorded, never fatal.
        cands = ((4, False, False), (8, False, True),
                 (16, False, True)) if not zero3 \
            else ((4, False, False), (8, False, True))
    else:
        cfg = LlamaConfig(vocab_size=256, hidden_size=64,
                          intermediate_size=128, num_layers=2, num_heads=4,
                          num_kv_heads=4, max_position_embeddings=128)
        seq, iters, windows = 64, 3, 2
        cands = ((2, False, False),)

    def run_candidate(batch, remat, bf16_moments=False):
        # HBM budget at 0.7B on one v5e (15.75 GB): f32 init params
        # 2.8 GB + f32 AdamW moments 5.5 GB must never coexist with
        # protective donate copies (r3: setup peak 16.5 GB ->
        # ResourceExhausted). donate="consume" skips the copies (the
        # stateful model is invalidated by the first step), and writing
        # the bf16 cast back frees the f32 originals pre-step.
        paddle.seed(0)
        ccfg = dataclasses.replace(cfg, use_recompute=remat,
                                   recompute_policy="dots_saveable")
        model = LlamaForCausalLM(ccfg)
        model.train() if remat else model.eval()
        opt = paddle.optimizer.AdamW(
            3e-4, parameters=model.parameters(),
            moment_dtype=jnp.bfloat16 if bf16_moments else None)
        scan_k = on_tpu
        if zero3:
            from jax.sharding import Mesh
            mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1),
                        ("dp", "tp"))
            named = {k: tuple(v.shape)
                     for k, v in model.named_parameters()}
            spec = lambda name: llama_fsdp_spec(  # noqa: E731
                name, named.get(name, (1,)), 1)
            step, params, opt_state, shard_batch = \
                create_sharded_train_step(
                    model, opt, mesh, spec, donate="consume",
                    steps=iters if scan_k else None)
        elif scan_k:
            # scan-of-iters: one execute per timed window, so the
            # tunnel's per-execute overhead amortizes (same trainer math
            # as the loop — tests/test_models.py pins scan == loop)
            from paddle_tpu.models import create_multistep_train_step
            step, params, opt_state = create_multistep_train_step(
                model, opt, donate="consume", steps=iters)
            shard_batch = lambda a: jnp.asarray(a)  # noqa: E731
        else:
            step, params, opt_state = create_train_step(
                model, opt, donate="consume")
            shard_batch = lambda a: jnp.asarray(a)  # noqa: E731

        params = {k: (v.astype(jnp.bfloat16)
                      if jnp.issubdtype(v.dtype, jnp.floating) else v)
                  for k, v in params.items()}
        write_back(model, params)  # drop last refs to the f32 originals
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (batch, seq + 1))
        x_np = ids[:, :-1].astype(np.int32)
        y_np = ids[:, 1:].astype(np.int32)
        if scan_k:
            # tile BEFORE sharding: with steps=K, shard_batch places the
            # per-step batch (dim 1) over the data axis
            x_np = np.tile(x_np[None], (iters, 1, 1))
            y_np = np.tile(y_np[None], (iters, 1, 1))
        x, y = shard_batch(x_np), shard_batch(y_np)
        key = jax.random.key(0)

        best, loss0, loss_end = _measure_steps(
            step, params, opt_state, key, x, y, 3e-4, iters, windows,
            scan_k)
        tps = batch * seq * iters / best
        n_params = sum(int(np.prod(v.shape)) for v in params.values())
        return {"tokens_per_sec": round(tps, 1),
                "mfu": round(_mfu_llama(cfg, seq, tps,
                                        peak_flops_per_chip(dev)), 4),
                "params": n_params, "batch": batch, "seq": seq,
                "remat": remat,
                "moments": "bf16" if bf16_moments else "f32",
                "timing": f"scan{iters}" if scan_k else f"loop{iters}",
                "loss_start": round(loss0, 4),
                "loss_end": round(loss_end, 4),
                "loss_finite_and_moving": bool(
                    np.isfinite(loss_end) and loss_end != loss0)}

    result, sweep = None, {}
    for batch, remat, bf16_mom in cands:
        tag = (f"b{batch}{'+remat_dots' if remat else ''}"
               f"{'+m_bf16' if bf16_mom else ''}")
        r = None
        try:
            r = run_candidate(batch, remat, bf16_mom)
        except Exception as e:  # noqa: BLE001 — e.g. RESOURCE_EXHAUSTED
            sweep[tag] = f"{type(e).__name__}: {e}"[:120]
        if r is not None:
            sweep[tag] = r["tokens_per_sec"]
            if result is None \
                    or r["tokens_per_sec"] > result["tokens_per_sec"]:
                result = r
        # free this candidate's buffers before the next one builds:
        # OUTSIDE the except block, where the exception's traceback no
        # longer pins the failed candidate's frame (and its ~8 GB of
        # device buffers) against collection
        gc.collect()
    if result is None:
        raise RuntimeError(f"every llama candidate failed: {sweep}")
    result["batch_sweep"] = sweep
    return result


def bench_bert_1f1b(on_tpu):
    import paddle_tpu as paddle
    from paddle_tpu.distributed.fleet.meta_parallel import PipelineParallel
    from paddle_tpu.models import BertConfig, bert_pipeline_model

    pp, acc = 4, 8
    cfg = BertConfig(vocab_size=8192, hidden_size=256, num_layers=8,
                     num_heads=8, intermediate_size=1024,
                     max_position_embeddings=256, dropout=0.0)
    paddle.seed(0)
    pipe = bert_pipeline_model(cfg, num_stages=pp)

    class _S:
        pipeline_configs = {"accumulate_steps": acc, "micro_batch_size": 1}

    engine = PipelineParallel(pipe, None, _S())
    engine.train()
    opt = paddle.optimizer.AdamW(1e-4, parameters=pipe.parameters())
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (acc, 128))
                           .astype(np.int64))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (acc, 128))
                              .astype(np.int64))

    # unpipelined cost baseline: the SAME model as a single-stage pipeline
    # ENGINE with the same microbatching — both sides run jitted per-chunk
    # programs, so the ratio isolates the multi-stage schedule + p2p hops
    # (an eager baseline would measure eager-vs-jit instead)
    paddle.seed(0)
    pipe1 = bert_pipeline_model(cfg, num_stages=1)
    engine1 = PipelineParallel(pipe1, None, _S())
    engine1.train()
    opt1 = paddle.optimizer.AdamW(1e-4, parameters=pipe1.parameters())

    import jax

    # r3 postmortem (VERDICT weak #6): the captured overhead of 0.02 was a
    # TIMING bug, not a schedule miracle — the pipelined lambda returned an
    # async Tensor so its window closed at enqueue time, while the
    # unpipelined side forced float() (a synchronous fetch). Both windows
    # now close with a device_get of the loss, and jit-cache growth across
    # the timed windows is recorded so an on-chip retrace leak can never
    # masquerade as schedule cost again.
    def run_batch(eng_, opt_):
        out = eng_.train_batch((ids, labels), opt_)
        return float(jax.device_get(out._data))     # closes the window

    def best_of(eng_, opt_, windows=3):
        run_batch(eng_, opt_)         # warmup: compiles every chunk program
        cache0 = {k: v._cache_size() for k, v in eng_._programs.items()}
        best, last = float("inf"), None
        n0 = eng_._program_executes
        for _ in range(windows):
            t0 = time.perf_counter()
            last = run_batch(eng_, opt_)
            best = min(best, time.perf_counter() - t0)
        retraced = sum(v._cache_size() - cache0.get(k, 0)
                       for k, v in eng_._programs.items())
        n_per_batch = (eng_._program_executes - n0) / windows
        return best, last, retraced, n_per_batch

    t_unpip, l_unpip, re_unpip, n_unpip = best_of(engine1, opt1)
    t_1f1b, loss, re_1f1b, n_1f1b = best_of(engine, opt)

    theo_bubble = (pp - 1) / (acc + pp - 1)
    overhead = t_1f1b / max(t_unpip, 1e-9)
    entry = {"pp": pp, "accumulate_steps": acc,
             "loss_1f1b": round(float(loss), 4),
             "loss_unpipelined": round(l_unpip, 4),
             "t_1f1b_s": round(t_1f1b, 3),
             "t_unpipelined_s": round(t_unpip, 3),
             # serial hardware: the schedule can only add overhead; 1.0 =
             # free. The 1F1B side dispatches ~7x more (smaller) programs
             # than the single-stage side, so on the remote tunnel the
             # per-dispatch floor inflates this — read it next to
             # bench_kernels' dispatch_floor_ms.
             "host_schedule_overhead": round(overhead, 3),
             "program_executes_per_batch": {"unpipelined": round(n_unpip),
                                            "1f1b": round(n_1f1b)},
             "theoretical_bubble_fraction": round(theo_bubble, 4),
             "retraced_programs": {"unpipelined": re_unpip,
                                   "1f1b": re_1f1b},
             "peak_stash_bound_ok": bool(all(
                 engine._peak_stash[s] <= min(pp - s, acc)
                 for s in range(pp)))}
    # per-dispatch floor correction: the 1F1B side dispatches ~7x more
    # (smaller) programs than the single-stage side, and on the remote
    # tunnel each dispatch pays a measured floor (bench_kernels
    # dispatch_floor_ms). Subtracting floor x executes from both sides
    # isolates what the schedule itself costs — reported ALONGSIDE the
    # raw ratio, never replacing it. TPU-only (a CPU run pays no tunnel
    # floor), same-device + fresh capture only (floors vary 7-50 ms
    # across tunnel sessions), and the corrected ratio obeys the same
    # impossible-ratio refusal as the raw one: a schedule cannot speed
    # up serial hardware, so an over-subtracted < 0.9 is dropped with a
    # note instead of recorded as clean.
    if on_tpu:
        try:
            import os as _osp

            import jax as _jax
            kpath = _osp.join(
                _osp.dirname(_osp.abspath(__file__)), "artifacts",
                "tpu_capture", "bench_kernels.json")
            with open(kpath) as f:
                kcap = json.load(f)
            fresh = (time.time() - float(kcap.get("captured_at_unix", 0))
                     < 86400)
            same_dev = kcap.get("device") == str(_jax.devices()[0])
            if fresh and same_dev:
                floor_s = float(kcap["dispatch_floor_ms"]) / 1e3
                c_1f1b = t_1f1b - n_1f1b * floor_s
                c_unpip = t_unpip - n_unpip * floor_s
                if c_1f1b > 0 and c_unpip > 0:
                    ratio = c_1f1b / c_unpip
                    entry["dispatch_floor_ms_used"] = round(
                        floor_s * 1e3, 3)
                    if ratio >= 0.9:
                        entry["floor_corrected_overhead"] = round(ratio, 3)
                    else:
                        entry["floor_corrected_overhead_note"] = (
                            f"dropped impossible corrected ratio "
                            f"{ratio:.3f} < 0.9 (floor over-subtraction)")
        except Exception:  # noqa: BLE001 — no capture, no correction
            pass
    if overhead < 0.9:
        # a schedule cannot speed up serial hardware: refuse to record an
        # impossible ratio as a clean result (r3's 0.02 artifact)
        entry["error"] = (
            f"impossible host_schedule_overhead {overhead:.3f} < 0.9 on "
            "serial hardware — timing or schedule bug; see "
            "retraced_programs and dispatch floor")
    return entry


def bench_resnet50(dev, on_tpu):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.models import create_train_step
    from paddle_tpu.vision.models import resnet50

    if on_tpu:
        batch, hw, iters, windows = 32, 224, 5, 2
    else:
        batch, hw, iters, windows = 2, 32, 2, 1

    paddle.seed(0)
    model = resnet50(num_classes=1000)
    model.train()
    # lr: 0.1 with momentum diverged in the 10-step window on random
    # labels (r3 capture: loss 7.61 -> 8.36), and the batch-2 CPU CI case
    # needs a gentler step than batch-32 — the signal here is "the
    # conv/bn fusion path trains", not an lr schedule
    lr = 0.02 if on_tpu else 0.001
    opt = paddle.optimizer.Momentum(lr, momentum=0.9,
                                    parameters=model.parameters())

    def loss_fn(m, images, labels):
        return F.cross_entropy(m(images), labels)

    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.randn(batch, 3, hw, hw), jnp.float32)
    labels = jnp.asarray(rng.randint(0, 1000, (batch,)), jnp.int32)
    key = jax.random.key(0)

    if on_tpu:
        # scan-of-iters execute (same trainer math as the loop; the tiled
        # batch keeps the loss trajectory comparable)
        from paddle_tpu.models import create_multistep_train_step
        step, params, opt_state = create_multistep_train_step(
            model, opt, loss_fn=loss_fn, steps=iters)
        images = jnp.tile(images[None], (iters, 1, 1, 1, 1))
        labels = jnp.tile(labels[None], (iters, 1))
    else:
        step, params, opt_state = create_train_step(model, opt,
                                                    loss_fn=loss_fn)
    best, loss0, loss_end = _measure_steps(
        step, params, opt_state, key, images, labels, lr, iters, windows,
        scan_k=on_tpu)
    return {"images_per_sec": round(batch * iters / best, 1),
            "batch": batch, "image_size": hw,
            "timing": f"scan{iters}" if on_tpu else f"loop{iters}",
            "loss_start": round(loss0, 4), "loss_end": round(loss_end, 4),
            "loss_dropping": bool(loss_end < loss0)}


def bench_serving(dev, on_tpu):
    """paddle_tpu.serving throughput: requests/sec and p50/p99 latency at
    max_batch_size 1/8/32 on the tiny llama, mixed 64-token requests from
    8 concurrent client threads. The trajectory later PRs improve: rps
    should scale with batch size until the executor saturates, with
    compile_count pinned at 1 per configuration (bucketed cache)."""
    import threading

    import paddle_tpu as paddle
    from paddle_tpu.jit import StaticFunction
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.serving import Server

    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny())
    model.eval()
    sf = StaticFunction(model)
    seq = 64
    n_requests = 256 if on_tpu else 96
    n_clients = 8
    rng = np.random.RandomState(0)
    examples = [rng.randint(0, 250, (seq,)).astype(np.int64)
                for _ in range(n_requests)]

    entry = {"seq": seq, "n_requests": n_requests,
             "n_clients": n_clients, "configs": {}}
    for mbs in (1, 8, 32):
        srv = Server(sf, max_batch_size=mbs, batch_buckets=[mbs],
                     seq_buckets=[seq], batch_timeout_ms=1.0,
                     max_queue_size=n_requests + n_clients)
        try:
            srv.warmup(examples[0])
            futs = [None] * n_requests

            def client(c):
                for i in range(c, n_requests, n_clients):
                    futs[i] = srv.submit(examples[i])

            t0 = time.perf_counter()
            threads = [threading.Thread(target=client, args=(c,),
                                        daemon=True)
                       for c in range(n_clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for f in futs:
                f.result(timeout=300)
            wall = time.perf_counter() - t0
            st = srv.stats()
            entry["configs"][f"b{mbs}"] = {
                "requests_per_sec": round(n_requests / wall, 1),
                "p50_latency_ms": round(st["latency_ms"]["p50"], 2),
                "p99_latency_ms": round(st["latency_ms"]["p99"], 2),
                "mean_batch": round(st["batch_size"]["mean"], 2),
                "batches": st["batches"],
                "compiles": st["compile_count"],
                "pad_waste": round(st["pad_waste"]["mean"], 3)}
        finally:
            srv.shutdown()
    return entry


def bench_input_pipeline(dev, on_tpu):
    """Async device feed (io.prefetch + trainer.run_steps) vs the
    synchronous loop, with a tunably slow synthetic producer. The
    producer sleeps ``delay`` per batch (calibrated to ~0.8x the measured
    step time — the regime where input prep and compute SHOULD fully
    overlap); the sync loop pays producer + step + blocking loss read
    serially, the async side hides the producer behind device compute
    and fetches losses one step behind. Scored quantity:
    ``recovered_frac`` = (t_sync - t_async) / (N * delay) — the fraction
    of injected producer latency the pipeline hides (>= 0.7 is the
    acceptance bar; > 1.0 is possible because the lagged loss fetch also
    hides the blocking read-back the sync loop pays ON TOP of the
    producer delay). ``pipeline`` carries the
    ``profiler.pipeline_stats()`` split for the async run: host-blocked
    vs device-blocked seconds is the input-bound/compute-bound answer."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import profiler
    from paddle_tpu.io import prefetch_to_device
    from paddle_tpu.models import (GPTForCausalLM, create_train_step,
                                   gpt2_tiny, run_steps)

    paddle.seed(0)
    cfg = gpt2_tiny()
    batch, seq, n_steps = (16, 128, 32) if on_tpu else (8, 64, 24)
    model = GPTForCausalLM(cfg)
    model.eval()
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
    # no donation: the initial trees stay valid, so the sync and async
    # runs start from identical params and must produce identical losses
    step, params, opt_state = create_train_step(model, opt)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (n_steps, batch, seq + 1))
    xs = ids[:, :, :-1].astype(np.int32)
    ys = ids[:, :, 1:].astype(np.int32)
    key = jax.random.key(0)
    lr = 1e-3

    def producer(delay):
        for i in range(n_steps):
            time.sleep(delay)   # synthetic decode/augment/IO latency
            yield xs[i], ys[i]

    # warmup (compile), then calibrate the synchronous per-step time
    loss, _, _ = step(params, opt_state, key, xs[0], ys[0], lr)
    float(jax.device_get(loss))
    t0 = time.perf_counter()
    p, s = params, opt_state
    for i in range(4):
        loss, p, s = step(p, s, jax.random.fold_in(key, 100 + i),
                          xs[i % n_steps], ys[i % n_steps], lr)
        # graft-lint: disable=GL504 -- calibration: the per-step sync is
        # the synchronous-step time being measured
        float(jax.device_get(loss))
    t_step = (time.perf_counter() - t0) / 4
    delay = max(0.002, 0.8 * t_step)

    # synchronous baseline: producer latency + step + blocking loss read,
    # paid serially every step
    sync_losses = []
    p, s = params, opt_state
    t0 = time.perf_counter()
    for i, (x, y) in enumerate(producer(delay)):
        loss, p, s = step(p, s, jax.random.fold_in(key, i), x, y, lr)
        # graft-lint: disable=GL504 -- this loop IS the synchronous
        # baseline the pipelined loop is measured against
        sync_losses.append(float(jax.device_get(loss)))
    t_sync = time.perf_counter() - t0

    # async pipeline: background prefetch-to-device + lagged metric fetch
    feed = prefetch_to_device(producer(delay), depth=2,
                              name="input_pipeline")
    t0 = time.perf_counter()
    _, _, async_losses = run_steps(step, params, opt_state, feed,
                                   key=key, lr=lr)
    t_async = time.perf_counter() - t0
    stats = profiler.pipeline_stats("input_pipeline")
    feed.close()

    recovered = (t_sync - t_async) / (n_steps * delay)
    return {"steps": n_steps, "batch": batch, "seq": seq,
            "t_step_ms": round(t_step * 1e3, 2),
            "injected_delay_ms": round(delay * 1e3, 2),
            "t_sync_s": round(t_sync, 3), "t_async_s": round(t_async, 3),
            "recovered_frac": round(recovered, 3),
            "recovered_ok": bool(recovered >= 0.7),
            "losses_match": bool(np.allclose(
                sync_losses, [float(l) for l in async_losses],
                rtol=1e-6)),
            "pipeline": {
                "bound": stats["bound"],
                "host_blocked_s": stats["host_blocked_s"],
                "device_blocked_s": stats["device_blocked_s"],
                "producer_blocked_s": stats["producer_blocked_s"],
                "transfer_ms_p50": stats["transfer_ms"]["p50"],
                "queue_depth_mean": round(
                    stats["queue_depth"]["mean"], 2)}}


def bench_continuous_batching(dev, on_tpu):
    """Continuous batching (serving.decode.DecodeServer, paged KV cache)
    vs the static-batch Server on mixed-length autoregressive traffic.

    The baseline is what generation through the batch server means
    today: every client resubmits its growing prefix once per token, so
    each token pays a full-context forward (the Server still coalesces
    concurrent clients into padded batches — it is the best static
    configuration of the existing stack). The decode engine pays one
    prefill per request plus one batched single-token step per
    generation round, attending over the paged cache. Scored quantity:
    ``tokens_per_sec_ratio`` (>= 1.3 is the acceptance bar)."""
    import threading

    import paddle_tpu as paddle
    from paddle_tpu.jit import StaticFunction
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.serving import Server, decode

    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny())
    model.eval()
    n_requests = 48 if on_tpu else 24
    max_ctx = 48
    rng = np.random.RandomState(0)
    reqs = [(rng.randint(0, 250, (int(rng.randint(4, 17)),)
                         ).astype(np.int32), int(rng.randint(4, 17)))
            for _ in range(n_requests)]
    total_new = sum(g for _, g in reqs)

    def run_clients(fn):
        errs = []

        def client(i):
            try:
                fn(*reqs[i])
            except Exception as e:  # noqa: BLE001
                errs.append(repr(e))

        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(n_requests)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise RuntimeError(f"{len(errs)} clients failed: {errs[0]}")
        return time.perf_counter() - t0

    entry = {"n_requests": n_requests, "total_new_tokens": total_new,
             "prompt_lens": "4..16", "new_tokens": "4..16"}

    # -- static-batch baseline: full-prefix recompute per token ----------
    sf = StaticFunction(model)
    with Server(sf, max_batch_size=8, batch_buckets=[8],
                seq_buckets=[16, 32, max_ctx], batch_timeout_ms=2.0,
                max_queue_size=n_requests + 8) as srv:
        # warm EVERY seq bucket the growing prefixes will hit (prompt +
        # new - 1 <= 31 → buckets 16 and 32), so the baseline pays no
        # compile inside its timed window — same footing as dsrv.warmup()
        srv.warmup(reqs[0][0])
        srv.warmup(np.zeros(17, np.int32))

        def static_gen(prompt, n_new):
            seq = list(prompt)
            for _ in range(n_new):
                logits = srv.run(np.asarray(seq, np.int32), timeout=600)
                seq.append(int(np.argmax(logits[-1])))

        wall_static = run_clients(static_gen)
        st = srv.stats()
        entry["static_batch"] = {
            "tokens_per_sec": round(total_new / wall_static, 1),
            "wall_s": round(wall_static, 3),
            "batches": st["batches"],
            "mean_batch": round(st["batch_size"]["mean"], 2),
            "compiles": st["compile_count"]}

    # -- continuous batching over the paged KV cache ---------------------
    with decode.DecodeServer(model, max_slots=8, page_len=8,
                             max_context=max_ctx,
                             prefill_buckets=[16],
                             max_queue_size=n_requests + 8) as dsrv:
        dsrv.warmup()

        def decode_gen(prompt, n_new):
            dsrv.submit(prompt, max_new_tokens=n_new).result(timeout=600)

        wall_decode = run_clients(decode_gen)
        dst = dsrv.stats()
        entry["continuous_batching"] = {
            "tokens_per_sec": round(total_new / wall_decode, 1),
            "wall_s": round(wall_decode, 3),
            "decode_steps": dst["decode_steps"],
            "mean_active_slots": round(dst["batch_size"]["mean"], 2),
            "slot_occupancy_mean": round(
                dst["slot_occupancy"]["mean"], 3),
            "page_utilization_mean": round(
                dst["page_utilization"]["mean"], 3),
            "ttft_ms_p50": round(dst["ttft_ms"]["p50"], 2),
            "compiles": dst["compile_count"]}

    ratio = wall_static / wall_decode
    entry["tokens_per_sec_ratio"] = round(ratio, 2)
    entry["speedup_ok"] = bool(ratio >= 1.3)
    return entry


def bench_tracing_overhead(dev, on_tpu):
    """The flight recorder's cost on the continuous-batching decode
    workload. The span API is compiled into the serving hot path
    unconditionally, so the number that matters is the DISABLED mode:
    a disabled ``trace_span``/``trace_event`` must be one branch + one
    null-object return. Measured three ways: (a) micro — ns per
    disabled call; (b) call rate — recorder invocations per generated
    token, counted from one traced run of the same workload; (c) the
    derived steady-state fraction (a)x(b) / per-token wall time, pinned
    under 1 % (``disabled_overhead_ok``). The enabled-mode wall ratio
    rides along as an informational number (ring pushes are real work;
    it has no bar)."""
    import threading

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.profiler import tracing
    from paddle_tpu.serving import decode

    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny())
    model.eval()
    n_requests = 48 if on_tpu else 24
    rng = np.random.RandomState(0)
    reqs = [(rng.randint(0, 250, (int(rng.randint(4, 17)),)
                         ).astype(np.int32), int(rng.randint(4, 17)))
            for _ in range(n_requests)]
    total_new = sum(g for _, g in reqs)

    def run_clients(dsrv):
        errs = []

        def client(i):
            try:
                p, g = reqs[i]
                dsrv.submit(p, max_new_tokens=g).result(timeout=600)
            except Exception as e:  # noqa: BLE001
                errs.append(repr(e))

        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(n_requests)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise RuntimeError(f"{len(errs)} clients failed: {errs[0]}")
        return time.perf_counter() - t0

    # (a) micro: the disabled record path, ns/call
    tracing.reset_tracing()
    tracing.disable_tracing()
    n_micro = 200_000
    t0 = time.perf_counter()
    for _ in range(n_micro):
        tracing.trace_span("bench::span", cat="bench")
        tracing.trace_event("bench::event", cat="bench")
    ns_per_call = (time.perf_counter() - t0) / (2 * n_micro) * 1e9

    entry = {"n_requests": n_requests, "total_new_tokens": total_new,
             "disabled_ns_per_call": round(ns_per_call, 1)}

    with decode.DecodeServer(model, max_slots=8, page_len=8,
                             max_context=48, prefill_buckets=[16],
                             max_queue_size=n_requests + 8) as dsrv:
        dsrv.warmup()
        run_clients(dsrv)                   # untimed warm pass
        wall_off = run_clients(dsrv)        # recorder compiled in, OFF
        # (b) one traced run of the same workload: events per token is
        # the recorder's call rate on this exact hot path
        tracing.enable_tracing(ring_size=1 << 16)
        wall_on = run_clients(dsrv)
        n_events = len(tracing.snapshot_events())
        tracing.reset_tracing()
        tracing.disable_tracing()

    per_token_s = wall_off / total_new
    events_per_token = n_events / total_new
    # (c) the steady-state disabled fraction: call rate x disabled cost
    frac = events_per_token * ns_per_call / (per_token_s * 1e9)
    entry.update({
        "tokens_per_sec_off": round(total_new / wall_off, 1),
        "tokens_per_sec_on": round(total_new / wall_on, 1),
        "wall_off_s": round(wall_off, 3),
        "wall_on_s": round(wall_on, 3),
        "enabled_wall_ratio": round(wall_on / wall_off, 3),
        "events_per_token": round(events_per_token, 2),
        "disabled_overhead_frac": round(frac, 6),
        "disabled_overhead_ok": bool(frac < 0.01)})
    return entry


def bench_router_failover(dev, on_tpu):
    """Multi-host serving router over 3 in-process DecodeServer
    backends: routing overhead vs a direct single server on the same
    mixed-length decode traffic, then the same traffic with one backend
    KILLED mid-run (the loss-free failover path), then BOTH phases again
    ACROSS REAL SOCKETS (``serving.transport``: RemoteBackend clients,
    BackendServer listeners, a fault proxy whose mid-stream RST is the
    kill). Scored quantities: ``routing_overhead`` (routed wall / direct
    wall on 1/3 of the traffic each — overhead should be small),
    ``kill_slowdown`` (killed wall / clean routed wall),
    ``wire_overhead`` (wire wall / in-process routed wall — the cost of
    pickling frames through localhost TCP), ``wire_kill_slowdown``, and
    ``parity_ok`` (every phase's greedy outputs bitwise-identical)."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed.resilience.faults import \
        get_fault_injector
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.serving import decode
    from paddle_tpu.serving.router import InProcessBackend, Router
    from paddle_tpu.serving.transport import (BackendServer, FaultProxy,
                                              RemoteBackend)

    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny())
    model.eval()
    n_requests = 36 if on_tpu else 18
    rng = np.random.RandomState(0)
    reqs = [(rng.randint(0, 250, (int(rng.randint(4, 13)),)
                         ).astype(np.int32), int(rng.randint(6, 13)))
            for _ in range(n_requests)]
    total_new = sum(g for _, g in reqs)

    def srv(name):
        return decode.DecodeServer(model, max_slots=8, page_len=8,
                                   max_context=32, prefill_buckets=[16],
                                   max_queue_size=n_requests + 8,
                                   name=name)

    def run_all(submit, kill_after_tokens=None, victim_of=None,
                arm=None):
        streams = [submit(p, g) for p, g in reqs]
        if kill_after_tokens is not None:
            while streams[0].token_count() < kill_after_tokens:
                time.sleep(0.001)
            (arm or get_fault_injector().arm_backend_kill)(victim_of())
        return [[int(t) for t in s.result(timeout=600)]
                for s in streams]

    entry = {"n_requests": n_requests, "total_new_tokens": total_new}

    # -- direct single server (no router) --------------------------------
    with srv("rb_direct") as d:
        d.warmup()
        t0 = time.perf_counter()
        ref = run_all(lambda p, g: d.submit(p, max_new_tokens=g))
        wall_direct = time.perf_counter() - t0
    entry["direct"] = {"tokens_per_sec": round(total_new / wall_direct, 1),
                       "wall_s": round(wall_direct, 3)}

    # -- routed over 3 backends, clean then with a mid-run kill ----------
    for phase, kill in (("routed", False), ("routed_killed", True)):
        servers = [srv(f"rb_{phase}_{i}") for i in range(3)]
        for s in servers:
            s.warmup()
        backends = [InProcessBackend(f"rb_{phase}_h{i}", decode_server=s)
                    for i, s in enumerate(servers)]
        compiles0 = sum(s.stats()["compile_count"] for s in servers)
        with get_fault_injector().scoped():
            with Router(backends, default_deadline_ms=600_000,
                        num_workers=n_requests,
                        probe_interval_ms=25) as router:
                t0 = time.perf_counter()
                outs = run_all(
                    lambda p, g: router.submit_decode(
                        p, max_new_tokens=g),
                    kill_after_tokens=2 if kill else None,
                    victim_of=lambda: list(
                        router.sticky_assignment().values())[0])
                wall = time.perf_counter() - t0
                rst = router.stats()
        compiles = sum(s.stats()["compile_count"]
                       for s in servers) - compiles0
        for s in servers:
            s.close()
        entry[phase] = {
            "tokens_per_sec": round(total_new / wall, 1),
            "wall_s": round(wall, 3),
            "parity_ok": bool(outs == ref),
            "failovers": rst["failovers"],
            "decode_failovers": rst["decode_failovers"],
            "tokens_resumed": rst["tokens_resumed"],
            "retries": rst["retries"],
            "compiles_during_run": compiles,
            "latency_ms_p99": round(rst["latency_ms"]["p99"], 2)}

    # -- routed over 3 backends ACROSS REAL SOCKETS (wire transport) -----
    for phase, kill in (("routed_wire", False),
                        ("routed_wire_killed", True)):
        servers = [srv(f"rb_{phase}_{i}") for i in range(3)]
        for s in servers:
            s.warmup()
        hosts = [BackendServer(backend_id=f"rb_{phase}_h{i}",
                               decode_server=s)
                 for i, s in enumerate(servers)]
        proxies = [FaultProxy(h.address, proxy_id=f"rb_{phase}_h{i}")
                   for i, h in enumerate(hosts)]
        compiles0 = sum(s.stats()["compile_count"] for s in servers)
        inj = get_fault_injector()
        with inj.scoped():
            backends = [RemoteBackend(f"rb_{phase}_h{i}", p.address,
                                      liveness_timeout_s=0.6,
                                      keepalive_s=0.1)
                        for i, p in enumerate(proxies)]
            with Router(backends, default_deadline_ms=600_000,
                        num_workers=n_requests, probe_interval_ms=25,
                        close_backends=True) as router:
                t0 = time.perf_counter()
                outs = run_all(
                    lambda p, g: router.submit_decode(
                        p, max_new_tokens=g),
                    kill_after_tokens=2 if kill else None,
                    victim_of=lambda: list(
                        router.sticky_assignment().values())[0],
                    arm=inj.arm_socket_reset)
                wall = time.perf_counter() - t0
                rst = router.stats()
                snaps = [b.metrics.snapshot() for b in backends]
                wire_bytes = sum(s["bytes_sent"] + s["bytes_received"]
                                 for s in snaps)
        compiles = sum(s.stats()["compile_count"]
                       for s in servers) - compiles0
        for p in proxies:
            p.close()
        for h in hosts:
            h.shutdown(drain=False)
        for s in servers:
            s.close()
        entry[phase] = {
            "tokens_per_sec": round(total_new / wall, 1),
            "wall_s": round(wall, 3),
            "parity_ok": bool(outs == ref),
            "failovers": rst["failovers"],
            "decode_failovers": rst["decode_failovers"],
            "tokens_resumed": rst["tokens_resumed"],
            "retries": rst["retries"],
            "compiles_during_run": compiles,
            "wire_bytes": int(wire_bytes),
            "latency_ms_p99": round(rst["latency_ms"]["p99"], 2)}

    entry["routing_overhead"] = round(
        entry["routed"]["wall_s"] / entry["direct"]["wall_s"], 3)
    entry["kill_slowdown"] = round(
        entry["routed_killed"]["wall_s"] / entry["routed"]["wall_s"], 3)
    entry["wire_overhead"] = round(
        entry["routed_wire"]["wall_s"] / entry["routed"]["wall_s"], 3)
    entry["wire_kill_slowdown"] = round(
        entry["routed_wire_killed"]["wall_s"]
        / entry["routed_wire"]["wall_s"], 3)
    entry["parity_ok"] = bool(
        entry["routed"]["parity_ok"]
        and entry["routed_killed"]["parity_ok"]
        and entry["routed_wire"]["parity_ok"]
        and entry["routed_wire_killed"]["parity_ok"])
    return entry


CONFIG_NAMES = ("llama_tp_chip", "llama_zero3_layout", "bert_1f1b",
                "resnet50", "serving_throughput", "input_pipeline",
                "continuous_batching", "router_failover",
                "tracing_overhead")


def _run_config(name, dev, on_tpu):
    fns = {
        "llama_tp_chip": lambda: bench_llama(dev, on_tpu, zero3=False),
        "llama_zero3_layout": lambda: bench_llama(dev, on_tpu, zero3=True),
        "bert_1f1b": lambda: bench_bert_1f1b(on_tpu),
        "resnet50": lambda: bench_resnet50(dev, on_tpu),
        "serving_throughput": lambda: bench_serving(dev, on_tpu),
        "input_pipeline": lambda: bench_input_pipeline(dev, on_tpu),
        "continuous_batching":
            lambda: bench_continuous_batching(dev, on_tpu),
        "router_failover": lambda: bench_router_failover(dev, on_tpu),
        "tracing_overhead": lambda: bench_tracing_overhead(dev, on_tpu),
    }
    return fns[name]()


def _parent(dev):
    """One subprocess per config on TPU: an OOM inside one config (e.g. a
    llama batch candidate) poisons the rest of an in-process run — the
    r5 sweep failure class — so each config's fit is kept independent."""
    import os

    from bench_common import spawn_json_child
    out = {"metric": "baseline_configs_2_to_5", "platform": dev.platform,
           "device": str(dev), "configs": {}}
    here = os.path.abspath(__file__)
    deadline = time.monotonic() + 2200
    for name in CONFIG_NAMES:
        remaining = deadline - time.monotonic()
        got_any = any(isinstance(c, dict) and "error" not in c
                      for c in out["configs"].values())
        if remaining <= (60 if got_any else -120):
            out["configs"][name] = {"error": "skipped: parent time budget"}
            continue
        got, err = spawn_json_child(
            here, "PADDLE_TPU_CFGBENCH", name,
            min(900, max(180, remaining)), "config")
        if got is None:
            out["configs"][name] = {"error": err}
        elif got.get("platform") != dev.platform:
            # the tunnel dropped mid-pass and this child's jax fell back
            # to CPU: its numbers must never merge into a TPU capture
            out["configs"][name] = {
                "error": f"child measured on platform="
                         f"{got.get('platform')!r}, parent on "
                         f"{dev.platform!r} (tunnel dropped mid-pass?)"}
        else:
            out["configs"][name] = got["result"]
    errs = [n for n, c in out["configs"].items() if "error" in c]
    if errs:
        out["error"] = "configs failed: " + ", ".join(errs)
    print(json.dumps(out))


def main():
    import os

    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    want = os.environ.get("PADDLE_TPU_CFGBENCH")
    if want:
        # single-config subprocess: raw result for the parent, stamped
        # with the platform THIS process measured on (the parent refuses
        # a CPU-fallback child inside a TPU capture)
        try:
            print(json.dumps({"config": want, "platform": dev.platform,
                              "result": _run_config(want, dev, on_tpu)}))
        except Exception as e:  # noqa: BLE001
            print(json.dumps({"config": want, "platform": dev.platform,
                              "result": {
                "error": f"{type(e).__name__}: {e}"[:300]}}))
        return
    if on_tpu:
        return _parent(dev)
    out = {"metric": "baseline_configs_2_to_5", "platform": dev.platform,
           "device": str(dev), "configs": {}}
    for name in CONFIG_NAMES:
        try:
            out["configs"][name] = _run_config(name, dev, on_tpu)
        except Exception as e:  # noqa: BLE001 — report per-config, keep going
            out["configs"][name] = {"error": f"{type(e).__name__}: {e}"[:300]}
    errs = [n for n, c in out["configs"].items() if "error" in c]
    if errs:
        out["error"] = "configs failed: " + ", ".join(errs)
    print(json.dumps(out))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001
        print(json.dumps({"metric": "baseline_configs_2_to_5",
                          "error": repr(e)[:400]}))
        sys.exit(0)
