"""Model-weight download helper (parity: python/paddle/utils/download.py
get_weights_path_from_url). Zero-egress: only cache hits resolve."""
from __future__ import annotations

import os

__all__ = ["get_weights_path_from_url"]

WEIGHTS_HOME = os.path.expanduser("~/.cache/paddle_tpu/hapi/weights")


def get_weights_path_from_url(url, md5sum=None):
    """Return the local cache path for ``url`` if it exists; this
    environment has no network egress, so a cache miss raises with the
    expected path instead of downloading."""
    fname = os.path.basename(url)
    path = os.path.join(WEIGHTS_HOME, fname)
    if os.path.exists(path):
        return path
    raise RuntimeError(
        f"no network egress: place {fname} at {path} manually "
        f"(requested from {url})")
