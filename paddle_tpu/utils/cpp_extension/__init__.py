"""Custom-op extension point (parity: python/paddle/utils/cpp_extension/ —
JIT-compiling user C++ into loadable ops; reference builds against the
paddle::Tensor C API, paddle/phi/api/ext/).

TPU-native design: device code is Pallas/XLA (write a Python op and
register it with core.op_registry); the C++ extension point covers the
OTHER role the reference's custom ops play — host-side compute (custom
tokenizers, feature extractors, IO decoders) — by compiling the user's
C++ with the in-image g++ into a shared library and exposing each
``extern "C"`` function as a framework op through ``jax.pure_callback``
(the host bridge XLA provides). The ABI is documented and checked:

    extern "C" void my_op(const float* x, float* out, int64_t n);          // unary
    extern "C" void my_op2(const float* x, const float* y, float* out,
                           int64_t n);                                     // binary

Functions named ``<op>_grad`` with the matching arity+1 signature are
registered as the op's vjp (straight product with the cotangent).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import re
import subprocess
import tempfile
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["load", "CppExtension", "CUDAExtension", "setup",
           "get_build_directory"]

_SIG_RE = re.compile(
    r'extern\s+"C"\s+void\s+(\w+)\s*\(([^)]*)\)')


def get_build_directory() -> str:
    d = os.environ.get("PADDLE_EXTENSION_DIR") or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_extensions")
    os.makedirs(d, exist_ok=True)
    return d


class CppExtension:
    """Parity shim: setup(ext_modules=[CppExtension(sources=[...])])."""

    def __init__(self, sources: Sequence[str], name: Optional[str] = None,
                 extra_compile_args: Optional[List[str]] = None, **kwargs):
        self.sources = list(sources)
        self.name = name
        self.extra_compile_args = list(extra_compile_args or [])


def _discover(sources: Sequence[str]) -> Dict[str, int]:
    """{symbol: n_float_inputs} for every extern "C" fn matching the ABI."""
    out = {}
    for src in sources:
        text = open(src).read()
        for sym, params in _SIG_RE.findall(text):
            parts = [p.strip() for p in params.split(",") if p.strip()]
            n_in = sum(1 for p in parts if p.startswith("const float"))
            has_out = any(p.startswith("float") and not
                          p.startswith("const") for p in parts)
            has_n = any("int64_t" in p for p in parts)
            if has_out and has_n and n_in >= 1:
                out[sym] = n_in
    return out


def _compile(name: str, sources: Sequence[str],
             extra_cflags: Sequence[str]) -> str:
    build = get_build_directory()
    tag = hashlib.sha1("".join(open(s).read() for s in sources)
                       .encode()).hexdigest()[:12]
    so = os.path.join(build, f"{name}_{tag}.so")
    if not os.path.exists(so):
        cmd = (["g++", "-O2", "-fPIC", "-shared", "-std=c++17"]
               + list(extra_cflags) + list(sources) + ["-o", so])
        r = subprocess.run(cmd, capture_output=True, text=True)
        if r.returncode != 0:
            raise RuntimeError(
                f"cpp_extension build failed:\n{' '.join(cmd)}\n{r.stderr}")
    return so


class _LoadedExtension:
    """Module-like: one attribute per discovered op."""

    def __init__(self, name, so_path, symbols: Dict[str, int]):
        self.name = name
        self.so_path = so_path
        self._lib = ctypes.CDLL(so_path)
        fp = ctypes.POINTER(ctypes.c_float)
        self._ops = {}
        grads = {s[:-5]: n for s, n in symbols.items()
                 if s.endswith("_grad")}
        for sym, n_in in symbols.items():
            if sym.endswith("_grad"):
                continue
            cfun = self._lib[sym]
            cfun.restype = None
            cfun.argtypes = [fp] * (n_in + 1) + [ctypes.c_int64]
            gfun = None
            if sym in grads:
                gfun = self._lib[sym + "_grad"]
                gfun.restype = None
                gfun.argtypes = [fp] * (grads[sym] + 1) + [ctypes.c_int64]
            op = _make_op(sym, cfun, n_in, gfun)
            self._ops[sym] = op
            setattr(self, sym, op)

    def op_names(self):
        return sorted(self._ops)


def _call_c(cfun, arrays: List[np.ndarray]) -> np.ndarray:
    arrays = [np.ascontiguousarray(a, np.float32) for a in arrays]
    out = np.empty_like(arrays[0])
    n = out.size
    fp = ctypes.POINTER(ctypes.c_float)
    args = [a.ctypes.data_as(fp) for a in arrays] + \
        [out.ctypes.data_as(fp), ctypes.c_int64(n)]
    cfun(*args)
    return out


def _make_op(sym, cfun, n_in, gfun):
    import jax
    import jax.numpy as jnp

    from ...core.dispatch import run_op
    from ...core.op_registry import register_op

    def host(*arrays):
        return _call_c(cfun, [np.asarray(a) for a in arrays])

    def impl(*ars):
        out_sds = jax.ShapeDtypeStruct(ars[0].shape, jnp.float32)
        return jax.pure_callback(host, out_sds, *ars,
                                 vmap_method="sequential")

    if gfun is not None:
        @jax.custom_vjp
        def core(*ars):
            return impl(*ars)

        def fwd(*ars):
            return impl(*ars), ars

        def bwd(res, g):
            def ghost(*arrays):
                return _call_c(gfun, [np.asarray(a) for a in arrays])
            out_sds = jax.ShapeDtypeStruct(res[0].shape, jnp.float32)
            gx = jax.pure_callback(ghost, out_sds, *(res + (g,)),
                                   vmap_method="sequential")
            # the C grad fn returns d/d(first input); other inputs get None
            return (gx,) + (None,) * (len(res) - 1)
        core.defvjp(fwd, bwd)
        fn = core
    else:
        fn = impl

    register_op(sym, impl=fn,
                vjp="custom" if gfun is not None else "auto")

    def op(*tensors):
        return run_op(sym, fn, tensors,
                      out_stop_gradient=gfun is None)
    op.__name__ = sym
    return op


def load(name: str, sources: Sequence[str],
         extra_cflags: Optional[Sequence[str]] = None,
         extra_cuda_cflags=None, verbose: bool = False,
         functions: Optional[Dict[str, int]] = None) -> _LoadedExtension:
    """Compile + load (parity: cpp_extension.load). ``functions`` overrides
    symbol discovery: {symbol: n_float_inputs}."""
    del extra_cuda_cflags, verbose  # no CUDA on TPU
    symbols = dict(functions) if functions else _discover(sources)
    if not symbols:
        raise ValueError(
            "no extern \"C\" functions matching the ABI found; expected "
            "e.g. extern \"C\" void my_op(const float* x, float* out, "
            "int64_t n)")
    so = _compile(name, sources, extra_cflags or [])
    return _LoadedExtension(name, so, symbols)


class CUDAExtension(CppExtension):
    """(parity: paddle.utils.cpp_extension.CUDAExtension — accepted for
    API compatibility; there is no CUDA toolchain on the TPU build, so
    .cu sources are rejected and C++ sources compile as a CppExtension)."""

    def __init__(self, sources, name=None, extra_compile_args=None,
                 **kwargs):
        cu = [s for s in sources if str(s).endswith((".cu", ".cuh"))]
        if cu:
            raise RuntimeError(
                f"CUDAExtension: no CUDA toolchain in the TPU build "
                f"(rejected sources: {cu}); write TPU kernels with "
                "Pallas and host code as C++ CppExtension")
        super().__init__(sources, name=name,
                         extra_compile_args=extra_compile_args, **kwargs)


def setup(name=None, ext_modules=None, **kwargs):
    """Build extensions eagerly (parity: paddle.utils.cpp_extension.setup
    — the reference wraps setuptools.setup with its BuildExtension; here
    each extension JIT-compiles into the build directory and the result
    is importable via ``load``)."""
    exts = ext_modules if isinstance(ext_modules, (list, tuple)) \
        else [ext_modules] if ext_modules else []
    built = []
    for ext in exts:
        ext_name = getattr(ext, "name", None) or name
        built.append(load(name=ext_name, sources=ext.sources,
                          extra_cflags=getattr(ext, "extra_compile_args",
                                               None)))
    return built
