"""paddle.utils parity namespace."""
from . import cpp_extension  # noqa: F401


def try_import(name):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError as e:
        raise ImportError(
            f"{name} is required but not installed: {e}") from None


def run_check():
    """Parity: paddle.utils.run_check — one tiny device computation."""
    import jax
    import jax.numpy as jnp
    out = jnp.ones((2, 2)) @ jnp.ones((2, 2))
    jax.block_until_ready(out)
    dev = jax.devices()[0]
    print(f"PaddlePaddle(TPU) works on {dev.platform}:{dev.id}.")


def deprecated(update_to="", since="", reason="", level=0):
    """Decorator marking an API deprecated (parity:
    paddle.utils.deprecated — warns once per call site)."""
    import functools
    import warnings

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API {fn.__module__}.{fn.__name__} is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f", use {update_to} instead"
            if reason:
                msg += f". Reason: {reason}"
            if level == 0:
                warnings.warn(msg, DeprecationWarning, stacklevel=2)
            elif level >= 2:
                raise RuntimeError(msg)
            return fn(*args, **kwargs)
        return wrapper
    return deco


def require_version(min_version, max_version=None):
    """Check the installed framework version (parity:
    paddle.utils.require_version)."""
    from .. import __version__

    def parse(v):
        return tuple(int(x) for x in str(v).split(".")[:3] if x.isdigit())
    cur = parse(__version__)
    if parse(min_version) > cur:
        raise Exception(
            f"version {__version__} < required min {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"version {__version__} > allowed max {max_version}")
    return True

from . import unique_name  # noqa: E402,F401
from . import dlpack  # noqa: E402,F401
from . import download  # noqa: E402,F401
