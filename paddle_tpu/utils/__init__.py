"""paddle.utils parity namespace."""
from . import cpp_extension  # noqa: F401


def try_import(name):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError as e:
        raise ImportError(
            f"{name} is required but not installed: {e}") from None


def run_check():
    """Parity: paddle.utils.run_check — one tiny device computation."""
    import jax
    import jax.numpy as jnp
    out = jnp.ones((2, 2)) @ jnp.ones((2, 2))
    jax.block_until_ready(out)
    dev = jax.devices()[0]
    print(f"PaddlePaddle(TPU) works on {dev.platform}:{dev.id}.")
