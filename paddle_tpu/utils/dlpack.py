"""DLPack interchange (parity: python/paddle/utils/dlpack.py) — jax
arrays speak DLPack natively; Tensors wrap/unwrap around it."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Tensor -> DLPack-protocol object (parity: paddle.utils.dlpack
    .to_dlpack). Modern DLPack interchange passes the object exposing
    __dlpack__/__dlpack_device__ (the jax array itself) rather than a
    bare capsule; every current consumer (torch/numpy/jax from_dlpack)
    accepts it."""
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def from_dlpack(dlpack):
    """DLPack object (or legacy capsule) -> Tensor (parity:
    paddle.utils.dlpack.from_dlpack)."""
    if hasattr(dlpack, "__dlpack__"):
        return Tensor(jnp.from_dlpack(dlpack))
    from jax import dlpack as jax_dlpack
    return Tensor(jax_dlpack.from_dlpack(dlpack))
