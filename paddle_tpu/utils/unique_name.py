"""Unique name generator (parity: python/paddle/utils/unique_name.py —
generate/switch/guard over per-generator counters)."""
from __future__ import annotations

import contextlib

__all__ = ["generate", "switch", "guard"]


class _Generator:
    def __init__(self, prefix=""):
        self.ids = {}
        self.prefix = prefix

    def __call__(self, key):
        self.ids[key] = self.ids.get(key, 0)
        name = f"{self.prefix}{key}_{self.ids[key]}"
        self.ids[key] += 1
        return name


_generator = _Generator()


def generate(key):
    """(parity: unique_name.generate)"""
    return _generator(key)


def switch(new_generator=None):
    """Swap in a fresh (or given) generator; returns the old one."""
    global _generator
    old = _generator
    _generator = new_generator or _Generator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    """Scoped generator switch (parity: unique_name.guard). A string
    argument becomes the name prefix of a fresh generator, matching the
    reference's guard('block0/') usage."""
    if isinstance(new_generator, str):
        new_generator = _Generator(prefix=new_generator)
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
