"""Framework-level utilities: save/load, mode queries.

Parity: python/paddle/framework/io.py paddle.save/paddle.load (pickle-based
state_dict serialization) — numpy payloads so checkpoints are portable.
"""
from __future__ import annotations

import os
import pickle

import jax.numpy as jnp
import numpy as np

from .core.tensor import Tensor


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": np.asarray(obj._data),
                "stop_gradient": obj.stop_gradient, "name": obj.name}
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_saveable(v) for v in obj)
    return obj


def _from_saved(obj):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            t = Tensor(jnp.asarray(obj["data"]),
                       stop_gradient=obj.get("stop_gradient", True))
            t.name = obj.get("name", "")
            return t
        return {k: _from_saved(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_from_saved(v) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    """Save a (nested) state_dict / object (parity: paddle.save)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, **configs):
    """Load an object saved by ``save`` (parity: paddle.load)."""
    with open(path, "rb") as f:
        return _from_saved(pickle.load(f))


def in_dynamic_mode() -> bool:
    return True


def in_dynamic_or_pir_mode() -> bool:
    return True


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_custom_device(device_type: str = "tpu") -> bool:
    return device_type in ("tpu", "axon")


class iinfo:
    """Integer dtype info (parity: paddle.iinfo)."""

    def __init__(self, dtype):
        import numpy as np
        from .core.dtype import convert_dtype
        i = np.iinfo(np.dtype(convert_dtype(dtype)))
        self.min = int(i.min)
        self.max = int(i.max)
        self.bits = int(i.bits)
        self.dtype = str(i.dtype)


class finfo:
    """Floating dtype info (parity: paddle.finfo)."""

    def __init__(self, dtype):
        import numpy as np
        from .core.dtype import convert_dtype
        dt = np.dtype(convert_dtype(dtype))
        try:
            f = np.finfo(dt)
        except Exception:
            import ml_dtypes
            f = ml_dtypes.finfo(dt)
        self.min = float(f.min)
        self.max = float(f.max)
        self.eps = float(f.eps)
        self.tiny = float(f.tiny)
        self.smallest_normal = float(f.smallest_normal)
        self.resolution = float(f.resolution)
        self.bits = int(f.bits)
        self.dtype = str(f.dtype)


class CPUPlace:
    def __repr__(self):
        return "Place(cpu)"

    def __eq__(self, other):
        return isinstance(other, CPUPlace)


class CUDAPlace:
    """GPU place stub — accepted for API compatibility; tensors live where
    XLA puts them (the TPU). (parity: paddle.CUDAPlace)"""

    def __init__(self, device_id=0):
        self.device_id = device_id

    def __repr__(self):
        return f"Place(gpu:{self.device_id})"

    def __eq__(self, other):
        return isinstance(other, CUDAPlace) and \
            other.device_id == self.device_id


class CUDAPinnedPlace:
    def __repr__(self):
        return "Place(gpu_pinned)"

    def __eq__(self, other):
        return isinstance(other, CUDAPinnedPlace)


class TPUPlace:
    """The native place of this framework."""

    def __init__(self, device_id=0):
        self.device_id = device_id

    def __repr__(self):
        return f"Place(tpu:{self.device_id})"

    def __eq__(self, other):
        return isinstance(other, TPUPlace) and \
            other.device_id == self.device_id


_PRINT_OPTIONS = {"precision": 8, "threshold": 1000, "edgeitems": 3,
                  "linewidth": 80, "sci_mode": None}


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """(parity: paddle.set_printoptions — applies to Tensor repr via numpy)"""
    import numpy as np
    kw = {}
    if precision is not None:
        _PRINT_OPTIONS["precision"] = precision
        kw["precision"] = precision
    if threshold is not None:
        _PRINT_OPTIONS["threshold"] = threshold
        kw["threshold"] = threshold
    if edgeitems is not None:
        _PRINT_OPTIONS["edgeitems"] = edgeitems
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        _PRINT_OPTIONS["linewidth"] = linewidth
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        _PRINT_OPTIONS["sci_mode"] = sci_mode
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def disable_signal_handler():
    """No-op (parity: paddle.disable_signal_handler — the reference
    unhooks its C++ signal handlers; this build installs none)."""


def check_shape(shape):
    """Validate a shape argument (parity helper used by static APIs)."""
    if shape is None:
        raise ValueError("shape must not be None")
    for s in (shape if isinstance(shape, (list, tuple)) else [shape]):
        if isinstance(s, int) and s < -1:
            raise ValueError(f"invalid dim {s} in shape {shape}")
    return True


class LazyGuard:
    """Context that defers parameter initialization (parity:
    paddle.LazyGuard, python/paddle/fluid/lazy_init.py). On this substrate
    parameter arrays are cheap host-side inits, so the guard only marks
    layers constructed inside it; ``layer.to()``-time re-init is a no-op."""

    _active = False

    def __enter__(self):
        LazyGuard._active = True
        return self

    def __exit__(self, *exc):
        LazyGuard._active = False
        return False


def batch(reader, batch_size, drop_last=False):
    """Wrap a sample reader into a batch reader (parity: paddle.batch)."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched
