"""Framework-level utilities: save/load, mode queries.

Parity: python/paddle/framework/io.py paddle.save/paddle.load (pickle-based
state_dict serialization) — numpy payloads so checkpoints are portable.
"""
from __future__ import annotations

import os
import pickle

import jax.numpy as jnp
import numpy as np

from .core.tensor import Tensor


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": np.asarray(obj._data),
                "stop_gradient": obj.stop_gradient, "name": obj.name}
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_saveable(v) for v in obj)
    return obj


def _from_saved(obj):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            t = Tensor(jnp.asarray(obj["data"]),
                       stop_gradient=obj.get("stop_gradient", True))
            t.name = obj.get("name", "")
            return t
        return {k: _from_saved(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_from_saved(v) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    """Save a (nested) state_dict / object (parity: paddle.save)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, **configs):
    """Load an object saved by ``save`` (parity: paddle.load)."""
    with open(path, "rb") as f:
        return _from_saved(pickle.load(f))


def in_dynamic_mode() -> bool:
    return True


def in_dynamic_or_pir_mode() -> bool:
    return True


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_custom_device(device_type: str = "tpu") -> bool:
    return device_type in ("tpu", "axon")
