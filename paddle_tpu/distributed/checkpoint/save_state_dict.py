"""Distributed checkpoint save.

Parity: reference ``python/paddle/distributed/checkpoint/save_state_dict.py``
(``save_state_dict`` at :104): every process writes its local shards to its
own file; the coordinator merges per-process chunk tables into one global
``metadata.json``. Non-tensor leaves (step counters, LR-scheduler state) go
to a pickle sidecar written by the coordinator.

Layout of a checkpoint directory::

    <path>/
      shard_r{rank}.npz     one per process: its unique local chunks
      meta_r{rank}.json     per-process chunk table (merged then kept)
      metadata.json         global table (coordinator)
      extras.pkl            non-tensor leaves (coordinator)

Crash consistency: this module writes the files; the commit protocol
(staging dir + ``COMMITTED`` marker + atomic ``latest`` pointer) lives in
``paddle_tpu.distributed.resilience`` and reuses these writers through the
injectable ``fs`` layer, which is also how the fault-injection harness
kills a save at any write boundary.
"""
from __future__ import annotations

import json
import os
import pickle
import re
import time

import numpy as np

from ..parallel import get_rank, get_world_size
from .metadata import Metadata, TensorMetadata
from .utils import npz_key, snapshot_state_dict

_RANK_FILE_RE = re.compile(r"^(?:shard_r(\d+)\.npz|meta_r(\d+)\.json)$")


def _npz_key(name: str, offset) -> str:  # back-compat alias
    return npz_key(name, offset)


def _default_fs():
    from ..resilience.faults import get_fs
    return get_fs()


def _npz_writer(chunks):
    """Streaming npz producer for ``Fs.write_stream`` — the archive goes
    straight to the file instead of materializing shard-sized bytes."""
    return lambda f: np.savez(f, **chunks)


def resolve_participants(process_group=None, coordinator_rank: int = 0):
    """(rank, ranks, coordinator) for this process — or ``None`` when this
    process is not a participant of ``process_group``."""
    if process_group is not None:
        ranks = list(process_group.ranks)
        rank = get_rank()
        if rank not in ranks:
            return None
        coordinator = ranks[coordinator_rank]
    else:
        ranks = list(range(get_world_size()))
        rank = get_rank()
        coordinator = coordinator_rank
    return rank, ranks, coordinator


def write_rank_files(path: str, rank: int, chunks, meta: Metadata,
                     uid: int, fs=None) -> None:
    """This rank's durable writes: the shard npz, then (npz first, so a
    merged table never references bytes not yet on disk) the per-rank
    chunk table, atomically."""
    fs = fs or _default_fs()
    fs.makedirs(path)
    fs.write_stream(os.path.join(path, f"shard_r{rank}.npz"),
                    _npz_writer(chunks), label="shard")
    meta_json = meta.to_json()
    meta_json["uid"] = uid
    tmp = os.path.join(path, f".meta_r{rank}.json.tmp")
    fs.write_bytes(tmp, json.dumps(meta_json).encode(), label="meta.tmp")
    fs.replace(tmp, os.path.join(path, f"meta_r{rank}.json"), label="meta")


def gc_stale_rank_files(path: str, ranks, fs=None) -> list:
    """Remove ``shard_r*.npz``/``meta_r*.json`` left by ranks that are not
    participants of THIS save — a re-save into a fixed directory from a
    shrunk world must not let the coordinator merge (or a later load read)
    stale shards from the previous, larger world. Returns removed names."""
    fs = fs or _default_fs()
    try:
        names = os.listdir(path)
    except OSError:
        return []
    keep = {f"shard_r{r}.npz" for r in ranks} | \
           {f"meta_r{r}.json" for r in ranks}
    removed = []
    for fn in sorted(names):
        if _RANK_FILE_RE.match(fn) and fn not in keep:
            fs.remove(os.path.join(path, fn), label="gc-stale-rank")
            removed.append(fn)
    return removed


def coordinator_finalize(path: str, extras: dict, ranks, uid: int,
                         fs=None, merge_timeout_s: float = 300.0) -> None:
    """Coordinator-side tail of a save: extras sidecar, stale-rank GC,
    then the rank-table merge into ``metadata.json``."""
    fs = fs or _default_fs()
    fs.write_bytes(os.path.join(path, "extras.pkl"), pickle.dumps(extras),
                   label="extras")
    gc_stale_rank_files(path, ranks, fs=fs)
    _merge_metadata(path, ranks, uid, timeout_s=merge_timeout_s, fs=fs)


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank: int = 0, unique_id=None,
                    async_save: bool = False) -> None:
    """Save a (possibly nested, possibly sharded) state_dict to ``path``.

    Every leaf may be a Tensor/jax.Array with any NamedSharding — only the
    locally-addressable, replica-0 shards are written by this process, so
    the aggregate over processes is exactly one copy of the global data.

    ``unique_id`` distinguishes successive saves into the same directory
    (the reference's contract): when re-saving to a fixed path, pass a
    value all processes agree on (e.g. the global step) so the coordinator
    never merges a stale table from a previous save.

    ``async_save=True`` snapshots device shards to host RAM (one batched
    ``device_get``) and performs every disk write on the shared
    write-behind thread; the bare flag registers an atexit ``wait()`` so
    the bytes are durable before interpreter exit — prefer
    ``paddle_tpu.distributed.resilience.CheckpointManager``, which adds
    the crash-consistent commit protocol, rotation and error surfacing.
    """
    uid = 0 if unique_id is None else int(unique_id)
    parts = resolve_participants(process_group, coordinator_rank)
    if parts is None:
        return  # not a participant
    rank, ranks, coordinator = parts

    if async_save:
        import warnings
        warnings.warn(
            "save_state_dict(async_save=True) without a CheckpointManager "
            "still blocks on wait() at interpreter exit and has no "
            "crash-consistent commit; use "
            "paddle_tpu.distributed.resilience.CheckpointManager",
            DeprecationWarning, stacklevel=2)
        from ..resilience.async_ckpt import default_async_checkpointer
        default_async_checkpointer().save_legacy(
            state_dict, path, uid=uid, rank=rank, ranks=ranks,
            coordinator=coordinator)
        return

    chunks, meta, extras = snapshot_state_dict(state_dict,
                                               f"shard_r{rank}.npz")
    write_rank_files(path, rank, chunks, meta, uid)
    if rank == coordinator:
        coordinator_finalize(path, extras, ranks, uid)


def _merge_metadata(path: str, ranks, uid: int,
                    timeout_s: float = 300.0, fs=None) -> None:
    """Coordinator: wait for every participant's table (matching this save's
    uid — stale tables from a previous save into the same dir are ignored),
    merge, write the global table atomically.

    Waiting backs off exponentially (50 ms doubling to a 1 s cap — a
    300 s multi-host straggler window must not busy-spin the coordinator);
    on timeout a ``FAILED`` marker is written so the resilience manager's
    GC can identify and delete the partial directory."""
    deadline = time.time() + timeout_s
    delay = 0.05
    metas = {}
    while len(metas) < len(ranks):
        for r in ranks:
            if r in metas:
                continue
            p = os.path.join(path, f"meta_r{r}.json")
            if os.path.exists(p):
                try:
                    with open(p) as f:
                        d = json.load(f)
                    if d.get("uid", 0) == uid:
                        metas[r] = Metadata.from_json(d)
                except (json.JSONDecodeError, OSError):
                    pass  # still being written
        if len(metas) < len(ranks):
            if time.time() > deadline:
                _write_failed_marker(path, ranks, uid, metas, timeout_s,
                                     fs=fs)
                raise TimeoutError(
                    f"save_state_dict: only {len(metas)}/{len(ranks)} "
                    f"process metadata files (uid={uid}) appeared in "
                    f"{timeout_s}s")
            time.sleep(delay)
            delay = min(delay * 2, 1.0)

    merged = Metadata()
    for r in sorted(metas):
        m = metas[r]
        merged.flat_mapping.update(m.flat_mapping)
        for name, tm in m.state_dict_metadata.items():
            dst = merged.state_dict_metadata.setdefault(
                name, TensorMetadata(tm.global_shape, tm.dtype))
            seen = {c[0].global_offset for c in dst.chunks}
            for c in tm.chunks:
                if c[0].global_offset not in seen:
                    dst.chunks.append(c)
                    seen.add(c[0].global_offset)
    merged_json = merged.to_json()
    merged_json["uid"] = uid
    fs = fs or _default_fs()
    tmp = os.path.join(path, ".metadata.json.tmp")
    fs.write_bytes(tmp, json.dumps(merged_json).encode(),
                   label="metadata.tmp")
    fs.replace(tmp, os.path.join(path, "metadata.json"), label="metadata")


def _write_failed_marker(path, ranks, uid, metas, timeout_s, fs=None):
    """Best-effort tombstone: an unmarked partial dir is indistinguishable
    from one still being written; ``FAILED`` makes it GC-able."""
    failed = {"reason": f"merge timed out after {timeout_s}s",
              "uid": uid, "want_ranks": sorted(ranks),
              "have_ranks": sorted(metas)}
    try:
        (fs or _default_fs()).write_bytes(
            os.path.join(path, "FAILED"), json.dumps(failed).encode(),
            label="failed-marker")
    except Exception:
        pass  # the marker is advisory; the TimeoutError is the signal
