"""Distributed checkpoint save.

Parity: reference ``python/paddle/distributed/checkpoint/save_state_dict.py``
(``save_state_dict`` at :104): every process writes its local shards to its
own file; the coordinator merges per-process chunk tables into one global
``metadata.json``. Non-tensor leaves (step counters, LR-scheduler state) go
to a pickle sidecar written by the coordinator.

Layout of a checkpoint directory::

    <path>/
      shard_r{rank}.npz     one per process: its unique local chunks
      meta_r{rank}.json     per-process chunk table (merged then kept)
      metadata.json         global table (coordinator)
      extras.pkl            non-tensor leaves (coordinator)
"""
from __future__ import annotations

import json
import os
import pickle
import time

import numpy as np

from ..parallel import get_rank, get_world_size
from .metadata import (LocalTensorIndex, LocalTensorMetadata, Metadata,
                       TensorMetadata)
from .utils import array_chunks, flatten_state_dict, to_jax_array


def _npz_key(name: str, offset) -> str:
    return f"{name}|{','.join(map(str, offset))}"


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank: int = 0, unique_id=None,
                    async_save: bool = False) -> None:
    """Save a (possibly nested, possibly sharded) state_dict to ``path``.

    Every leaf may be a Tensor/jax.Array with any NamedSharding — only the
    locally-addressable, replica-0 shards are written by this process, so
    the aggregate over processes is exactly one copy of the global data.

    ``unique_id`` distinguishes successive saves into the same directory
    (the reference's contract): when re-saving to a fixed path, pass a
    value all processes agree on (e.g. the global step) so the coordinator
    never merges a stale table from a previous save.
    """
    del async_save
    uid = 0 if unique_id is None else int(unique_id)
    if process_group is not None:
        ranks = list(process_group.ranks)
        rank = get_rank()
        if rank not in ranks:
            return  # not a participant
        coordinator = ranks[coordinator_rank]
    else:
        ranks = list(range(get_world_size()))
        rank = get_rank()
        coordinator = coordinator_rank
    os.makedirs(path, exist_ok=True)

    flat, mapping = flatten_state_dict(state_dict)
    meta = Metadata(flat_mapping=mapping)
    extras = {}
    chunks_out = {}
    shard_file = f"shard_r{rank}.npz"

    for name, leaf in flat.items():
        arr = to_jax_array(leaf)
        if arr is None:
            extras[name] = leaf
            continue
        tm = TensorMetadata(tuple(arr.shape), str(np.dtype(arr.dtype)))
        for offset, data in array_chunks(arr):
            key = _npz_key(name, offset)
            chunks_out[key] = data
            tm.chunks.append((
                LocalTensorMetadata(offset, tuple(data.shape),
                                    str(data.dtype)),
                LocalTensorIndex(shard_file, key)))
        meta.state_dict_metadata[name] = tm

    np.savez(os.path.join(path, shard_file), **chunks_out)
    # npz first, then the table atomically: a merged table never references
    # bytes that are not yet on disk
    meta_json = meta.to_json()
    meta_json["uid"] = uid
    tmp = os.path.join(path, f".meta_r{rank}.json.tmp")
    with open(tmp, "w") as f:
        json.dump(meta_json, f)
    os.replace(tmp, os.path.join(path, f"meta_r{rank}.json"))

    if rank == coordinator:
        with open(os.path.join(path, "extras.pkl"), "wb") as f:
            pickle.dump(extras, f)
        _merge_metadata(path, ranks, uid)


def _merge_metadata(path: str, ranks, uid: int,
                    timeout_s: float = 300.0) -> None:
    """Coordinator: wait for every participant's table (matching this save's
    uid — stale tables from a previous save into the same dir are ignored),
    merge, write the global table."""
    deadline = time.time() + timeout_s
    metas = {}
    while len(metas) < len(ranks):
        for r in ranks:
            if r in metas:
                continue
            p = os.path.join(path, f"meta_r{r}.json")
            if os.path.exists(p):
                try:
                    with open(p) as f:
                        d = json.load(f)
                    if d.get("uid", 0) == uid:
                        metas[r] = Metadata.from_json(d)
                except (json.JSONDecodeError, OSError):
                    pass  # still being written
        if len(metas) < len(ranks):
            if time.time() > deadline:
                raise TimeoutError(
                    f"save_state_dict: only {len(metas)}/{len(ranks)} "
                    f"process metadata files (uid={uid}) appeared in "
                    f"{timeout_s}s")
            time.sleep(0.05)

    merged = Metadata()
    for r in sorted(metas):
        m = metas[r]
        merged.flat_mapping.update(m.flat_mapping)
        for name, tm in m.state_dict_metadata.items():
            dst = merged.state_dict_metadata.setdefault(
                name, TensorMetadata(tm.global_shape, tm.dtype))
            seen = {c[0].global_offset for c in dst.chunks}
            for c in tm.chunks:
                if c[0].global_offset not in seen:
                    dst.chunks.append(c)
                    seen.add(c[0].global_offset)
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(merged.to_json(), f)
