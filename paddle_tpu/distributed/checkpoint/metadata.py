"""Distributed-checkpoint metadata model.

Parity with the reference's ``python/paddle/distributed/checkpoint/metadata.py``:
the saved checkpoint is a set of per-process shard files plus one global
metadata table recording, for every (flattened) tensor name, which global
slice each stored chunk covers. Load-time resharding works purely off this
table (see ``load_state_dict.compute_overlap``).

TPU-native difference: a "chunk" is an addressable shard of a
``jax.Array`` (one device's local view under a ``NamedSharding``) rather
than a rank-local DenseTensor; dedup across replicas uses jax's
``Shard.replica_id`` instead of rank bookkeeping.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple


@dataclasses.dataclass(frozen=True)
class LocalTensorMetadata:
    """One stored chunk: where it sits in the global tensor."""
    global_offset: Tuple[int, ...]
    local_shape: Tuple[int, ...]
    dtype: str


@dataclasses.dataclass(frozen=True)
class LocalTensorIndex:
    """Where a chunk's bytes live on disk."""
    file_name: str      # npz file (relative to checkpoint dir)
    npz_key: str        # key inside the npz


@dataclasses.dataclass
class TensorMetadata:
    global_shape: Tuple[int, ...]
    dtype: str
    chunks: List[Tuple[LocalTensorMetadata, LocalTensorIndex]] = \
        dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Metadata:
    """The global checkpoint table (one per checkpoint directory)."""
    state_dict_metadata: Dict[str, TensorMetadata] = \
        dataclasses.field(default_factory=dict)
    flat_mapping: Dict[str, List[str]] = dataclasses.field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "state_dict_metadata": {
                k: {
                    "global_shape": list(v.global_shape),
                    "dtype": v.dtype,
                    "chunks": [
                        {"global_offset": list(m.global_offset),
                         "local_shape": list(m.local_shape),
                         "dtype": m.dtype,
                         "file_name": i.file_name,
                         "npz_key": i.npz_key}
                        for m, i in v.chunks
                    ],
                } for k, v in self.state_dict_metadata.items()
            },
            "flat_mapping": self.flat_mapping,
        }

    @classmethod
    def from_json(cls, d: dict) -> "Metadata":
        out = cls()
        for k, v in d.get("state_dict_metadata", {}).items():
            tm = TensorMetadata(tuple(v["global_shape"]), v["dtype"])
            for c in v["chunks"]:
                tm.chunks.append((
                    LocalTensorMetadata(tuple(c["global_offset"]),
                                        tuple(c["local_shape"]), c["dtype"]),
                    LocalTensorIndex(c["file_name"], c["npz_key"])))
            out.state_dict_metadata[k] = tm
        out.flat_mapping = dict(d.get("flat_mapping", {}))
        return out
