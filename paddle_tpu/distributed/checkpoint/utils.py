"""Checkpoint helpers: state-dict flattening and jax-array chunk extraction.

Parity: reference ``python/paddle/distributed/checkpoint/utils.py``
(``flatten_state_dict``/``unflatten_state_dict``).
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import numpy as np

from ...core.tensor import Tensor

SEP = "."


def flatten_state_dict(state_dict) -> Tuple[Dict[str, Any], Dict[str, List[str]]]:
    """Flatten nested dicts into {joined_key: leaf}. Returns (flat, mapping)
    where mapping records the original key path for unflatten."""
    flat: Dict[str, Any] = {}
    mapping: Dict[str, List[str]] = {}

    def walk(prefix: List[str], obj):
        if isinstance(obj, dict):
            for k, v in obj.items():
                walk(prefix + [str(k)], v)
        else:
            key = SEP.join(prefix)
            if key in flat:
                raise ValueError(
                    f"state_dict flattening collision on '{key}': a dotted "
                    f"key and a nested path produce the same flat name")
            flat[key] = obj
            mapping[key] = list(prefix)

    walk([], state_dict)
    return flat, mapping


def unflatten_state_dict(flat: Dict[str, Any],
                         mapping: Dict[str, List[str]]) -> dict:
    out: dict = {}
    for key, path in mapping.items():
        cur = out
        for p in path[:-1]:
            cur = cur.setdefault(p, {})
        cur[path[-1]] = flat[key]
    return out


def to_jax_array(value):
    """Unwrap a state-dict leaf to a jax.Array (or None for non-tensors)."""
    if isinstance(value, Tensor):
        return value._data
    if isinstance(value, jax.Array):
        return value
    if isinstance(value, np.ndarray):
        return value
    return None


def array_chunk_refs(arr) -> List[Tuple[Tuple[int, ...], Any]]:
    """Unique (global_offset, ref) chunks of a possibly-sharded array,
    with the device→host copy DEFERRED: each ref is either a host
    ``np.ndarray`` or a single-device ``jax.Array`` shard. Callers batch
    all refs into one ``jax.device_get`` (see ``snapshot_state_dict``)
    instead of paying one blocking D2H per shard.

    For a sharded jax.Array we keep every addressable shard once
    (replica_id == 0 dedupes replicas); on multi-host each process only
    sees — and therefore only saves — its own shards, which is exactly the
    reference's per-rank shard file layout.
    """
    if isinstance(arr, np.ndarray):
        return [((0,) * arr.ndim, arr)]
    try:
        shards = arr.addressable_shards
    except Exception:
        shards = None
    if not shards:
        return [((0,) * arr.ndim, arr)]
    out = []
    seen = set()
    for sh in shards:
        if getattr(sh, "replica_id", 0) != 0:
            continue
        idx = sh.index  # tuple of slices into the global array
        offset = tuple((s.start or 0) for s in idx)
        if offset in seen:
            continue
        seen.add(offset)
        out.append((offset, sh.data))
    if not out:  # every addressable shard is a replica (e.g. fully replicated
        # on a remote-primary host): still persist one copy
        sh = shards[0]
        offset = tuple((s.start or 0) for s in sh.index)
        out.append((offset, sh.data))
    return out


def array_chunks(arr) -> List[Tuple[Tuple[int, ...], np.ndarray]]:
    """``array_chunk_refs`` with the D2H copies materialized (one sync per
    chunk — prefer ``snapshot_state_dict``'s batched fetch on hot paths)."""
    return [(offset, np.asarray(ref)) for offset, ref in
            array_chunk_refs(arr)]


def npz_key(name: str, offset) -> str:
    """Key of one chunk inside a rank's shard npz."""
    return f"{name}|{','.join(map(str, offset))}"


def snapshot_state_dict(state_dict, shard_file: str):
    """Device→host snapshot of this process's replica-0 local shards in
    ONE batched ``jax.device_get`` — the only point a checkpoint save
    blocks on the device (the resilience AsyncCheckpointer moves every
    write after it behind a thread).

    Returns ``(chunks, meta, extras)``: ``chunks`` maps npz keys to host
    arrays (host-resident leaves are copied, so later in-place training
    mutation cannot corrupt a queued snapshot), ``meta`` is this rank's
    ``Metadata`` table referencing ``shard_file``, ``extras`` the
    non-tensor leaves.
    """
    from .metadata import (LocalTensorIndex, LocalTensorMetadata, Metadata,
                           TensorMetadata)

    flat, mapping = flatten_state_dict(state_dict)
    meta = Metadata(flat_mapping=mapping)
    extras = {}
    keys: List[str] = []
    refs: List[Any] = []
    for name, leaf in flat.items():
        arr = to_jax_array(leaf)
        if arr is None:
            extras[name] = leaf
            continue
        tm = TensorMetadata(tuple(arr.shape), str(np.dtype(arr.dtype)))
        for offset, ref in array_chunk_refs(arr):
            key = npz_key(name, offset)
            keys.append(key)
            refs.append(ref)
            tm.chunks.append((
                LocalTensorMetadata(offset, tuple(ref.shape),
                                    str(np.dtype(ref.dtype))),
                LocalTensorIndex(shard_file, key)))
        meta.state_dict_metadata[name] = tm

    host: List[Any] = [None] * len(refs)
    dev_idx = [i for i, r in enumerate(refs)
               if not isinstance(r, np.ndarray)]
    if dev_idx:
        fetched = jax.device_get([refs[i] for i in dev_idx])
        for i, a in zip(dev_idx, fetched):
            host[i] = np.asarray(a)
    for i, r in enumerate(refs):
        if host[i] is None:
            host[i] = np.array(r)  # snapshot semantics: owned copy
    return dict(zip(keys, host)), meta, dict(extras)
