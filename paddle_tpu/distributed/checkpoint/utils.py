"""Checkpoint helpers: state-dict flattening and jax-array chunk extraction.

Parity: reference ``python/paddle/distributed/checkpoint/utils.py``
(``flatten_state_dict``/``unflatten_state_dict``).
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import numpy as np

from ...core.tensor import Tensor

SEP = "."


def flatten_state_dict(state_dict) -> Tuple[Dict[str, Any], Dict[str, List[str]]]:
    """Flatten nested dicts into {joined_key: leaf}. Returns (flat, mapping)
    where mapping records the original key path for unflatten."""
    flat: Dict[str, Any] = {}
    mapping: Dict[str, List[str]] = {}

    def walk(prefix: List[str], obj):
        if isinstance(obj, dict):
            for k, v in obj.items():
                walk(prefix + [str(k)], v)
        else:
            key = SEP.join(prefix)
            if key in flat:
                raise ValueError(
                    f"state_dict flattening collision on '{key}': a dotted "
                    f"key and a nested path produce the same flat name")
            flat[key] = obj
            mapping[key] = list(prefix)

    walk([], state_dict)
    return flat, mapping


def unflatten_state_dict(flat: Dict[str, Any],
                         mapping: Dict[str, List[str]]) -> dict:
    out: dict = {}
    for key, path in mapping.items():
        cur = out
        for p in path[:-1]:
            cur = cur.setdefault(p, {})
        cur[path[-1]] = flat[key]
    return out


def to_jax_array(value):
    """Unwrap a state-dict leaf to a jax.Array (or None for non-tensors)."""
    if isinstance(value, Tensor):
        return value._data
    if isinstance(value, jax.Array):
        return value
    if isinstance(value, np.ndarray):
        return value
    return None


def array_chunks(arr) -> List[Tuple[Tuple[int, ...], np.ndarray]]:
    """Unique (global_offset, host_data) chunks of a possibly-sharded array.

    For a sharded jax.Array we save every addressable shard once
    (replica_id == 0 dedupes replicas); on multi-host each process only
    sees — and therefore only saves — its own shards, which is exactly the
    reference's per-rank shard file layout.
    """
    if isinstance(arr, np.ndarray):
        return [((0,) * arr.ndim, arr)]
    try:
        shards = arr.addressable_shards
    except Exception:
        shards = None
    if not shards:
        return [((0,) * arr.ndim, np.asarray(arr))]
    out = []
    seen = set()
    for sh in shards:
        if getattr(sh, "replica_id", 0) != 0:
            continue
        idx = sh.index  # tuple of slices into the global array
        offset = tuple((s.start or 0) for s in idx)
        if offset in seen:
            continue
        seen.add(offset)
        out.append((offset, np.asarray(sh.data)))
    if not out:  # every addressable shard is a replica (e.g. fully replicated
        # on a remote-primary host): still persist one copy
        sh = shards[0]
        offset = tuple((s.start or 0) for s in sh.index)
        out.append((offset, np.asarray(sh.data)))
    return out
