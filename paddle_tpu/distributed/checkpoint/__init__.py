"""Distributed checkpoint with reshard-on-load.

Parity: reference ``python/paddle/distributed/checkpoint/`` — per-process
shard files + global metadata, overlap-based partial reads so a checkpoint
saved under one mesh/parallelism loads under any other (SURVEY.md §5.4).
"""
from .load_state_dict import (compute_overlap, get_read_items,  # noqa: F401
                              load_state_dict)
from .metadata import (LocalTensorIndex, LocalTensorMetadata,  # noqa: F401
                       Metadata, TensorMetadata)
from .save_state_dict import save_state_dict  # noqa: F401
from .utils import flatten_state_dict, unflatten_state_dict  # noqa: F401
