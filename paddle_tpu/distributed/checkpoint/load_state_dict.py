"""Distributed checkpoint load with reshard-on-load.

Parity: reference ``python/paddle/distributed/checkpoint/load_state_dict.py``
(``load_state_dict:377``, ``compute_overlap:247``, ``get_read_items:297``):
the target state_dict may be sharded over a *different* mesh/placements than
the checkpoint was saved with; for every target shard we compute the overlap
with each stored chunk and read only the intersecting slices.

TPU-native twist: the target layout is read straight off each
``jax.Array``'s ``NamedSharding`` (addressable shards), and the resharded
result is rebuilt with ``jax.make_array_from_single_device_arrays`` so no
collective or host round-trip of non-owned data ever happens.
"""
from __future__ import annotations

import json
import os
import pickle
from typing import Dict, List, Tuple

import jax
import numpy as np

from ...core.tensor import Tensor
from .metadata import Metadata
from .utils import flatten_state_dict, to_jax_array


def compute_overlap(a_offset, a_shape, b_offset, b_shape):
    """Intersection of two boxes. Returns (offset, shape) in global coords,
    or None if disjoint. Mirrors reference compute_overlap (:247)."""
    off, shp = [], []
    for ao, al, bo, bl in zip(a_offset, a_shape, b_offset, b_shape):
        lo, hi = max(ao, bo), min(ao + al, bo + bl)
        if hi <= lo:
            return None
        off.append(lo)
        shp.append(hi - lo)
    return tuple(off), tuple(shp)


def get_read_items(meta: Metadata, name: str, target_offset, target_shape
                   ) -> List[Tuple[tuple, tuple, object, object]]:
    """All (global_offset, shape, chunk_meta, chunk_index) intersecting the
    target box. Mirrors reference get_read_items (:297)."""
    tm = meta.state_dict_metadata.get(name)
    if tm is None:
        return []
    out = []
    for cm, ci in tm.chunks:
        ov = compute_overlap(target_offset, target_shape,
                             cm.global_offset, cm.local_shape)
        if ov is not None:
            out.append((ov[0], ov[1], cm, ci))
    return out


class _ChunkReader:
    """Lazy npz access: one open NpzFile per shard file, per-key reads."""

    def __init__(self, path: str):
        self._path = path
        self._files: Dict[str, object] = {}

    def read(self, index) -> np.ndarray:
        f = self._files.get(index.file_name)
        if f is None:
            f = np.load(os.path.join(self._path, index.file_name))
            self._files[index.file_name] = f
        return f[index.npz_key]


def _assemble(reader: _ChunkReader, meta: Metadata, name: str,
              offset, shape, dtype) -> np.ndarray:
    """Fill one target box by copying every intersecting stored slice."""
    buf = np.zeros(shape, dtype=dtype)
    # boolean mask, not an overlap-volume sum: stored chunks may overlap
    # each other (replicated saves), and summing volumes would double-count
    # and mask a genuine gap elsewhere in the target box
    covered = np.zeros(shape, dtype=bool)
    for ov_off, ov_shape, cm, ci in get_read_items(meta, name, offset, shape):
        chunk = reader.read(ci)
        src = tuple(slice(o - co, o - co + l)
                    for o, l, co in zip(ov_off, ov_shape, cm.global_offset))
        dst = tuple(slice(o - to, o - to + l)
                    for o, l, to in zip(ov_off, ov_shape, offset))
        buf[dst] = chunk[src]
        covered[dst] = True
    if not covered.all():
        raise ValueError(
            f"checkpoint '{name}': stored chunks cover only "
            f"{int(covered.sum())} of {int(np.prod(shape))} elements of "
            f"target shard at {offset}")
    return buf


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank: int = 0, unique_id=None) -> None:
    """In-place load into ``state_dict`` (the reference contract): each leaf
    keeps its current sharding; data is resharded from the checkpoint
    layout to the leaf's layout via overlap reads."""
    del process_group, coordinator_rank, unique_id
    meta_path = os.path.join(path, "metadata.json")
    if not os.path.exists(meta_path):
        raise FileNotFoundError(f"no checkpoint metadata at {meta_path}")
    with open(meta_path) as f:
        meta = Metadata.from_json(json.load(f))
    extras_path = os.path.join(path, "extras.pkl")
    extras = {}
    if os.path.exists(extras_path):
        with open(extras_path, "rb") as f:
            extras = pickle.load(f)

    reader = _ChunkReader(path)
    flat, mapping = flatten_state_dict(state_dict)
    for name, leaf in flat.items():
        arr = to_jax_array(leaf)
        if arr is None:
            # non-tensor leaf of any type (step counters, lists, None
            # placeholders): restore verbatim from the extras sidecar
            if name in extras and isinstance(state_dict, dict):
                _set_nested(state_dict, mapping[name], extras[name])
            continue
        if name not in meta.state_dict_metadata:
            continue  # missing keys tolerated, reference behavior
        tm = meta.state_dict_metadata[name]
        if tuple(tm.global_shape) != tuple(arr.shape):
            raise ValueError(
                f"checkpoint '{name}': saved global shape {tm.global_shape} "
                f"!= target global shape {tuple(arr.shape)}")
        new_arr = _load_into_like(reader, meta, name, arr)
        if isinstance(leaf, Tensor):
            leaf._data = new_arr
        elif isinstance(state_dict, dict):
            _set_nested(state_dict, mapping[name], Tensor(new_arr))


def _load_into_like(reader, meta, name, arr):
    """Build a jax.Array with ``arr``'s sharding filled from the checkpoint."""
    dtype = np.dtype(arr.dtype) if not isinstance(arr, np.ndarray) \
        else arr.dtype
    if isinstance(arr, np.ndarray):
        full = _assemble(reader, meta, name, (0,) * arr.ndim, arr.shape, dtype)
        return jax.numpy.asarray(full)
    sharding = getattr(arr, "sharding", None)
    shards = getattr(arr, "addressable_shards", None)
    if sharding is None or not shards:
        full = _assemble(reader, meta, name, (0,) * arr.ndim,
                         tuple(arr.shape), dtype)
        return jax.numpy.asarray(full)
    per_device = []
    cache = {}  # replicas share the same (offset, shape): assemble once
    for sh in shards:
        idx = sh.index
        offset = tuple((s.start or 0) for s in idx)
        shape = tuple((s.stop if s.stop is not None else dim) - (s.start or 0)
                      for s, dim in zip(idx, arr.shape))
        local = cache.get((offset, shape))
        if local is None:
            local = _assemble(reader, meta, name, offset, shape, dtype)
            cache[(offset, shape)] = local
        per_device.append(jax.device_put(local, sh.device))
    return jax.make_array_from_single_device_arrays(
        tuple(arr.shape), sharding, per_device)


def _set_nested(d: dict, path_parts, value) -> None:
    cur = d
    for p in path_parts[:-1]:
        if not isinstance(cur, dict) or p not in cur:
            return
        cur = cur[p]
    if isinstance(cur, dict):
        cur[path_parts[-1]] = value
