from .mp_layers import (VocabParallelEmbedding, ColumnParallelLinear,  # noqa: F401
                        RowParallelLinear, ParallelCrossEntropy)
from . import mp_ops  # noqa: F401
from ....parallel import get_rank  # noqa: F401
from .....core.random import (RNGStatesTracker, get_rng_state_tracker,  # noqa: F401
                              model_parallel_random_seed)
