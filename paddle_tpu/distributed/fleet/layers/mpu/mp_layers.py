"""Tensor-parallel (model-parallel) layers.

Capability parity with the reference's mpu layers (reference:
python/paddle/distributed/fleet/layers/mpu/mp_layers.py —
VocabParallelEmbedding:47, ColumnParallelLinear:333, RowParallelLinear:540,
ParallelCrossEntropy:741).

TPU-native design: a TP layer is a layer whose parameter carries a
NamedSharding over the 'model' mesh axis. Forward code is the plain dense
math; GSPMD inserts the identity/all-reduce/all-gather collectives the
reference implements by hand (_c_identity = forward-identity/backward-
all-reduce falls out of differentiating a sharding constraint). The
explicit-collective variants remain available under shard_map via
distributed.communication for the comm-visible path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .....core.dispatch import run_op
from .....core.tensor import Tensor
from .....nn import functional as F
from .....nn.initializer import Constant, XavierUniform
from .....nn.layer.layers import Layer
from ....process_mesh import ProcessMesh

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy"]


def _mp_mesh():
    """The active hybrid mesh + model-axis name from fleet (topology.py)."""
    from ...fleet import fleet
    hcg = fleet.get_hybrid_communicate_group()
    if hcg is None:
        raise RuntimeError("call fleet.init(is_collective=True, strategy) "
                           "with hybrid_configs before building TP layers")
    return hcg.topology.mesh, "model"


def _shard_param(p, spec_entries):
    mesh, _ = _mp_mesh()
    jmesh = mesh.to_jax()
    p._data = jax.device_put(p._data, NamedSharding(jmesh, P(*spec_entries)))
    p.is_distributed = True
    return p


def _constraint(x: Tensor, spec_entries) -> Tensor:
    """Apply a sharding constraint (tracing) / device_put (eager)."""
    mesh, _ = _mp_mesh()
    jmesh = mesh.to_jax()
    sharding = NamedSharding(jmesh, P(*spec_entries))

    def fn(a):
        if isinstance(a, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(a, sharding)
        return jax.device_put(a, sharding)
    return run_op("sharding_constraint", fn, (x,))


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over the model axis (reference
    mp_layers.py:47: per-rank vocab range + mask + allreduce; here the
    sharded gather's psum is GSPMD-inserted)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=XavierUniform())
        _shard_param(self.weight, ("model", None))

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return _constraint(out, (None,) * (x.ndim + 1))


class ColumnParallelLinear(Layer):
    """Linear with output-dim-sharded weight (reference mp_layers.py:333).
    gather_output=True adds an all-gather on the output (a replicated
    sharding constraint)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        _shard_param(self.weight, (None, "model"))
        if has_bias:
            self.bias = self.create_parameter(
                shape=[out_features], is_bias=True)
            _shard_param(self.bias, ("model",))
        else:
            self.bias = None

    def forward(self, x):
        # identity fwd / allreduce bwd on x (reference _c_identity) is the
        # differentiated replicated->replicated constraint under GSPMD
        y = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            y = _constraint(y, (None,) * y.ndim)
        else:
            y = _constraint(y, (None,) * (y.ndim - 1) + ("model",))
        return y


class RowParallelLinear(Layer):
    """Linear with input-dim-sharded weight (reference mp_layers.py:540).
    The partial matmul output is all-reduced by constraining it replicated."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        _shard_param(self.weight, ("model", None))
        if has_bias:
            self.bias = self.create_parameter(
                shape=[out_features], is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        if not self.input_is_parallel:
            x = _constraint(x, (None,) * (x.ndim - 1) + ("model",))
        y = F.linear(x, self.weight)
        y = _constraint(y, (None,) * y.ndim)  # psum of partials
        if self.bias is not None:
            y = y + self.bias
        return y


class ParallelCrossEntropy(Layer):
    """Softmax CE over class-dim-sharded logits (reference mp_layers.py:741
    over c_softmax_with_cross_entropy). The sharded logsumexp / label gather
    reductions become GSPMD psums over the model axis."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        loss = F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
        from .....tensor.manipulation import unsqueeze
        return unsqueeze(loss, -1)
