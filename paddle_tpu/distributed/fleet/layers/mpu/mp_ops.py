"""Model-parallel comm primitives (reference: fleet/layers/mpu/mp_ops.py —
_c_identity:83, _c_concat:126, _c_split:188, _mp_allreduce:285, split:700).

Two faces, same semantics:
* GSPMD face (global arrays): each primitive is a sharding-constraint
  move whose vjp is the dual collective (identity fwd / allreduce bwd, etc.).
* shard_map face (rank-local tracers): lax collectives directly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .....core.dispatch import run_op
from .....core.tensor import Tensor

__all__ = ["_c_identity", "_c_concat", "_c_split", "_mp_allreduce",
           "_c_lookup_table", "_c_softmax_with_cross_entropy", "split"]


def _axis_of(group):
    return group.axis_name if group is not None and group.axis_name else "model"


def _mesh():
    from ...fleet import fleet
    hcg = fleet.get_hybrid_communicate_group()
    return hcg.topology.mesh.to_jax() if hcg else None


def _c_identity(tensor, group=None, skip_c_identity_dynamic=False):
    """Identity forward / all-reduce backward over the mp axis."""
    arr = tensor._data if isinstance(tensor, Tensor) else tensor
    ax = _axis_of(group)
    if isinstance(arr, jax.core.Tracer) and not hasattr(arr, "sharding"):
        # shard_map face: custom vjp
        @jax.custom_vjp
        def ident(a):
            return a

        def fwd(a):
            return a, None

        def bwd(_, g):
            return (jax.lax.psum(g, ax),)
        ident.defvjp(fwd, bwd)
        return run_op("c_identity", ident, (tensor,))
    # GSPMD face: replicated constraint (its grad is psum'd automatically)
    m = _mesh()
    if m is None:
        return tensor if isinstance(tensor, Tensor) else Tensor(tensor)

    def fn(a):
        sh = NamedSharding(m, P(*(None,) * a.ndim))
        if isinstance(a, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(a, sh)
        return jax.device_put(a, sh)
    return run_op("c_identity", fn, (tensor,))


def _mp_allreduce(tensor, op=None, group=None, use_calc_stream=True,
                  use_model_parallel=True, skip_c_identity_dynamic=False):
    """All-reduce forward / identity backward (dual of _c_identity)."""
    ax = _axis_of(group)
    arr = tensor._data if isinstance(tensor, Tensor) else tensor
    if isinstance(arr, jax.core.Tracer) and not hasattr(arr, "sharding"):
        @jax.custom_vjp
        def ar(a):
            return jax.lax.psum(a, ax)

        def fwd(a):
            return jax.lax.psum(a, ax), None

        def bwd(_, g):
            return (g,)
        ar.defvjp(fwd, bwd)
        return run_op("mp_allreduce", ar, (tensor,))
    m = _mesh()
    if m is None:
        return tensor if isinstance(tensor, Tensor) else Tensor(tensor)

    def fn(a):
        sh = NamedSharding(m, P(*(None,) * a.ndim))
        if isinstance(a, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(a, sh)
        return jax.device_put(a, sh)
    return run_op("mp_allreduce", fn, (tensor,))


def _c_concat(tensor, group=None):
    """Gather last-dim shards and concat (reference _c_concat): replicate
    the last dim via constraint."""
    m = _mesh()
    ax = _axis_of(group)
    arr = tensor._data if isinstance(tensor, Tensor) else tensor
    if isinstance(arr, jax.core.Tracer) and not hasattr(arr, "sharding"):
        def fn(a):
            g = jax.lax.all_gather(a, ax, axis=0)
            return jnp.concatenate([g[i] for i in range(g.shape[0])], axis=-1)
        return run_op("c_concat", fn, (tensor,))
    if m is None:
        return tensor if isinstance(tensor, Tensor) else Tensor(tensor)

    def fn(a):
        sh = NamedSharding(m, P(*(None,) * a.ndim))
        if isinstance(a, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(a, sh)
        return jax.device_put(a, sh)
    return run_op("c_concat", fn, (tensor,))


def _c_split(tensor, group=None):
    """Split last dim across the mp axis (reference _c_split)."""
    m = _mesh()
    arr = tensor._data if isinstance(tensor, Tensor) else tensor
    if m is None:
        return tensor if isinstance(tensor, Tensor) else Tensor(tensor)

    def fn(a):
        sh = NamedSharding(m, P(*((None,) * (a.ndim - 1) + ("model",))))
        if isinstance(a, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(a, sh)
        return jax.device_put(a, sh)
    return run_op("c_split", fn, (tensor,))


def _c_lookup_table(table, index, start_index=0, name=None):
    from .....nn import functional as F
    return F.embedding(index, table)


def _c_softmax_with_cross_entropy(logits, label, group=None,
                                  return_softmax=False):
    from .....nn import functional as F
    loss = F.softmax_with_cross_entropy(logits, label,
                                        return_softmax=return_softmax)
    return loss


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Static-graph style model-parallel split API (reference mp_ops.py:700):
    builds the corresponding parallel layer on the fly."""
    from .mp_layers import (ColumnParallelLinear, RowParallelLinear,
                            VocabParallelEmbedding)
    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1],
                                       weight_attr=weight_attr)
        return layer(x)
    if operation == "linear":
        if axis == 0:
            layer = RowParallelLinear(size[0], size[1],
                                      weight_attr=weight_attr,
                                      has_bias=bias_attr is not False)
        else:
            layer = ColumnParallelLinear(size[0], size[1],
                                         weight_attr=weight_attr,
                                         has_bias=bias_attr is not False,
                                         gather_output=gather_out)
        return layer(x)
    raise ValueError(f"unknown operation {operation}")
