"""Hybrid-parallel optimizer wrappers.

Capability parity with the reference (reference: fleet/meta_optimizers/
dygraph_optimizer/hybrid_parallel_optimizer.py — HybridParallelOptimizer
:254, HybridParallelClipGrad:44; hybrid_parallel_gradscaler.py:24;
dygraph_sharding_optimizer.py:48 DygraphShardingOptimizer).

TPU-native notes: the reference's TP-grad `_insert_sync` (broadcast of
non-distributed params over the mp group) and the cross-group partial-norm
allreduces exist because each rank owns a fragment. Under single-controller
SPMD, grads of sharded params are sharded global arrays — a global norm over
them is already the cross-rank norm (XLA inserts the psums) — so the clip
math is written once over global arrays and is exactly the reference's
semantics on a pod.
"""
from __future__ import annotations

import jax.numpy as jnp

from ....core.tensor import Tensor
from ....nn.clip import ClipGradByGlobalNorm

__all__ = ["HybridParallelOptimizer", "HybridParallelClipGrad",
           "HybridParallelGradScaler", "DygraphShardingOptimizer",
           "DygraphShardingOptimizerV2"]


class HybridParallelClipGrad(ClipGradByGlobalNorm):
    """Global-norm clip across all hybrid axes (reference :44). Sharded grad
    arrays contribute their global norm; Partial-represented grads are
    reduced first."""

    def __init__(self, clip, hcg=None):
        inner = clip if isinstance(clip, (int, float)) else clip.clip_norm
        super().__init__(inner)
        self._hcg = hcg

    def _dygraph_clip(self, params_grads):
        fixed = []
        for p, g in params_grads:
            if g is not None and isinstance(g, Tensor) and \
                    g.dist_attr is not None and g.dist_attr.partial_axes:
                from ...auto_parallel.api import unshard_dtensor
                g = unshard_dtensor(g)
            fixed.append((p, g))
        return super()._dygraph_clip(fixed)


class HybridParallelOptimizer:
    """Wraps the user optimizer for hybrid parallel (reference :254)."""

    def __init__(self, optimizer, hcg=None, strategy=None):
        self._hcg = hcg
        self._strategy = strategy
        # only global-norm clip needs the hybrid cross-axis treatment
        # (reference also swaps only ClipGradByGlobalNorm and warns
        # otherwise). Swap BEFORE any wrapping: the sharding wrapper
        # delegates reads via __getattr__ but a write would land on the
        # wrapper's __dict__ and the real optimizer would keep its plain
        # clip.
        if isinstance(optimizer._grad_clip, ClipGradByGlobalNorm) and \
                not isinstance(optimizer._grad_clip, HybridParallelClipGrad):
            optimizer._grad_clip = HybridParallelClipGrad(
                optimizer._grad_clip, hcg)
        # sharding axis active: the inner optimizer becomes the ZeRO-1
        # sharded one (reference :254 picks DygraphShardingOptimizer)
        if hcg is not None and hcg.get_sharding_parallel_world_size() > 1 \
                and not isinstance(optimizer, DygraphShardingOptimizer):
            optimizer = DygraphShardingOptimizer(optimizer, hcg)
        self._inner_opt = optimizer

    def _insert_sync(self):
        """TP-grad sync of non-distributed params (reference :333-421): a
        param replicated over the mp group can be left with a Partial or
        mp-sharded grad when activations are mp/sequence-sharded; reduce it
        to the whole value before stepping (the reference broadcasts or
        allreduces over the mp group, per sync_mode). Distributed
        (is_distributed) params own per-rank shards and are skipped."""
        from ...auto_parallel.api import reshard, unshard_dtensor
        from ...process_mesh import Replicate, Shard
        for p in (self._inner_opt._parameter_list or []):
            if getattr(p, "is_distributed", False):
                continue
            g = getattr(p, "grad", None)
            da = getattr(g, "dist_attr", None)
            if g is None or da is None:
                continue
            if da.partial_axes:
                p.grad = unshard_dtensor(g)  # p_to_r allreduce
            elif any(isinstance(pl, Shard) for pl in da.placements):
                p.grad = reshard(g, da.process_mesh,
                                 [Replicate()] * da.process_mesh.ndim)

    def step(self):
        if self._hcg is not None and \
                self._hcg.get_model_parallel_world_size() > 1:
            self._insert_sync()
        self._inner_opt.step()

    def clear_grad(self, set_to_zero=True):
        self._inner_opt.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        return self._inner_opt.minimize(loss)

    def get_lr(self):
        return self._inner_opt.get_lr()

    def set_lr(self, v):
        self._inner_opt.set_lr(v)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)

    @property
    def _learning_rate(self):
        return self._inner_opt._learning_rate

    @property
    def _parameter_list(self):
        return self._inner_opt._parameter_list

    def __getattr__(self, name):
        return getattr(self.__dict__["_inner_opt"], name)


class HybridParallelGradScaler:
    """AMP scaler with cross-group found_inf sync (reference
    hybrid_parallel_gradscaler.py:24). Single-controller: found_inf is
    computed over global grad arrays, already cross-rank."""

    def __init__(self, scaler, hcg=None):
        self._scaler = scaler
        self._hcg = hcg

    def __getattr__(self, name):
        return getattr(self.__dict__["_scaler"], name)


class DygraphShardingOptimizer:
    """ZeRO stage-1: shard optimizer states over the sharding axis
    (reference dygraph_sharding_optimizer.py:48). TPU-native: states are
    created with zeros_like(param-with-sharding); this wrapper additionally
    re-lays the states over the 'sharding' mesh axis so each rank stores
    1/N of them, and the reference's reduce_gradients + broadcast of updated
    shards becomes XLA's reduce-scatter/all-gather pair from the sharding
    annotations."""

    def __init__(self, optimizer, hcg=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._shard_states_lazily = True

    def _shard_axis(self):
        if self._hcg is None:
            return None
        return "sharding" if self._hcg.get_sharding_parallel_world_size() > 1 \
            else ("data" if self._hcg.get_data_parallel_world_size() > 1 else None)

    def step(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        axis = self._shard_axis()
        self._inner_opt.step()
        if axis is None:
            return
        mesh = self._hcg.topology.mesh.to_jax()
        if self._shard_states_lazily:
            # after the first step the states exist: lay them over the axis
            # (ZeRO-1 state partition, reference
            # dygraph_sharding_optimizer.py:48 — each rank stores 1/N)
            from paddle_tpu.distributed.spec_layout import SpecLayout
            layout = SpecLayout(fsdp_axis=axis)
            n = self._hcg.topology.get_dim(axis)
            for key, state in self._inner_opt._states.items():
                for name, arr in state.items():
                    if arr.ndim >= 1 and arr.shape[0] % n == 0:
                        state[name] = jax.device_put(
                            arr, NamedSharding(
                                mesh, layout.fsdp_rows(arr.ndim)))
            self._shard_states_lazily = False
        # post-step broadcast of updated shards (reference
        # _sharding_sync_parameters): the eager update mixes sharded states
        # into the param math, so updated params can come out sharded over
        # the sharding axis — drop ONLY that axis from the spec (XLA
        # all-gather over the sharding group) so every sharding rank holds
        # the full updated weights. TP (is_distributed) params keep their
        # per-rank shards untouched, as does any other mesh axis in the
        # spec.
        for p in (self._inner_opt._parameter_list or []):
            if getattr(p, "is_distributed", False):
                continue
            arr = p._data
            sh = getattr(arr, "sharding", None)
            spec = getattr(sh, "spec", None)
            if sh is None or spec is None or sh.is_fully_replicated:
                continue

            def _drop(entry):
                if entry == axis:
                    return None
                if isinstance(entry, tuple):
                    kept = tuple(a for a in entry if a != axis)
                    return kept if kept else None
                return entry
            new_entries = [_drop(e) for e in tuple(spec)]
            if new_entries != list(tuple(spec)):
                p._data = jax.device_put(
                    arr, NamedSharding(mesh, P(*new_entries)))

    def clear_grad(self, set_to_zero=True):
        self._inner_opt.clear_grad()

    clear_gradients = clear_grad

    def __getattr__(self, name):
        return getattr(self.__dict__["_inner_opt"], name)


class DygraphShardingOptimizerV2(DygraphShardingOptimizer):
    """V2 (comm-fused buffers, reference :470): buffer fusion is XLA's
    scheduling job on TPU; behaviorally identical here."""
