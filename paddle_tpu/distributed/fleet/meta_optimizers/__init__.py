from .hybrid_parallel_optimizer import (HybridParallelOptimizer,  # noqa: F401
                                        HybridParallelClipGrad,
                                        HybridParallelGradScaler,
                                        DygraphShardingOptimizer,
                                        DygraphShardingOptimizerV2)
