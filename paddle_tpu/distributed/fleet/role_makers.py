"""Role makers + util base + data generators (parity:
python/paddle/distributed/fleet/base/role_maker.py, util_base.py,
data_generator/).
"""
from __future__ import annotations

import os

__all__ = ["Role", "PaddleCloudRoleMaker", "UserDefinedRoleMaker",
           "UtilBase", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class Role:
    """(parity: fleet.base.role_maker.Role)"""
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class PaddleCloudRoleMaker:
    """Reads the PADDLE_TRAINER_* env contract (parity:
    fleet.PaddleCloudRoleMaker — the collective path)."""

    def __init__(self, is_collective=True, **kwargs):
        self._is_collective = is_collective
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._endpoints = eps.split(",") if eps else []

    def worker_index(self):
        return self._rank

    def worker_num(self):
        return self._size

    def is_worker(self):
        return True

    def is_server(self):
        return False

    def is_first_worker(self):
        return self._rank == 0

    def role(self):
        return Role.WORKER

    def get_trainer_endpoints(self):
        return self._endpoints


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """Explicit ranks instead of env (parity: fleet.UserDefinedRoleMaker)."""

    def __init__(self, is_collective=True, init_gloo=False, **kwargs):
        super().__init__(is_collective)
        self._rank = kwargs.get("current_id", 0)
        self._size = kwargs.get("worker_num",
                                len(kwargs.get("worker_endpoints", [])) or 1)
        self._endpoints = kwargs.get("worker_endpoints", [])
        self._role = kwargs.get("role", Role.WORKER)

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def role(self):
        return self._role


class UtilBase:
    """Cross-worker utilities (parity: fleet.UtilBase,
    fleet/base/util_factory.py) over the collective API."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        import numpy as np

        from .. import communication_impl as C
        from ...core.tensor import Tensor
        t = input if isinstance(input, Tensor) else Tensor(np.asarray(input))
        op = {"sum": C.ReduceOp.SUM, "max": C.ReduceOp.MAX,
              "min": C.ReduceOp.MIN}[mode]
        out = C.all_reduce(t, op=op)
        return np.asarray((out if out is not None else t).numpy())

    def barrier(self, comm_world="worker"):
        from .. import communication_impl as C
        C.barrier()

    def all_gather(self, input, comm_world="worker"):
        from .. import communication_impl as C
        from ...core.tensor import Tensor
        import numpy as np
        t = input if isinstance(input, Tensor) else Tensor(np.asarray(input))
        outs = []
        C.all_gather(outs, t)
        return [np.asarray(o.numpy()) for o in outs]

    def get_file_shard(self, files):
        import os as _os
        rank = int(_os.environ.get("PADDLE_TRAINER_ID", "0"))
        size = int(_os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        return files[rank::size]

    def print_on_rank(self, message, rank_id=0):
        import os as _os
        if int(_os.environ.get("PADDLE_TRAINER_ID", "0")) == rank_id:
            print(message)


class _DataGeneratorBase:
    """line -> sample generator -> batched slot output (parity:
    fleet.data_generator — feeds the PS/QueueDataset pipeline)."""

    def __init__(self):
        self.batch_size_ = 32

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    def generate_sample(self, line):
        raise NotImplementedError(
            "implement generate_sample returning an iterator of "
            "(name, value-list) tuples")

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    def run_from_stdin(self):
        import sys
        for line in sys.stdin:
            for out in self._lines_out(line):
                sys.stdout.write(out)

    def _lines_out(self, line):
        gen = self.generate_sample(line)
        for sample in gen():
            yield self._format(sample)


class MultiSlotDataGenerator(_DataGeneratorBase):
    def _format(self, sample):
        parts = []
        for name, values in sample:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(_DataGeneratorBase):
    def _format(self, sample):
        parts = []
        for name, values in sample:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"
