"""Fleet global metrics (parity: fleet/metrics/metric.py — numpy-in,
numpy-out aggregation across trainers). Aggregation rides the fleet
util's object collectives when a parallel env with >1 ranks is up;
single-process (and the single-controller global-array substrate, where
every rank computes on the global batch already) is the identity."""
from __future__ import annotations

import builtins

import numpy as np

__all__ = ["sum", "max", "min", "auc", "mae", "rmse", "mse", "acc"]


def _coerce(x):
    if hasattr(x, "numpy"):
        x = x.numpy()
    return np.asarray(x)


def _all_reduce(arr: np.ndarray, mode: str, util=None) -> np.ndarray:
    if util is not None and hasattr(util, "all_reduce"):
        return np.asarray(util.all_reduce(arr, mode)).reshape(arr.shape)
    from ... import parallel as _par
    if getattr(_par, "get_world_size", lambda: 1)() > 1:
        from ...communication_impl import all_gather_object
        try:
            parts: list = []
            all_gather_object(parts, arr)
            stack = np.stack([np.asarray(p) for p in parts])
            op = {"sum": np.sum, "max": np.amax, "min": np.amin}[mode]
            return op(stack, axis=0)
        except Exception:  # no live comm group: local value is global
            pass
    return arr


def sum(input, scope=None, util=None):
    """Distributed sum (reference metric.py:26)."""
    a = _coerce(input)
    return _all_reduce(a, "sum", util)


def max(input, scope=None, util=None):
    a = _coerce(input)
    return _all_reduce(a, "max", util)


def min(input, scope=None, util=None):
    a = _coerce(input)
    return _all_reduce(a, "min", util)


def auc(stat_pos, stat_neg, scope=None, util=None):
    """Global AUC from positive/negative prediction-bucket stats
    (reference metric.py:149: the distributed streaming-AUC buckets)."""
    pos = _all_reduce(_coerce(stat_pos).astype(np.float64), "sum", util)
    neg = _all_reduce(_coerce(stat_neg).astype(np.float64), "sum", util)
    pos, neg = pos.reshape(-1), neg.reshape(-1)
    # walk buckets from highest score down, accumulating the ROC integral
    tot_pos = tot_neg = 0.0
    area = 0.0
    for i in range(len(pos) - 1, -1, -1):
        new_pos = tot_pos + pos[i]
        new_neg = tot_neg + neg[i]
        area += neg[i] * (tot_pos + new_pos) / 2.0
        tot_pos, tot_neg = new_pos, new_neg
    if tot_pos == 0.0 or tot_neg == 0.0:
        return 0.5
    return float(area / (tot_pos * tot_neg))


def mae(abserr, total_ins_num, scope=None, util=None):
    e = float(np.sum(_all_reduce(_coerce(abserr), "sum", util)))
    n = float(np.sum(_all_reduce(_coerce(total_ins_num), "sum", util)))
    return e / builtins.max(n, 1.0)


def rmse(sqrerr, total_ins_num, scope=None, util=None):
    e = float(np.sum(_all_reduce(_coerce(sqrerr), "sum", util)))
    n = float(np.sum(_all_reduce(_coerce(total_ins_num), "sum", util)))
    return (e / builtins.max(n, 1.0)) ** 0.5


def mse(sqrerr, total_ins_num, scope=None, util=None):
    e = float(np.sum(_all_reduce(_coerce(sqrerr), "sum", util)))
    n = float(np.sum(_all_reduce(_coerce(total_ins_num), "sum", util)))
    return e / builtins.max(n, 1.0)


def acc(correct, total, scope=None, util=None):
    c = float(np.sum(_all_reduce(_coerce(correct), "sum", util)))
    t = float(np.sum(_all_reduce(_coerce(total), "sum", util)))
    return c / builtins.max(t, 1.0)
