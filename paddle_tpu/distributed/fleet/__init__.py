"""paddle_tpu.distributed.fleet (parity: python/paddle/distributed/fleet/)."""
from .fleet import (DistributedStrategy, Fleet, fleet, init,  # noqa: F401
                    distributed_model, distributed_optimizer,
                    get_hybrid_communicate_group)
from .topology import CommunicateTopology, HybridCommunicateGroup  # noqa: F401
from . import meta_parallel  # noqa: F401
from . import utils  # noqa: F401
from .meta_parallel import (PipelineLayer, LayerDesc, SharedLayerDesc,  # noqa: F401
                            get_rng_state_tracker)
from .recompute import recompute, recompute_sequential  # noqa: F401
from . import elastic  # noqa: F401
from .dataset import (DatasetBase, InMemoryDataset, QueueDataset,  # noqa: F401
                      FileInstantDataset, BoxPSDataset)
from . import metrics  # noqa: F401
from .scaler import distributed_scaler  # noqa: F401
from .. import auto_parallel as auto  # noqa: F401
from .utils import log_util  # noqa: F401
from .role_makers import (Role, PaddleCloudRoleMaker,  # noqa: E402,F401
                           UserDefinedRoleMaker, UtilBase,
                           MultiSlotDataGenerator,
                           MultiSlotStringDataGenerator)
