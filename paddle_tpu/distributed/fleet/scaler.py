"""distributed_scaler (parity: fleet/scaler.py:28): wrap a GradScaler so
found-inf detection is agreed ACROSS the hybrid-parallel group before the
skip/step decision — a rank seeing inf must make every rank skip, or the
replicas diverge.

Single-controller note: gradients here are global jax arrays, so a local
finite-check already sees every shard's values; the cross-rank max is a
semantic no-op but is still routed through the comm group when one is
alive (keeping the reference's behavior observable under tests)."""
from __future__ import annotations

import numpy as np

__all__ = ["distributed_scaler"]


def distributed_scaler(scaler):
    inner_unscale = scaler.unscale_

    def unscale_(optimizer):
        inner_unscale(optimizer)
        found = bool(getattr(scaler, "_found_inf", False))
        from .. import parallel as _par
        if getattr(_par, "get_world_size", lambda: 1)() > 1:
            from ..communication_impl import all_gather_object
            try:
                parts: list = []
                all_gather_object(parts, np.asarray(found))
                found = bool(np.any(np.stack(parts)))
            except Exception:
                pass
        scaler._found_inf = found

    scaler.unscale_ = unscale_
    return scaler
