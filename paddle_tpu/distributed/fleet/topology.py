"""Hybrid-parallel topology.

Capability parity with the reference's CommunicateTopology /
HybridCommunicateGroup (reference: python/paddle/distributed/fleet/base/
topology.py:61,174 — 5-D cartesian rank mesh [data, pipe, sharding, sep,
model], axis order pp->mp->sep->sharding->dp at topology.py:299).

TPU-native: the topology IS a jax device mesh. Each axis becomes a named
mesh dimension; "comm groups" are axis names (collectives over an axis ride
ICI); fused axes (dp+sharding, dp+sep) are tuple-of-axes specs. No NCCL
ring-id bookkeeping exists because XLA identifies groups by mesh axes.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..communication_impl import Group
from ..process_mesh import ProcessMesh

__all__ = ["CommunicateTopology", "HybridCommunicateGroup"]

# the reference's axis nesting order (outermost..innermost), topology.py:299
_HYBRID_ORDER = ["data", "pipe", "sharding", "sep", "model"]


class CommunicateTopology:
    def __init__(self, hybrid_group_names: Sequence[str] = _HYBRID_ORDER,
                 dims: Sequence[int] = (1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self._world_size = int(np.prod(dims))
        self._rank_mesh = np.arange(self._world_size).reshape(self._dims)
        self._mesh = ProcessMesh(self._rank_mesh, self._parallel_names)

    def get_hybrid_group_names(self):
        return list(self._parallel_names)

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return self._world_size

    @property
    def mesh(self) -> ProcessMesh:
        return self._mesh

    def get_rank(self, **kwargs) -> int:
        idx = tuple(kwargs[name] for name in self._parallel_names)
        return int(self._rank_mesh[idx])

    def get_coord(self, rank: int):
        loc = np.argwhere(self._rank_mesh == rank)[0]
        return dict(zip(self._parallel_names, (int(x) for x in loc)))

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        axis = self._parallel_names.index(axis_name)
        sl = [np.s_[:]] * len(self._dims)
        sl[axis] = index
        return sorted(int(x) for x in self._rank_mesh[tuple(sl)].reshape(-1))

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        """All groups along an axis: list of rank-lists (parity:
        CommunicateTopology.get_comm_list)."""
        axis = self._parallel_names.index(axis_name)
        moved = np.moveaxis(self._rank_mesh, axis, -1)
        return [list(map(int, row)) for row in moved.reshape(-1, self._dims[axis])]

    def get_rank_from_stage(self, global_rank: int, **kwargs) -> int:
        coord = self.get_coord(global_rank)
        coord.update(kwargs)
        return self.get_rank(**coord)


class HybridCommunicateGroup:
    """Builds per-axis communication groups over the hybrid mesh (parity:
    topology.py:174). Axis groups carry the mesh axis name so collectives
    lower to lax primitives over that axis."""

    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        self.global_rank = 0  # single-controller: logical rank 0's view
        self._dp_degree = topology.get_dim("data")
        self._pp_degree = topology.get_dim("pipe")
        self._sharding_degree = topology.get_dim("sharding")
        self._sep_degree = topology.get_dim("sep")
        self._mp_degree = topology.get_dim("model")
        self.nranks = topology.world_size()

        def make_group(axis):
            return Group(axis, topology.get_comm_list(axis)[0],
                         mesh=topology.mesh)

        def fused_ranks(axes):
            # the rank-0 fused group: all coords 0 except the fused axes
            sl = [0] * len(topology._dims)
            for ax in axes:
                sl[topology._parallel_names.index(ax)] = np.s_[:]
            return sorted(int(x) for x in
                          topology._rank_mesh[tuple(sl)].reshape(-1))

        self._dp_group = make_group("data")
        self._pp_group = make_group("pipe")
        self._sharding_group = make_group("sharding")
        self._sep_group = make_group("sep")
        self._mp_group = make_group("model")
        # fused groups (reference: dp+sep, dp+sharding fusion for grad sync)
        self._dp_sep_group = Group(("data", "sep"), fused_ranks(["data", "sep"]),
                                   mesh=topology.mesh)
        self._sharding_dp_group = Group(("sharding", "data"),
                                        fused_ranks(["sharding", "data"]),
                                        mesh=topology.mesh)

    @property
    def topology(self):
        return self._topo

    # -- degrees / ranks (reference API surface) ---------------------------
    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_sharding_parallel_rank(self):
        return 0

    def get_sep_parallel_rank(self):
        return 0

    # -- groups ------------------------------------------------------------
    def get_data_parallel_group(self):
        return self._dp_group

    def get_model_parallel_group(self):
        return self._mp_group

    def get_pipe_parallel_group(self):
        return self._pp_group

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sep_parallel_group(self):
        return self._sep_group

    def get_dp_sep_parallel_group(self):
        return self._dp_sep_group

    def get_sharding_dp_parallel_group(self):
        return self._sharding_dp_group

    def get_data_parallel_group_src_rank(self):
        return self._dp_group.ranks[0]

    def get_model_parallel_group_src_rank(self):
        return self._mp_group.ranks[0]

    # -- pipe neighbors ----------------------------------------------------
    def is_first_stage(self):
        return True

    def is_last_stage(self):
        return self._pp_degree == 1

    def get_p2p_groups(self):
        return (self._pp_group, self._pp_group)

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank_from_stage(self.global_rank,
                                              pipe=stage_id, **kwargs)
