from ..recompute import recompute, recompute_sequential  # noqa: F401
from .hybrid_parallel_util import fused_allreduce_gradients  # noqa: F401
from . import sequence_parallel_utils  # noqa: F401
from . import hybrid_parallel_util  # noqa: F401
from . import timer_helper  # noqa: F401
from .timer_helper import get_timers, set_timers  # noqa: F401
