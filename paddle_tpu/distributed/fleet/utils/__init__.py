from ..recompute import recompute, recompute_sequential  # noqa: F401
from .hybrid_parallel_util import fused_allreduce_gradients  # noqa: F401
from . import sequence_parallel_utils  # noqa: F401
from . import hybrid_parallel_util  # noqa: F401
from . import timer_helper  # noqa: F401
from .timer_helper import get_timers, set_timers  # noqa: F401


class LocalFS:
    """Local filesystem client (parity: paddle.distributed.fleet.utils
    .LocalFS, fleet/utils/fs.py — the FS interface the checkpoint and
    PS paths use)."""

    def ls_dir(self, fs_path):
        import os
        dirs, files = [], []
        if not os.path.exists(fs_path):
            return dirs, files
        for e in sorted(os.listdir(fs_path)):
            (dirs if os.path.isdir(os.path.join(fs_path, e))
             else files).append(e)
        return dirs, files

    def mkdirs(self, fs_path):
        import os
        os.makedirs(fs_path, exist_ok=True)

    def is_dir(self, fs_path):
        import os
        return os.path.isdir(fs_path)

    def is_file(self, fs_path):
        import os
        return os.path.isfile(fs_path)

    def is_exist(self, fs_path):
        import os
        return os.path.exists(fs_path)

    def delete(self, fs_path):
        import os
        import shutil
        if os.path.isdir(fs_path):
            shutil.rmtree(fs_path)
        elif os.path.exists(fs_path):
            os.remove(fs_path)

    def rename(self, fs_src_path, fs_dst_path):
        import os
        os.rename(fs_src_path, fs_dst_path)

    def mv(self, src_path, dst_path, overwrite=False, test_exists=True):
        import os
        if not overwrite and os.path.exists(dst_path):
            raise FileExistsError(dst_path)
        os.replace(src_path, dst_path)

    def upload(self, local_path, fs_path):
        import shutil
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        import shutil
        shutil.copy(fs_path, local_path)

    def touch(self, fs_path, exist_ok=True):
        import os
        if os.path.exists(fs_path) and not exist_ok:
            raise FileExistsError(fs_path)
        open(fs_path, "a").close()

    def cat(self, fs_path):
        with open(fs_path, "rb") as f:
            return f.read()

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]


class HDFSClient:
    """HDFS client stub (parity surface: fleet.utils.HDFSClient — the
    reference shells out to the hadoop CLI; no hadoop exists in this
    image, so construction requires an explicit local fallback)."""

    def __init__(self, hadoop_home=None, configs=None, time_out=300000,
                 sleep_inter=1000):
        raise RuntimeError(
            "HDFSClient is not implemented in the TPU build (the "
            "reference shells out to the hadoop CLI, which this image "
            "does not ship) — use LocalFS or mount the HDFS path")


class DistributedInfer:
    """Distributed inference helper (parity: fleet.utils.DistributedInfer
    — the reference rewrites a PS program for inference; here it wraps a
    Layer/program and runs the local shard)."""

    def __init__(self, main_program=None, startup_program=None):
        self._main = main_program

    def init_distributed_infer_env(self, exe, loss, role_maker=None,
                                   dirname=None):
        if dirname is not None:
            from ....framework import load
            state = load(dirname)
            if hasattr(self._main, "set_state_dict"):
                self._main.set_state_dict(state)

    def get_dist_infer_program(self):
        return self._main
