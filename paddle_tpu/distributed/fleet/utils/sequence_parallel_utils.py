"""Megatron-style sequence parallelism utilities.

Capability parity with the reference (reference: fleet/utils/
sequence_parallel_utils.py — ScatterOp/GatherOp/AllGatherOp/ReduceScatterOp
PyLayers :85-230, ColumnSequenceParallelLinear:230,
RowSequenceParallelLinear:340, register_sequence_parallel_allreduce_hooks).

TPU-native: activations sharded on the sequence dim over the model axis are
a Shard(seq-dim) constraint; the scatter/gather/reduce-scatter transitions
are sharding moves whose collectives XLA schedules. The PyLayer op set is
kept for the comm-explicit shard_map face.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ....core.dispatch import run_op
from ....core.tensor import Tensor
from ....nn import functional as F
from ....nn.initializer import XavierUniform
from ....nn.layer.layers import Layer

__all__ = ["ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
           "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
           "mark_as_sequence_parallel_parameter",
           "register_sequence_parallel_allreduce_hooks"]

_SEQ_DIM = 0  # the reference shards dim 0 ([s, b, h]) inside the TP region


def _mesh():
    from ..fleet import fleet as _fleet
    hcg = _fleet.get_hybrid_communicate_group()
    return hcg.topology.mesh.to_jax() if hcg else None


def _move(x, spec_entries, name):
    m = _mesh()
    if m is None:
        return x if isinstance(x, Tensor) else Tensor(x)
    sh = NamedSharding(m, P(*spec_entries))

    def fn(a):
        if isinstance(a, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(a, sh)
        return jax.device_put(a, sh)
    return run_op(name, fn, (x,))


class ScatterOp:
    """Split activations along the sequence dim over the model axis
    (reference ScatterOp: fwd split / bwd all-gather)."""

    @staticmethod
    def apply(x, axis=_SEQ_DIM):
        entries = [None] * x.ndim
        entries[axis] = "model"
        return _move(x, entries, "sp_scatter")


class GatherOp:
    """All-gather along sequence dim (fwd) / split (bwd)."""

    @staticmethod
    def apply(x, axis=_SEQ_DIM):
        return _move(x, [None] * x.ndim, "sp_gather")


class AllGatherOp:
    """All-gather fwd / reduce-scatter bwd (reference AllGatherOp) — the
    grad-reducing gather used before column-parallel matmuls."""

    @staticmethod
    def apply(x, axis=_SEQ_DIM):
        return _move(x, [None] * x.ndim, "sp_all_gather")


class ReduceScatterOp:
    """Reduce-scatter fwd / all-gather bwd (reference ReduceScatterOp)."""

    @staticmethod
    def apply(x, axis=_SEQ_DIM):
        entries = [None] * x.ndim
        entries[axis] = "model"
        return _move(x, entries, "sp_reduce_scatter")


class ColumnSequenceParallelLinear(Layer):
    """Column-parallel linear fed by sequence-sharded activations
    (reference :230): all-gather(seq) -> matmul(col-sharded W)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=False, mp_group=None, name=None):
        super().__init__()
        from ..layers.mpu.mp_layers import _shard_param
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        _shard_param(self.weight, (None, "model"))
        if has_bias:
            self.bias = self.create_parameter(shape=[out_features],
                                              is_bias=True)
            _shard_param(self.bias, ("model",))
        else:
            self.bias = None

    def forward(self, x):
        x = AllGatherOp.apply(x)
        y = F.linear(x, self.weight, self.bias)
        entries = [None] * y.ndim
        entries[-1] = "model"
        return _move(y, entries, "csp_out")


class RowSequenceParallelLinear(Layer):
    """Row-parallel linear producing sequence-sharded output
    (reference :340): matmul(row-sharded W) -> reduce-scatter(seq)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True, mp_group=None,
                 name=None):
        super().__init__()
        from ..layers.mpu.mp_layers import _shard_param
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        _shard_param(self.weight, ("model", None))
        if has_bias:
            self.bias = self.create_parameter(shape=[out_features],
                                              is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        y = F.linear(x, self.weight)
        y = ReduceScatterOp.apply(y)
        if self.bias is not None:
            y = y + self.bias
        return y


def mark_as_sequence_parallel_parameter(parameter):
    parameter.sequence_parallel = True


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_sequence_parallel_allreduce=False):
    """Reference registers backward hooks to allreduce SP-param grads over
    the mp group; under SPMD those grads are computed on global arrays and
    are already correct — kept as an API no-op with the marker check."""
    return model
