"""Fleet logging helpers (parity: fleet/utils/log_util.py)."""
from __future__ import annotations

import logging

__all__ = ["logger", "set_log_level", "get_log_level_code",
           "get_log_level_name", "layer_to_str"]

logger = logging.getLogger("paddle_tpu.distributed.fleet")
if not logger.handlers:
    h = logging.StreamHandler()
    h.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s: %(message)s"))
    logger.addHandler(h)
logger.setLevel(logging.INFO)


def set_log_level(level):
    if isinstance(level, int):
        logger.setLevel(level)
    else:
        logger.setLevel(str(level).upper())


def get_log_level_code():
    return logger.getEffectiveLevel()


def get_log_level_name():
    return logging.getLevelName(get_log_level_code())


def layer_to_str(base, *args, **kwargs):
    parts = [str(a) for a in args]
    parts += [f"{k}={v}" for k, v in kwargs.items()]
    return f"{base}({', '.join(parts)})"
