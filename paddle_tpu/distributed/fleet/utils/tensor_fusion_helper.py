"""Tensor fusion for communication (parity:
fleet/utils/tensor_fusion_helper.py — flatten many small param/grad
tensors into one fused buffer so the comm backend launches one collective
per bucket instead of one per tensor).

TPU-first note: inside a jitted step XLA already buckets and schedules
collectives, so the *performance* role of fusion is owned by the
compiler. What remains real on this substrate — and is implemented
natively here — is the EAGER path's bucketing (fewer dispatches of
``all_reduce`` during dygraph DP training) and the memory layout
contract (grad views into one flat buffer) that sharding bookkeeping
uses.
"""
from __future__ import annotations

import builtins
from collections import OrderedDict

import jax.numpy as jnp
import numpy as np

from ....core.tensor import Tensor

__all__ = ["HOOK_ACTION", "assign_group_by_size", "flatten_dense_tensors",
           "FusedCommBuffer", "fused_parameters", "filter_params"]


class HOOK_ACTION:
    ALL_REDUCE = 0
    REDUCE = 1
    REDUCE_SCATTER = 2


def assign_group_by_size(parameters, group_size=128 * 1024 * 1024):
    """Greedy size-bucketing of parameters (reference :45): consecutive
    params go to the same group until its byte size exceeds
    ``group_size``. Returns {group_idx: [params]}."""
    var_groups: "OrderedDict[int, list]" = OrderedDict()
    gidx, acc = 0, 0
    for p in parameters:
        nbytes = int(np.prod(p.shape)) * p._data.dtype.itemsize
        if acc > 0 and acc + nbytes > group_size:
            gidx += 1
            acc = 0
        var_groups.setdefault(gidx, []).append(p)
        acc += nbytes
    return var_groups


def flatten_dense_tensors(parameters, use_main_grad=False, fuse_param=True,
                          warp_buffer=False):
    """Concatenate the params' storage into ONE flat f32/bf16 buffer and
    return (param_storage, grad_storage) Tensors; each param keeps its
    shape but its ``.grad`` is expected to be written back into its slice
    (reference :59 ParamStorage/GradStorage semantics)."""
    dtype = parameters[0]._data.dtype
    gdtype = jnp.float32 if use_main_grad else dtype
    flats = [p._data.reshape(-1) for p in parameters]
    param_storage = Tensor(jnp.concatenate(flats).astype(dtype)) \
        if fuse_param else None
    total = sum(int(np.prod(p.shape)) for p in parameters)
    grad_storage = Tensor(jnp.zeros((total,), gdtype))
    return param_storage, grad_storage


def filter_params(params, is_fp32, is_distributed, need_clip):
    """Split params by (fp32?, distributed?, need-clip?) — the grouping
    keys the fused buffers are built per (reference :639)."""
    out = []
    for p in params:
        p_fp32 = p._data.dtype == jnp.float32
        p_dist = getattr(p, "is_distributed", False)
        p_clip = getattr(p, "need_clip", True)
        if (p_fp32 == is_fp32 and p_dist == is_distributed
                and p_clip == need_clip):
            out.append(p)
    dtype = out[0]._data.dtype if out else None
    return out, dtype


class FusedCommBuffer:
    """One comm bucket: accumulates its params' grads into a flat buffer
    and launches a single collective when every grad of the bucket has
    arrived (reference :310). Eager-path semantics; pass ``act`` from
    HOOK_ACTION."""

    def __init__(self, id, params, comm_group, acc_steps=1, act=None,
                 dst=-1, use_main_grad=None, fuse_param=False,
                 scale_after_comm=True, release_grads=False):
        self._id = id
        self._params = list(params)
        self._comm_group = comm_group
        self._acc_steps = acc_steps
        self._act = HOOK_ACTION.ALL_REDUCE if act is None else act
        if self._act == HOOK_ACTION.REDUCE and dst < 0:
            raise ValueError("HOOK_ACTION.REDUCE needs a dst rank")
        self._dst = dst
        self._scale_after_comm = scale_after_comm
        self._sizes = [int(np.prod(p.shape)) for p in self._params]
        self._offsets = np.cumsum([0] + self._sizes).tolist()
        self._index = {builtins.id(p): i
                       for i, p in enumerate(self._params)}
        self._pending = set(self._index)
        self.param_storage, self.grad_storage = flatten_dense_tensors(
            self._params, use_main_grad=bool(use_main_grad),
            fuse_param=fuse_param)

    @property
    def params(self):
        return self._params

    def add_grad(self, param, use_comm=True):
        """Record ``param``'s grad into its slice; when the bucket is
        complete, run the fused collective and scatter results back."""
        pid = builtins.id(param)
        if pid not in self._index:
            raise ValueError(
                "param does not belong to this FusedCommBuffer bucket")
        if pid not in self._pending:
            raise ValueError("param already added this step")
        i = self._index[pid]
        lo, hi = self._offsets[i], self._offsets[i + 1]
        # ACCUMULATE into the slice: micro-steps before the sync step add
        # up (the reference's grad-accumulation contract)
        g = param.grad._data.reshape(-1).astype(self.grad_storage._data.dtype)
        self.grad_storage._data = self.grad_storage._data.at[lo:hi].add(g)
        # bank-and-clear: this framework's backward() ACCUMULATES into
        # param.grad (core/autograd.py _accumulate_grad), so leaving the
        # banked value in place would double-count it when the next
        # micro-step's backward adds on top and add_grad banks the running
        # sum again (2*g1+g2 after two micro-steps). The reference never
        # hits this because its grads are views INTO the fused buffer;
        # here the buffer owns the running sum, so the param-side slot is
        # zeroed once banked and every micro-step contributes its delta.
        param.grad._data = jnp.zeros_like(param.grad._data)
        self._pending.discard(pid)
        if not self._pending:
            if use_comm:
                if not self._scale_after_comm and self._acc_steps > 1:
                    # reference contract: scale_after_comm=False means
                    # scale BEFORE the collective, never "don't scale"
                    self.grad_storage._data = (
                        self.grad_storage._data / self._acc_steps)
                self.comm_grads()
                self.scale_and_split_grads()
            else:
                # non-sync micro-step: re-arm for the next accumulation
                # round, keep the accumulated buffer
                self._pending = set(self._index)

    def comm_grads(self):
        from ... import parallel as _par
        if getattr(_par, "get_world_size", lambda: 1)() <= 1:
            return
        if self._act == HOOK_ACTION.ALL_REDUCE:
            from ...communication_impl import all_reduce
            t = Tensor(self.grad_storage._data)
            all_reduce(t, group=self._comm_group)
        elif self._act == HOOK_ACTION.REDUCE:
            from ...communication_impl import reduce as _reduce
            t = Tensor(self.grad_storage._data)
            _reduce(t, dst=self._dst, group=self._comm_group)
        else:
            raise NotImplementedError(
                "HOOK_ACTION.REDUCE_SCATTER buckets ride the sharding "
                "stack's own reduce-scatter (auto_parallel shard_optimizer"
                " / fleet sharding), not FusedCommBuffer")
        self.grad_storage._data = t._data

    def scale_and_split_grads(self):
        """Write fused results back into each param.grad (scaled by the
        accumulation steps when scale_after_comm)."""
        buf = self.grad_storage._data
        if self._scale_after_comm and self._acc_steps > 1:
            buf = buf / self._acc_steps
        for i, p in enumerate(self._params):
            lo, hi = self._offsets[i], self._offsets[i + 1]
            p.grad._data = buf[lo:hi].reshape(p.shape).astype(
                p.grad._data.dtype)
        # re-arm and clear the accumulator for the next round
        self._pending = set(self._index)
        self.grad_storage._data = jnp.zeros_like(self.grad_storage._data)


def fused_parameters(parameters, use_main_grad=False, fuse_param=True,
                     comm_overlap=False, comm_group=None, act=None,
                     dst=-1, group_params=False, group_size=128 * 1024 * 1024,
                     apply_decay_param_fun=None, scale_after_comm=True):
    """Bucket ``parameters`` by size and build a FusedCommBuffer per
    bucket (reference :758). Returns (decay_fused, all_fused, all_buffers)
    with the reference's triple shape."""
    groups = assign_group_by_size(parameters, group_size)
    buffers = []
    for gid, params in groups.items():
        buffers.append(FusedCommBuffer(
            gid, params, comm_group, act=act, dst=dst,
            use_main_grad=use_main_grad, fuse_param=fuse_param,
            scale_after_comm=scale_after_comm))
    decay_fused = [p for p in parameters
                   if apply_decay_param_fun is None
                   or apply_decay_param_fun(getattr(p, "name", ""))]
    return decay_fused, list(parameters), buffers
