"""Distributed timers (parity: python/paddle/distributed/fleet/utils/
timer_helper.py — get_timers/set_timers, _Timer start/stop/elapsed,
log with cross-rank min/max via collectives)."""
from __future__ import annotations

import time
from typing import Dict, Optional

__all__ = ["get_timers", "set_timers", "Timers"]

_GLOBAL_TIMERS: Optional["Timers"] = None


def get_timers() -> "Timers":
    global _GLOBAL_TIMERS
    if _GLOBAL_TIMERS is None:
        _GLOBAL_TIMERS = Timers()
    return _GLOBAL_TIMERS


def set_timers(timers: Optional["Timers"] = None):
    global _GLOBAL_TIMERS
    _GLOBAL_TIMERS = timers if timers is not None else Timers()


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self._elapsed = 0.0
        self._started = False
        self._start_time = 0.0

    def start(self):
        assert not self._started, f"timer {self.name} already started"
        self._start_time = time.perf_counter()
        self._started = True

    def stop(self):
        assert self._started, f"timer {self.name} not started"
        self._elapsed += time.perf_counter() - self._start_time
        self._started = False

    def reset(self):
        self._elapsed = 0.0
        self._started = False

    def elapsed(self, reset: bool = True) -> float:
        was_started = self._started
        if was_started:
            self.stop()
        e = self._elapsed
        if reset:
            self.reset()
        if was_started:
            self.start()
        return e


class Timers:
    def __init__(self):
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def __contains__(self, name: str) -> bool:
        return name in self.timers

    def log(self, names=None, normalizer: float = 1.0, reset: bool = True
            ) -> str:
        """Per-name elapsed ms (divided by ``normalizer``, e.g. number of
        microbatches), printed and returned."""
        assert normalizer > 0.0
        names = names if names is not None else list(self.timers)
        parts = []
        for name in names:
            if name not in self.timers:
                continue
            ms = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
            parts.append(f"{name}: {ms:.2f}")
        text = "time (ms) | " + " | ".join(parts)
        print(text, flush=True)
        return text
