"""Hybrid-parallel gradient utilities (reference: fleet/utils/
hybrid_parallel_util.py — fused_allreduce_gradients, param broadcast
helpers).

Single-controller SPMD: grads of replicated params are computed from the
full (mesh-wide) batch, so the DP all-reduce is already folded into the
backward reduction; these helpers normalize Partial-represented grads and
keep the reference API for training loops that call them.
"""
from __future__ import annotations

from ....core.tensor import Tensor

__all__ = ["fused_allreduce_gradients", "broadcast_mp_parameters",
           "broadcast_dp_parameters", "broadcast_sharding_parameters",
           "sharding_reduce_gradients"]


def fused_allreduce_gradients(parameter_list, hcg=None):
    """Reduce any Partial grads to full values (reference: bucketed
    allreduce over the dp(+sep) group)."""
    for p in parameter_list:
        g = p.grad if isinstance(p, Tensor) else None
        if g is not None and g.dist_attr is not None and \
                g.dist_attr.partial_axes:
            from ...auto_parallel.api import unshard_dtensor
            p.grad = unshard_dtensor(g)


def broadcast_mp_parameters(model, hcg=None):
    """No-op under SPMD: replicated params are one global array."""


def broadcast_dp_parameters(model, hcg=None):
    """No-op under SPMD."""


def broadcast_sharding_parameters(model, hcg=None):
    """No-op under SPMD."""


def sharding_reduce_gradients(parameter_list, hcg=None):
    fused_allreduce_gradients(parameter_list, hcg)
