"""Elastic manager: node heartbeats + membership watch over the native KV
store (parity: python/paddle/distributed/fleet/elastic/manager.py:126 —
ElasticManager with etcd leases/heartbeats, scale detection, relaunch).

TPU-native difference: the reference heartbeats into etcd; here nodes
heartbeat timestamped keys into the job's TCPStore (the launcher master).
TPU slices have fixed shape, so ELASTIC-level scale-up/down maps to
slice-level reprovisioning — FAULT_TOLERANCE (dead-node detection +
re-rendezvous signal) is the primary mode.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional

from ...store import TCPStore

__all__ = ["ElasticLevel", "ElasticStatus", "ElasticManager"]


class ElasticLevel:
    """Parity: manager.py:43."""
    FAULT_TOLERANCE = 1   # fixed np; survive restarts of members
    ELASTIC = 2           # np range; membership may grow/shrink


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """Heartbeat this node; watch peers; report membership health."""

    def __init__(self, store: TCPStore, node_id: str,
                 np_target: int, heartbeat_interval: float = 1.0,
                 heartbeat_timeout: float = 5.0,
                 level: int = ElasticLevel.FAULT_TOLERANCE,
                 job_id: str = "default"):
        self.store = store
        self.node_id = node_id
        self.np_target = np_target
        self.interval = heartbeat_interval
        self.timeout = heartbeat_timeout
        self.level = level
        self.prefix = f"__elastic/{job_id}"
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._epoch_key = f"{self.prefix}/epoch"
        # node -> (last counter, monotonic time it was first observed)
        self._seen: dict = {}

    # -- heartbeats --------------------------------------------------------
    # heartbeats are monotonic counters bumped via store.add, and liveness
    # is "counter changed within timeout BY THE WATCHER'S OWN CLOCK" —
    # cross-host wall-clock skew can neither kill a healthy node nor mask
    # a dead one (the reference leans on etcd lease TTLs for the same
    # property).
    def start(self):
        import weakref as _weakref
        self.store.add(f"{self.prefix}/hb/{self.node_id}", 1)
        self._stop.clear()
        # the beat thread holds only a WEAK ref to self: an abandoned
        # manager (no stop() call) must stay collectible so the
        # _ACTIVE_MANAGERS weak registry can drop it
        self._thread = threading.Thread(
            target=_beat_loop,
            args=(_weakref.ref(self), self._stop, self.interval),
            daemon=True)
        self._thread.start()
        _ACTIVE_MANAGERS[id(self)] = self

    def stop(self):
        _ACTIVE_MANAGERS.pop(id(self), None)
        self._stop.set()
        if self._thread:
            self._thread.join(self.interval * 3)
            self._thread = None
        self.store.set(f"{self.prefix}/hb/{self.node_id}", "")

    def _beat(self):  # kept for API compatibility; start() uses _beat_loop
        _beat_loop(lambda: self, self._stop, self.interval)

    # -- membership --------------------------------------------------------
    def register_nodes(self, node_ids: List[str]):
        """The launcher registers the full expected membership."""
        self.store.set(f"{self.prefix}/members", ",".join(node_ids))

    def _snapshot(self):
        """One consistent poll: (alive, dead) from a single read pass.
        A node is alive while its heartbeat counter keeps advancing within
        ``timeout`` seconds of this watcher's monotonic clock."""
        members = self.store.get(f"{self.prefix}/members").decode()
        now = time.monotonic()
        alive, dead = [], []
        for n in members.split(","):
            if not n:
                continue
            try:
                raw = self.store.get(f"{self.prefix}/hb/{n}",
                                     wait=False).decode()
            except KeyError:
                raw = ""
            if not raw:  # never started, or stopped cleanly
                self._seen.pop(n, None)
                dead.append(n)
                continue
            counter = int(raw)
            last = self._seen.get(n)
            if last is None or last[0] != counter:
                self._seen[n] = (counter, now)
                alive.append(n)
            elif now - last[1] < self.timeout:
                alive.append(n)
            else:
                dead.append(n)
        return alive, dead

    def alive_nodes(self) -> List[str]:
        return self._snapshot()[0]

    def dead_nodes(self) -> List[str]:
        return self._snapshot()[1]

    # -- health decision (parity: manager's watch loop outcome) -----------
    def watch(self) -> str:
        """One poll: HOLD if healthy, RESTART if a member died (fault
        tolerance), EXIT if membership can never reach np_target."""
        alive, dead = self._snapshot()
        if len(alive) >= self.np_target and not dead:
            return ElasticStatus.HOLD
        if self.level == ElasticLevel.FAULT_TOLERANCE:
            return ElasticStatus.RESTART
        # ELASTIC: shrink is acceptable down to 1 node
        return ElasticStatus.RESTART if alive else ElasticStatus.EXIT

    def signal_restart(self):
        """Bump the job epoch — every node's training loop polls this and
        re-enters rendezvous (the reference's relaunch signal)."""
        self.store.add(self._epoch_key, 1)

    def current_epoch(self) -> int:
        return self.store.add(self._epoch_key, 0)


def _beat_loop(ref, stop_event, interval):
    """Heartbeat loop resolving the manager through a weak ref each tick:
    when the manager is garbage (abandoned without stop()), the thread
    exits instead of pinning it alive forever."""
    while not stop_event.wait(interval):
        m = ref()
        if m is None:
            return
        try:
            m.store.add(f"{m.prefix}/hb/{m.node_id}", 1)
        except Exception:
            return  # store gone: the watcher will see us dead
        del m  # don't hold the strong ref across the sleep


# comm-watchdog integration (reference: the NCCL watchdog aborts training
# so the elastic layer relaunches rather than letting the job hang).
# Weak values: a manager abandoned without stop() must not be kept alive
# (pinning its store/threads) nor have its stale job epoch bumped later.
import weakref  # noqa: E402

_ACTIVE_MANAGERS: "weakref.WeakValueDictionary" = weakref.WeakValueDictionary()


def notify_comm_hang(desc: str) -> None:
    """Called by CommTaskManager when a device sync times out: signal a
    restart on every active elastic manager so the cluster re-rendezvous."""
    for m in list(_ACTIVE_MANAGERS.values()):
        try:
            m.signal_restart()
        except Exception:
            pass
