"""Elastic manager: node heartbeats + membership watch over the native KV
store (parity: python/paddle/distributed/fleet/elastic/manager.py:126 —
ElasticManager with etcd leases/heartbeats, scale detection, relaunch).

TPU-native difference: the reference heartbeats into etcd; here nodes
heartbeat timestamped keys into the job's TCPStore (the launcher master).
TPU slices have fixed shape, so ELASTIC-level scale-up/down maps to
slice-level reprovisioning — FAULT_TOLERANCE (dead-node detection +
re-rendezvous signal) is the primary mode.
"""
from __future__ import annotations

import threading
from typing import List, Optional

from ...store import TCPStore

__all__ = ["ElasticLevel", "ElasticStatus", "ElasticManager"]


class ElasticLevel:
    """Parity: manager.py:43."""
    FAULT_TOLERANCE = 1   # fixed np; survive restarts of members
    ELASTIC = 2           # np range; membership may grow/shrink


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """Heartbeat this node; watch peers; report membership health.

    ``np_target`` is either a fixed int (FAULT_TOLERANCE: survive member
    restarts at constant world size) or a ``(min_np, max_np)`` range, which
    selects ELASTIC level: membership may grow (announce_join) or shrink
    (leave/death) between epochs, and watch() asks for a re-rendezvous
    whenever the live membership can change shape (reference:
    fleet/elastic/manager.py:126 np-range parsing + scale in/out)."""

    def __init__(self, store: TCPStore, node_id: str,
                 np_target, heartbeat_interval: float = 1.0,
                 heartbeat_timeout: float = 5.0,
                 level: Optional[int] = None,
                 job_id: str = "default",
                 comm_manager=None):
        self.store = store
        self.node_id = node_id
        if isinstance(np_target, (tuple, list)):
            self.min_np, self.max_np = int(np_target[0]), int(np_target[1])
        else:
            self.min_np = self.max_np = int(np_target)
        self.np_target = self.min_np
        if level is None:
            level = (ElasticLevel.ELASTIC if self.min_np != self.max_np
                     else ElasticLevel.FAULT_TOLERANCE)
        self.interval = heartbeat_interval
        self.timeout = heartbeat_timeout
        self.level = level
        self.prefix = f"__elastic/{job_id}"
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._epoch_key = f"{self.prefix}/epoch"
        self._epoch_ver = 0
        self._last_epoch = 0
        self._comm_manager = comm_manager

    # -- heartbeats --------------------------------------------------------
    # each node renews a server-side LEASE (csrc/kv_store.cpp LEASE_SET):
    # the key expires ttl=heartbeat_timeout after the last renewal, so
    # liveness is a single existence check with no watcher-side clock
    # bookkeeping — the reference's etcd-lease contract, natively.
    def start(self):
        import weakref as _weakref
        self.store.lease_set(f"{self.prefix}/hb/{self.node_id}", "1",
                             ttl=self.timeout)
        self._last_epoch = self.current_epoch()
        self._epoch_ver = self._probe_version(self._epoch_key)
        self._stop.clear()
        # the beat thread holds only a WEAK ref to self: an abandoned
        # manager (no stop() call) must stay collectible so the
        # _ACTIVE_MANAGERS weak registry can drop it
        self._thread = threading.Thread(
            target=_beat_loop,
            args=(_weakref.ref(self), self._stop, self.interval),
            daemon=True)
        self._thread.start()
        _ACTIVE_MANAGERS[id(self)] = self

    def attach_comm_manager(self, comm_manager) -> None:
        """Tie a ``CommTaskManager``'s lifetime to this node's elastic
        membership: ``stop()`` closes it, so the watchdog worker pool
        cannot outlive the node it watches."""
        self._comm_manager = comm_manager

    def stop(self):
        _ACTIVE_MANAGERS.pop(id(self), None)
        self._stop.set()
        if self._thread:
            self._thread.join(self.interval * 3)
            self._thread = None
        self.store.delete_key(f"{self.prefix}/hb/{self.node_id}")
        if self._comm_manager is not None:
            self._comm_manager.close()

    def _beat(self):  # kept for API compatibility; start() uses _beat_loop
        _beat_loop(lambda: self, self._stop, self.interval)

    def _probe_version(self, key: str) -> int:
        """Current change-version of a key (0 if never touched)."""
        try:
            ver, _ = self.store.watch(key, 0, timeout=0.001)
            return ver
        except TimeoutError:
            return 0

    # -- membership --------------------------------------------------------
    def register_nodes(self, node_ids: List[str]):
        """The launcher registers the full expected membership."""
        self.store.set(f"{self.prefix}/members", ",".join(node_ids))

    def _members(self) -> List[str]:
        return [n for n in self.store.get(f"{self.prefix}/members")
                .decode().split(",") if n]

    def _hb_alive(self, node: str) -> bool:
        try:
            self.store.get(f"{self.prefix}/hb/{node}", wait=False)
            return True
        except KeyError:
            return False

    def _snapshot(self):
        """One consistent poll: (alive, dead). A node is alive while its
        heartbeat lease exists — the server expires it ``timeout`` seconds
        after the last renewal."""
        alive, dead = [], []
        for n in self._members():
            (alive if self._hb_alive(n) else dead).append(n)
        return alive, dead

    def alive_nodes(self) -> List[str]:
        return self._snapshot()[0]

    def dead_nodes(self) -> List[str]:
        return self._snapshot()[1]

    # -- elastic membership (level == ELASTIC) ------------------------------
    def announce_join(self):
        """A new node asks to join the job: append to the join log and
        start heartbeating; the cluster re-rendezvouses at the next
        watch() (reference scale-out path)."""
        idx = self.store.add(f"{self.prefix}/joinlog/next", 1)
        self.store.set(f"{self.prefix}/joinlog/{idx}", self.node_id)

    def pending_joiners(self) -> List[str]:
        """Announced nodes not yet admitted into the membership, oldest
        first, only those actually heartbeating."""
        n = self.store.add(f"{self.prefix}/joinlog/next", 0)
        members = set(self._members())
        out = []
        for i in range(1, n + 1):
            try:
                node = self.store.get(f"{self.prefix}/joinlog/{i}",
                                      wait=False).decode()
            except KeyError:
                continue
            try:
                self.store.get(f"{self.prefix}/joinlog/done/{node}",
                               wait=False)
                continue   # already admitted once
            except KeyError:
                pass
            if node and node not in members and node not in out \
                    and self._hb_alive(node):
                out.append(node)
        return out

    def accept_joiners(self) -> List[str]:
        """Fold pending joiners into the registered membership (launcher
        calls this while re-rendezvousing after a scale-up RESTART): dead
        members are dropped first, then joiners are admitted oldest-first
        up to max_np; joiners that still don't fit stay pending for the
        next cycle. Returns the new member list."""
        live, _ = self._snapshot()
        joiners = self.pending_joiners()
        admitted = joiners[:max(self.max_np - len(live), 0)]
        members = live + admitted
        self.register_nodes(members)
        for node in admitted:
            self.store.set(f"{self.prefix}/joinlog/done/{node}", "1")
        return members

    def drop_dead(self) -> List[str]:
        """Shrink the registered membership to the live nodes (launcher
        calls this on a scale-down RESTART). Returns the new member list."""
        alive, _ = self._snapshot()
        self.register_nodes(alive)
        return alive

    # -- health decision (parity: manager's watch loop outcome) -----------
    def watch(self) -> str:
        """One poll: HOLD if healthy, RESTART when membership must change
        shape (a member died, or — at ELASTIC level — new nodes can scale
        the job up), EXIT when the job cannot reach min_np."""
        alive, dead = self._snapshot()
        if self.level == ElasticLevel.FAULT_TOLERANCE:
            if len(alive) >= self.np_target and not dead:
                return ElasticStatus.HOLD
            return ElasticStatus.RESTART
        # ELASTIC
        joiners = self.pending_joiners()
        if dead:
            return (ElasticStatus.RESTART
                    if len(alive) + len(joiners) >= self.min_np
                    else ElasticStatus.EXIT)
        if joiners and len(alive) < self.max_np:
            return ElasticStatus.RESTART   # scale up
        if len(alive) >= self.min_np:
            return ElasticStatus.HOLD
        return (ElasticStatus.RESTART if joiners else ElasticStatus.EXIT)

    def signal_restart(self):
        """Bump the job epoch — every node's training loop observes this
        and re-enters rendezvous (the reference's relaunch signal)."""
        self.store.add(self._epoch_key, 1)

    def current_epoch(self) -> int:
        return self.store.add(self._epoch_key, 0)

    def wait_restart_signal(self, timeout: float) -> Optional[int]:
        """Block on the native WATCH until signal_restart() advances the
        epoch past what this manager last observed (no polling; a peer
        merely reading current_epoch() — which may create the key at 0 —
        never wakes us). Returns the new epoch, or None on timeout."""
        import time as _time
        deadline = _time.monotonic() + timeout
        while True:
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                return None
            try:
                ver, val = self.store.watch(self._epoch_key,
                                            self._epoch_ver, remaining)
            except TimeoutError:
                return None
            self._epoch_ver = ver
            epoch = int(val or b"0")
            if epoch > self._last_epoch:
                self._last_epoch = epoch
                return epoch


def _beat_loop(ref, stop_event, interval):
    """Lease-renewal loop resolving the manager through a weak ref each
    tick: when the manager is garbage (abandoned without stop()), the
    thread exits and the server expires the lease — peers see us dead."""
    while not stop_event.wait(interval):
        m = ref()
        if m is None:
            return
        if not _heartbeat_allowed(m.node_id):
            # fault harness: renewal suppressed — the server-side lease
            # expires and peers observe this node dead, process alive
            del m
            continue
        try:
            m.store.lease_set(f"{m.prefix}/hb/{m.node_id}", "1",
                              ttl=m.timeout)
        except Exception:
            return  # store gone: the watcher will see us dead
        del m  # don't hold the strong ref across the sleep


def _heartbeat_allowed(node_id: str) -> bool:
    """Fault-harness hook (resilience.faults heartbeat-drop injector)."""
    try:
        from ...resilience.faults import get_fault_injector
    except Exception:
        return True
    inj = get_fault_injector()
    if not inj.armed:
        return True
    return inj.heartbeat_allowed(node_id)


# comm-watchdog integration (reference: the NCCL watchdog aborts training
# so the elastic layer relaunches rather than letting the job hang).
# Weak values: a manager abandoned without stop() must not be kept alive
# (pinning its store/threads) nor have its stale job epoch bumped later.
import weakref  # noqa: E402

_ACTIVE_MANAGERS: "weakref.WeakValueDictionary" = weakref.WeakValueDictionary()


def notify_comm_hang(desc: str) -> None:
    """Called by CommTaskManager when a device sync times out: signal a
    restart on every active elastic manager so the cluster re-rendezvous."""
    for m in list(_ACTIVE_MANAGERS.values()):
        try:
            m.signal_restart()
        except Exception:
            pass
