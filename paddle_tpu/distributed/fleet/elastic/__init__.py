"""Elastic training manager (parity: python/paddle/distributed/fleet/
elastic/manager.py:126)."""
from .manager import ElasticLevel, ElasticManager, ElasticStatus  # noqa: F401
