"""ShardingParallel: the model-side half of ZeRO-1.

Capability parity with the reference (reference: fleet/meta_parallel/
sharding_parallel.py + dygraph_optimizer/dygraph_sharding_optimizer.py:48):
grads are reduced over the sharding group, each rank updates only its
optimizer-state shard, and updated weight shards are broadcast back.

TPU-native split of responsibilities: state partition + post-step
broadcast live in ``DygraphShardingOptimizer`` (meta_optimizers/
hybrid_parallel_optimizer.py) — picked automatically by
``fleet.distributed_optimizer`` when sharding_degree > 1. This wrapper
supplies the model-side contract: batch sharded over the fused
data×sharding axes (the reference reduces grads over exactly that fused
group, hybrid_parallel_util.py) and grad normalization after backward.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ....core.dispatch import run_op
from ....core.tensor import Tensor
from ...parallel import DataParallel

__all__ = ["ShardingParallel"]


class ShardingParallel(DataParallel):
    def __init__(self, layers, hcg=None, strategy=None, **kwargs):
        super().__init__(layers)
        self._hcg = hcg
        self._strategy = strategy

    def shard_batch(self, x, axis: int = 0):
        """Shard the batch dim over the fused data×sharding axes — the
        sharding group consumes distinct microbatches like dp (reference
        topology order pp->mp->sep->sharding->dp)."""
        t = x if isinstance(x, Tensor) else Tensor(x)
        if self._hcg is None:
            return t
        axes = []
        if self._hcg.get_data_parallel_world_size() > 1:
            axes.append("data")
        if self._hcg.get_sharding_parallel_world_size() > 1:
            axes.append("sharding")
        if not axes:
            return t
        n = 1
        for a in axes:
            n *= self._hcg.topology.get_dim(a)
        if t.shape[axis] % n:
            raise ValueError(
                f"batch dim {t.shape[axis]} not divisible by "
                f"data*sharding degree {n}")
        entries = [None] * len(t.shape)
        entries[axis] = tuple(axes) if len(axes) > 1 else axes[0]
        sh = NamedSharding(self._hcg.topology.mesh.to_jax(),
                           PartitionSpec(*entries))
        return run_op("sharding_batch_split",
                      lambda a: jax.device_put(a, sh), (t,))
