"""ShardingParallel wrapper (parity: fleet/meta_parallel/sharding_parallel.py)."""
from __future__ import annotations

from ...parallel import DataParallel


class ShardingParallel(DataParallel):
    def __init__(self, layers, hcg=None, strategy=None, **kwargs):
        super().__init__(layers)
        self._hcg = hcg
        self._strategy = strategy
