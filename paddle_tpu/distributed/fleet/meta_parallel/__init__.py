from .tensor_parallel import TensorParallel  # noqa: F401
from .sharding_parallel import ShardingParallel  # noqa: F401
from .segment_parallel import SegmentParallel  # noqa: F401
from .pipeline_parallel import (PipelineParallel,  # noqa: F401
                                PipelineParallelWithInterleave,
                                PipelineParallelWithInterleaveFthenB)
from .parallel_layers import (PipelineLayer, LayerDesc, SharedLayerDesc,  # noqa: F401
                              RNGStatesTracker, get_rng_state_tracker)
