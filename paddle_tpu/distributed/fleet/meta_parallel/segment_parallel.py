"""SegmentParallel (SEP) wrapper (parity: fleet/meta_parallel/
segment_parallel.py). The sep axis splits activations along the sequence
dim; under SPMD this is a Shard(seq) constraint on the activations — see
sequence_parallel_utils for the op set."""
from __future__ import annotations

from ...parallel import DataParallel


class SegmentParallel(DataParallel):
    def __init__(self, layers, hcg=None, strategy=None, **kwargs):
        super().__init__(layers)
        self._hcg = hcg
        self._strategy = strategy
