"""SegmentParallel (SEP): the dedicated long-context sequence axis.

Capability parity with the reference (reference: fleet/meta_parallel/
segment_parallel.py wrapper; sequence split via Split.apply(x, axis=1,
group=sep_group) in test/collective/fleet/hybrid_parallel_sep_model.py:143;
param-grad allreduce over the sep and fused dp×sep groups,
fleet/utils/hybrid_parallel_util.py:246-259).

TPU-native design: the sep axis is one named axis of the hybrid mesh.
``split_sequence`` shards the sequence dim of an activation over it (a
NamedSharding placement — XLA scatters over ICI); because activations are
then sep-sharded global arrays, the backward of any replicated param is a
global reduction and XLA inserts the psum over sep — the explicit
allreduce the reference does by hand. ``sync_gradients`` remains for grads
that surface as Partial metadata. Ring/Ulysses attention over the same
axis lives in distributed/long_context.py.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ....core.dispatch import run_op
from ....core.tensor import Tensor
from ...parallel import DataParallel

__all__ = ["SegmentParallel", "split_sequence", "gather_sequence",
           "sep_attention"]


def _sep_sharding(hcg, ndim: int, axis: int) -> NamedSharding:
    mesh = hcg.topology.mesh.to_jax()
    entries = [None] * ndim
    entries[axis] = "sep"
    return NamedSharding(mesh, PartitionSpec(*entries))


def split_sequence(x, hcg, axis: int = 1):
    """Shard the sequence dim over the sep axis (the reference's
    Split.apply over the sep group; backward = the gather, handled by the
    device_put vjp)."""
    t = x if isinstance(x, Tensor) else Tensor(x)
    n = hcg.get_sep_parallel_world_size()
    if n <= 1:
        return t
    if t.shape[axis] % n:
        raise ValueError(
            f"sequence dim {t.shape[axis]} not divisible by sep degree {n}")
    sh = _sep_sharding(hcg, len(t.shape), axis)
    return run_op("sep_split",
                  lambda a: jax.device_put(a, sh), (t,))


def gather_sequence(x, hcg, axis: int = 1):
    """Re-replicate the sequence dim (the reference's Concat over sep)."""
    t = x if isinstance(x, Tensor) else Tensor(x)
    if hcg.get_sep_parallel_world_size() <= 1:
        return t
    mesh = hcg.topology.mesh.to_jax()
    sh = NamedSharding(mesh, PartitionSpec())
    return run_op("sep_gather", lambda a: jax.device_put(a, sh), (t,))


def sep_attention(q, k, v, hcg, strategy=None, causal=True, scale=None,
                  impl=None):
    """Long-context attention over the fleet sep axis, strategy-selectable
    (VERDICT r4 #5): q/k/v are sep-sharded activations [B, S, H(k), D].

    The mode comes from ``strategy.sep_configs["attention"]``:
      - "ring": k/v chunks rotate over ICI, flash block kernel per step
        (distributed/long_context.py — the leapfrog over the reference's
        gather-then-local-kernel, segment_parallel reference above);
      - "ulysses": one all_to_all to head-sharding, local full-sequence
        flash, swap back (cheaper at moderate S, needs H % sep == 0);
      - "gather": replicate the sequence and run the local kernel — the
        reference's only sep mode, kept as the conservative fallback.
    """
    from ...long_context import ring_attention, ulysses_attention
    mode = "ring"
    if strategy is not None:
        mode = getattr(strategy, "sep_configs", {}).get("attention", "ring")
    if mode not in ("ring", "ulysses", "gather"):
        # validate BEFORE the sep==1 early-return: a typo'd strategy must
        # fail at degree 1 too, not only when the job scales out
        raise ValueError(
            f"unknown sep attention strategy {mode!r}: expected "
            "'ring' | 'ulysses' | 'gather'")
    layout = "contiguous"
    if strategy is not None:
        layout = getattr(strategy, "sep_configs", {}).get(
            "ring_layout", "contiguous")
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(
            f"unknown sep ring_layout {layout!r}: expected "
            "'contiguous' | 'zigzag'")
    n = hcg.get_sep_parallel_world_size()
    mesh = hcg.topology.mesh
    if scale is None:
        import math
        scale = 1.0 / math.sqrt(int(q.shape[-1]))
    if n <= 1 or mode == "gather":
        from ....core.dispatch import select_impl
        qg = gather_sequence(q, hcg)
        kg = gather_sequence(k, hcg)
        vg = gather_sequence(v, hcg)
        fa = select_impl("flash_attention")
        out = run_op("sep_local_attention",
                     lambda a, b, c: fa(a, b, c, None, causal, scale,
                                        0.0, None), (qg, kg, vg))
        return split_sequence(out, hcg) if n > 1 else out
    if mode == "ring":
        return ring_attention(q, k, v, mesh=mesh, seq_axis="sep",
                              causal=causal, scale=scale, impl=impl,
                              layout=layout if causal else "contiguous")
    return ulysses_attention(q, k, v, mesh=mesh, seq_axis="sep",
                             causal=causal, scale=scale)


class SegmentParallel(DataParallel):
    """Model wrapper for the sep axis (reference segment_parallel.py): the
    input's sequence dim is split across the sep group before the wrapped
    forward, and param grads are synchronized over sep(+dp) after
    backward."""

    def __init__(self, layers, hcg=None, strategy=None, seq_axis: int = 1,
                 **kwargs):
        super().__init__(layers)
        self._hcg = hcg
        self._strategy = strategy
        self._seq_axis = seq_axis

    def forward(self, *inputs, **kwargs):
        if inputs and self._hcg is not None and \
                self._hcg.get_sep_parallel_world_size() > 1:
            inputs = (split_sequence(inputs[0], self._hcg, self._seq_axis),
                      ) + inputs[1:]
        return self._layers(*inputs, **kwargs)

    __call__ = forward
