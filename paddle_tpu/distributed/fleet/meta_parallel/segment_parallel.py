"""SegmentParallel (SEP): the dedicated long-context sequence axis.

Capability parity with the reference (reference: fleet/meta_parallel/
segment_parallel.py wrapper; sequence split via Split.apply(x, axis=1,
group=sep_group) in test/collective/fleet/hybrid_parallel_sep_model.py:143;
param-grad allreduce over the sep and fused dp×sep groups,
fleet/utils/hybrid_parallel_util.py:246-259).

TPU-native design: the sep axis is one named axis of the hybrid mesh.
``split_sequence`` shards the sequence dim of an activation over it (a
NamedSharding placement — XLA scatters over ICI); because activations are
then sep-sharded global arrays, the backward of any replicated param is a
global reduction and XLA inserts the psum over sep — the explicit
allreduce the reference does by hand. ``sync_gradients`` remains for grads
that surface as Partial metadata. Ring/Ulysses attention over the same
axis lives in distributed/long_context.py.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ....core.dispatch import run_op
from ....core.tensor import Tensor
from ...parallel import DataParallel

__all__ = ["SegmentParallel", "split_sequence", "gather_sequence"]


def _sep_sharding(hcg, ndim: int, axis: int) -> NamedSharding:
    mesh = hcg.topology.mesh.to_jax()
    entries = [None] * ndim
    entries[axis] = "sep"
    return NamedSharding(mesh, PartitionSpec(*entries))


def split_sequence(x, hcg, axis: int = 1):
    """Shard the sequence dim over the sep axis (the reference's
    Split.apply over the sep group; backward = the gather, handled by the
    device_put vjp)."""
    t = x if isinstance(x, Tensor) else Tensor(x)
    n = hcg.get_sep_parallel_world_size()
    if n <= 1:
        return t
    if t.shape[axis] % n:
        raise ValueError(
            f"sequence dim {t.shape[axis]} not divisible by sep degree {n}")
    sh = _sep_sharding(hcg, len(t.shape), axis)
    return run_op("sep_split",
                  lambda a: jax.device_put(a, sh), (t,))


def gather_sequence(x, hcg, axis: int = 1):
    """Re-replicate the sequence dim (the reference's Concat over sep)."""
    t = x if isinstance(x, Tensor) else Tensor(x)
    if hcg.get_sep_parallel_world_size() <= 1:
        return t
    mesh = hcg.topology.mesh.to_jax()
    sh = NamedSharding(mesh, PartitionSpec())
    return run_op("sep_gather", lambda a: jax.device_put(a, sh), (t,))


class SegmentParallel(DataParallel):
    """Model wrapper for the sep axis (reference segment_parallel.py): the
    input's sequence dim is split across the sep group before the wrapped
    forward, and param grads are synchronized over sep(+dp) after
    backward."""

    def __init__(self, layers, hcg=None, strategy=None, seq_axis: int = 1,
                 **kwargs):
        super().__init__(layers)
        self._hcg = hcg
        self._strategy = strategy
        self._seq_axis = seq_axis

    def forward(self, *inputs, **kwargs):
        if inputs and self._hcg is not None and \
                self._hcg.get_sep_parallel_world_size() > 1:
            inputs = (split_sequence(inputs[0], self._hcg, self._seq_axis),
                      ) + inputs[1:]
        return self._layers(*inputs, **kwargs)

    __call__ = forward
