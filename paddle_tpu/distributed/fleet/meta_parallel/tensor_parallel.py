"""TensorParallel model wrapper (parity: fleet/meta_parallel/
tensor_parallel.py). The reference broadcasts non-distributed params over
the mp group at construction and syncs their grads in the optimizer; under
single-controller SPMD replication is the storage default, so construction
is free — grad sync of replicated params is XLA's duty (identical values by
construction)."""
from __future__ import annotations

from ...parallel import DataParallel


class TensorParallel(DataParallel):
    def __init__(self, layers, hcg=None, strategy=None, **kwargs):
        super().__init__(layers)
        self._hcg = hcg
        self._strategy = strategy
