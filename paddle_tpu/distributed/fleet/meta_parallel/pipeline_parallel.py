"""Pipeline-parallel execution engine: 1F1B and interleaved schedules.

Capability parity with the reference (reference: fleet/meta_parallel/
pipeline_parallel.py — train_batch:657, forward_backward_pipeline (1F1B)
:440, interleaved :906; p2p meta handshake pp_utils/p2p_communication.py:52).

TPU-native design — a real pipeline, not a grad-accumulation loop:

* **Stage sub-meshes.** The device list is partitioned into one sub-mesh
  per pipeline stage; every chunk's params are ``jax.device_put`` onto its
  stage's sub-mesh at engine construction (the analog of each pp rank
  holding only its stage, reference pp_layers.py:237).
* **Per-stage jitted programs.** Each chunk gets a pure functional
  forward (and a vjp-recompute backward) compiled once per shape; the
  host drives the schedule, so no recompilation per microbatch
  (SURVEY §7.3 #1: per-stage jitted programs with host-driven schedule).
* **p2p activation transfer.** Stage boundaries move activations (fwd)
  and activation-grads (bwd) between sub-meshes with ``jax.device_put`` —
  the single-controller analog of the reference's isend/irecv pairs; no
  shape/dtype meta handshake is needed because XLA shapes are static.
* **1F1B order.** Every (virtual) stage executes the exact reference
  action sequence — warmup forwards (min(P-1-s, m)), steady 1F1B
  alternation, cooldown backwards — via a dependency-driven scheduler.
  Stage s therefore never holds more than min(P-s, m) in-flight
  microbatch stashes (the 1F1B memory bound; reference
  pipeline_parallel.py:440), which ``_peak_stash`` records for tests.
* **Backward = recompute.** The stashed state per in-flight microbatch is
  the stage *input* only; the backward jit recomputes the stage forward
  inside ``jax.vjp``. Memory ≤ the reference's 1F1B profile (which stashes
  all intermediate activations) at ~1/3 extra FLOPs, the standard
  trade on HBM-bound hardware.
* **Interleave.** ``PipelineParallelWithInterleave`` runs
  ``num_stages * v`` virtual chunks with chunk g placed on sub-mesh
  g % num_stages (reference :906's virtual-pipeline assignment); the same
  scheduler executes the longer virtual chain.

Because dispatch is async, stage k's XLA program runs concurrently with
stage k+1's on its own sub-mesh — the overlap the reference gets from its
actor-based FleetExecutor falls out of the dependency order.
"""
from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ....core import random as _random
from ....core.autograd import tape_paused
from ....core.tensor import Tensor
from ....nn.layer.layers import _swapped_state
from .parallel_layers import PipelineLayer

__all__ = ["PipelineParallel", "PipelineParallelWithInterleave",
           "PipelineParallelWithInterleaveFthenB"]


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


class _CountingProgram:
    """Thin wrapper over a jitted chunk program that counts executions on
    the owning engine (``_program_executes``) — schedule-efficiency
    benches use the count to price the per-dispatch floor separately from
    real schedule cost. Passes ``_cache_size`` through for the retrace
    accounting."""

    def __init__(self, fn, owner):
        self._fn = fn
        self._owner = owner

    def __call__(self, *args, **kwargs):
        self._owner._program_executes += 1
        return self._fn(*args, **kwargs)

    def _cache_size(self):
        return self._fn._cache_size()


class PipelineParallel:
    def __init__(self, layers, hcg=None, strategy=None, devices=None,
                 stage_mesh_axes=None, batch_axis=None):
        """``stage_mesh_axes``: optional named shape for each stage's
        sub-mesh, e.g. ``{"dp": 2, "tp": 2}`` — the hybrid pp x tp x dp
        topology of the reference's HybridCommunicateGroup (§3.3 north
        star). Stage params pre-sharded over those axes keep their layout;
        ``batch_axis`` names the axis microbatch activations shard over
        (data parallelism within each stage)."""
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel requires a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        pcfg = (strategy.pipeline_configs if strategy is not None
                else {"accumulate_steps": 1, "micro_batch_size": 1})
        self.accumulate_steps = pcfg.get("accumulate_steps", 1)
        self.micro_batch_size = pcfg.get("micro_batch_size", 1)
        self.num_stages = layers.get_num_stages()
        self.num_chunks = layers.get_num_chunks()
        self.training = True
        self._batch_count = 0
        self._programs: Dict = {}  # (chunk, kind, train) -> jitted fn
        # device-program executions since construction: the schedule's
        # dispatch count, used by benches to separate per-dispatch floor
        # (remote tunnels: ~7 ms/program) from real schedule cost
        self._program_executes = 0
        self._peak_stash: List[int] = [0] * self.num_chunks
        self._stage_mesh_axes = dict(stage_mesh_axes or {})
        self._batch_axis = batch_axis
        if batch_axis is not None and batch_axis not in self._stage_mesh_axes:
            raise ValueError(
                f"batch_axis '{batch_axis}' not in stage_mesh_axes "
                f"{list(self._stage_mesh_axes)}")
        self._build_meshes(devices)
        self._collect_chunk_params()
        self._place_params()

    # -- sub-mesh construction ----------------------------------------------
    def _build_meshes(self, devices):
        from jax.sharding import Mesh, NamedSharding

        devs = list(devices) if devices is not None else list(jax.devices())
        p = self.num_stages
        per = len(devs) // p
        axes = self._stage_mesh_axes
        if axes:
            size = int(np.prod(list(axes.values())))
            if per != size:
                raise ValueError(
                    f"stage_mesh_axes {axes} needs {size} devices/stage, "
                    f"have {per} ({len(devs)} over {p} stages)")
        self._stage_meshes = []
        for s in range(p):
            sub = (devs[s * per:(s + 1) * per] if per >= 1
                   else [devs[s % len(devs)]])
            if axes:
                self._stage_meshes.append(Mesh(
                    np.array(sub).reshape(tuple(axes.values())),
                    tuple(axes)))
            else:
                self._stage_meshes.append(
                    Mesh(np.array(sub), ("stage_data",)))
        from paddle_tpu.distributed.spec_layout import default_layout
        self._stage_shardings = [
            NamedSharding(m, default_layout().replicated())
            for m in self._stage_meshes]
        # expose placements so the stateful PipelineLayer.forward can hop
        self._layers._stage_shardings = [
            self._chunk_sharding(c) for c in range(self.num_chunks)]
        self._layers._engine_fetch = self._fetch_chunk_params

    def _chunk_mesh_idx(self, chunk: int) -> int:
        return chunk % self.num_stages

    def _chunk_sharding(self, chunk: int):
        return self._stage_shardings[self._chunk_mesh_idx(chunk)]

    # -- param bookkeeping ---------------------------------------------------
    def _collect_chunk_params(self):
        """Canonical (dedup'd) param names used by each chunk; shared layers
        (tied embeddings) appear in every chunk that runs them and their
        grads are summed at write-back — the single-controller equivalent of
        allreduce_shared_weight_gradients over the pp group."""
        pipe_params = dict(self._layers.named_parameters())
        self._param_objs = pipe_params
        self._chunk_param_names: List[List[str]] = []
        for c in range(self.num_chunks):
            ids = set()
            for lyr in self._layers.stage_layers(c):
                for p in lyr.parameters():
                    ids.add(id(p))
            self._chunk_param_names.append(
                [n for n, p in pipe_params.items() if id(p) in ids])

    def _place_params(self):
        """Params (and buffers) of chunk c live on stage sub-mesh c % p.
        Shared params stay on the first chunk that owns them. Params that
        are already partitioned (TP/FSDP layouts) are never silently
        re-replicated: they must already sit inside their stage's sub-mesh."""
        placed = set()
        for c in range(self.num_chunks):
            sh = self._chunk_sharding(c)
            stage_ids = {d.id for d in sh.mesh.devices.flat}
            for n in self._chunk_param_names[c]:
                p = self._param_objs[n]
                if id(p) in placed:
                    continue
                placed.add(id(p))
                psh = getattr(p._data, "sharding", None)
                if psh is not None and not psh.is_fully_replicated:
                    have = {d.id for d in psh.device_set}
                    if not have <= stage_ids:
                        raise NotImplementedError(
                            f"param '{n}' is partitioned over devices "
                            f"{sorted(have)} but its pipeline stage owns "
                            f"{sorted(stage_ids)}; shard TP/FSDP params "
                            "inside the stage sub-mesh before wrapping in "
                            "PipelineParallel")
                    continue  # keep the existing partitioned layout
                p._data = jax.device_put(p._data, sh)
            for lyr in self._layers.stage_layers(c):
                for _, b in lyr.named_buffers():
                    if b is not None and id(b) not in placed:
                        placed.add(id(b))
                        b._data = jax.device_put(b._data, sh)

    def _fetch_chunk_params(self, c: int) -> Dict[str, jnp.ndarray]:
        """Current param arrays for chunk c, transferred to its sub-mesh if
        the canonical copy lives elsewhere (shared/tied weights). Params
        already on the stage's device set (incl. TP/FSDP layouts) pass
        through untouched."""
        sh = self._chunk_sharding(c)
        stage_ids = {d.id for d in sh.mesh.devices.flat}
        out = {}
        for n in self._chunk_param_names[c]:
            arr = self._param_objs[n]._data
            psh = getattr(arr, "sharding", None)
            if psh is None or {d.id for d in psh.device_set} != stage_ids:
                arr = jax.device_put(arr, sh)
            out[n] = arr
        return out

    # -- per-chunk programs ---------------------------------------------------
    def _chunk_f(self, c: int):
        pipe = self._layers

        def f(params, x, key):
            with _random.key_context(key):
                with _swapped_state(pipe, params), tape_paused():
                    out = pipe.forward_stage(Tensor(x), c)
            return out._data
        return f

    def _loss_f(self, c: int):
        pipe = self._layers
        f = self._chunk_f(c)

        def floss(params, x, label, key):
            out = f(params, x, key)
            with _swapped_state(pipe, params), tape_paused():
                loss = pipe._loss_fn(Tensor(out), Tensor(label))
            return loss._data
        return floss

    def _program(self, c: int, kind: str):
        key = (c, kind, self._layers.training)
        prog = self._programs.get(key)
        if prog is not None:
            return prog
        f = self._chunk_f(c)
        last = c == self.num_chunks - 1
        if kind == "fwd":
            prog = jax.jit(f)
        elif kind == "loss_fwd":
            prog = jax.jit(self._loss_f(c))
        elif kind == "bwd":
            assert not last

            def bwd(params, x, key, g):
                _, vjp = jax.vjp(lambda p, xx: f(p, xx, key), params, x)
                return vjp(g)  # (dparams, dx)
            prog = jax.jit(bwd)
        elif kind == "loss_bwd":
            floss = self._loss_f(c)

            def loss_bwd(params, x, label, key, gscale):
                loss, vjp = jax.vjp(
                    lambda p, xx: floss(p, xx, label, key), params, x)
                # cotangent = gscale: grads of the scaled loss, one forward
                dparams, dx = vjp(gscale.astype(loss.dtype))
                return loss, dparams, dx
            prog = jax.jit(loss_bwd)
        else:
            raise ValueError(kind)
        prog = _CountingProgram(prog, self)
        self._programs[key] = prog
        return prog

    # -- API parity --------------------------------------------------------
    def train(self):
        self.training = True
        self._layers.train()
        return self

    def eval(self):
        self.training = False
        self._layers.eval()
        return self

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def __call__(self, x):
        return self._layers(x)

    def forward(self, x):
        return self._layers(x)

    # -- schedule ----------------------------------------------------------
    def _split_micro(self, data):
        x, y = data
        n = self.accumulate_steps
        xa, ya = _unwrap(x), _unwrap(y)
        bs = xa.shape[0]
        assert bs % n == 0, f"batch {bs} not divisible by accumulate_steps {n}"
        mb = bs // n
        return [(xa[i * mb:(i + 1) * mb], ya[i * mb:(i + 1) * mb])
                for i in range(n)]

    def _next_batch_key(self):
        """Per-batch dropout key derived from the CURRENT global seed (so
        paddle.seed() after engine construction takes effect, like the
        non-pipeline path) and a per-batch counter (eval advances it too)."""
        seed = getattr(_random.default_generator, "_seed", 0)
        k = jax.random.fold_in(jax.random.key(seed), self._batch_count)
        self._batch_count += 1
        return k

    @staticmethod
    def _schedule_queue(vs: int, n_vstages: int, m: int) -> deque:
        """The per-(virtual-)stage action order; subclasses override to
        change the schedule. Default is 1F1B (reference
        pipeline_parallel.py:440): warmup forwards, steady F/B alternation,
        cooldown backwards — stage s never stashes more than min(P-s, m)
        microbatch inputs."""
        warmup = min(n_vstages - 1 - vs, m)
        q = [("F", i) for i in range(warmup)]
        for k in range(m - warmup):
            q.append(("F", warmup + k))
            q.append(("B", k))
        q.extend(("B", k) for k in range(m - warmup, m))
        return deque(q)

    def _transfer(self, arr, chunk: int):
        """Activation / activation-grad hop onto ``chunk``'s sub-mesh — the
        p2p edge of the pipeline (reference p2p_communication.py:313).
        With ``batch_axis`` the microbatch rows shard over that stage axis
        (dp within the stage); otherwise activations replicate."""
        from jax.sharding import NamedSharding

        from paddle_tpu.distributed.spec_layout import SpecLayout
        mesh = self._stage_meshes[self._chunk_mesh_idx(chunk)]
        ba = self._batch_axis
        if (ba is not None and getattr(arr, "ndim", 0) >= 1
                and arr.shape[0] % self._stage_mesh_axes[ba] == 0):
            sh = NamedSharding(
                mesh, SpecLayout(data_axis=ba).batch(arr.ndim))
        else:
            sh = self._chunk_sharding(chunk)
        if getattr(arr, "sharding", None) == sh:
            return arr
        return jax.device_put(arr, sh)

    def forward_backward_pipeline(self, data, scaler=None):
        if self._layers._loss_fn is None:
            raise ValueError(
                "training through the pipeline engine requires the "
                "PipelineLayer to be built with loss_fn (the last stage "
                "computes the loss; reference pp_layers.py:237)")
        micro = self._split_micro(data)
        m = len(micro)
        nv = self.num_chunks
        batch_key = self._next_batch_key()
        gscale = 1.0 / m
        # only pre-scale grads when the scaler will actually unscale them in
        # step(); bf16/amp-off is a passthrough (GradScaler._passthrough)
        if scaler is not None and not scaler._passthrough():
            gscale = gscale * float(scaler._scale)

        chunk_params = [self._fetch_chunk_params(c) for c in range(nv)]
        acts = {(0, i): self._transfer(mx, 0) for i, (mx, _) in enumerate(micro)}
        labels = [self._transfer(my, nv - 1) for _, my in micro]
        gout: Dict = {}
        stash: List[Dict] = [dict() for _ in range(nv)]
        grad_acc: List[Dict[str, jnp.ndarray]] = [dict() for _ in range(nv)]
        queues = [self._schedule_queue(vs, nv, m) for vs in range(nv)]
        self._peak_stash = [0] * nv
        losses = []

        def mbkey(vs, i):
            return jax.random.fold_in(batch_key, vs * m + i)

        remaining = sum(len(q) for q in queues)
        while remaining:
            progressed = False
            for vs in range(nv):
                if not queues[vs]:
                    continue
                kind, i = queues[vs][0]
                last = vs == nv - 1
                if kind == "F":
                    if (vs, i) not in acts:
                        continue
                    x = acts.pop((vs, i))
                    if not last:
                        y = self._program(vs, "fwd")(
                            chunk_params[vs], x, mbkey(vs, i))
                        acts[(vs + 1, i)] = self._transfer(y, vs + 1)
                    # the last chunk only stashes here: its B (which 1F1B
                    # runs immediately after) computes loss AND grads in one
                    # forward via the loss_bwd program
                    stash[vs][i] = x
                    self._peak_stash[vs] = max(self._peak_stash[vs],
                                               len(stash[vs]))
                else:  # B
                    if last:
                        x = stash[vs].pop(i)
                        loss, dparams, dx = self._program(vs, "loss_bwd")(
                            chunk_params[vs], x, labels[i], mbkey(vs, i),
                            jnp.float32(gscale))
                        losses.append(loss)
                    else:
                        if (vs, i) not in gout:
                            continue
                        g = gout.pop((vs, i))
                        x = stash[vs].pop(i)
                        dparams, dx = self._program(vs, "bwd")(
                            chunk_params[vs], x, mbkey(vs, i), g)
                    for n, d in dparams.items():
                        acc = grad_acc[vs].get(n)
                        grad_acc[vs][n] = d if acc is None else acc + d
                    if vs > 0:
                        gout[(vs - 1, i)] = self._transfer(dx, vs - 1)
                queues[vs].popleft()
                remaining -= 1
                progressed = True
            if not progressed:
                raise RuntimeError(
                    "pipeline schedule deadlock: no stage can make progress "
                    f"(queues={[list(q)[:2] for q in queues]})")

        self._write_back_grads(grad_acc)
        total = losses[0]
        for l in losses[1:]:
            total = total + l
        return Tensor(total / m)

    def _write_back_grads(self, grad_acc):
        """Accumulate functional grads into the stateful ``.grad`` slots the
        optimizer consumes; shared-weight contributions from different
        chunks are moved to the canonical copy's sub-mesh and summed."""
        for vs, accs in enumerate(grad_acc):
            for n, g in accs.items():
                p = self._param_objs[n]
                sh = getattr(p._data, "sharding", None)
                if sh is not None and getattr(g, "sharding", None) != sh:
                    g = jax.device_put(g, sh)
                if p.grad is None:
                    p.grad = Tensor(g)
                else:
                    p.grad = Tensor(p.grad._data + g)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Parity: PipelineParallel.train_batch (pipeline_parallel.py:657)."""
        assert self.training, "call train() before train_batch"
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        micro = self._split_micro(data)
        nv = self.num_chunks
        chunk_params = [self._fetch_chunk_params(c) for c in range(nv)]
        batch_key = self._next_batch_key()
        total = None
        for i, (mx, my) in enumerate(micro):
            x = self._transfer(mx, 0)
            for vs in range(nv - 1):
                x = self._transfer(
                    self._program(vs, "fwd")(
                        chunk_params[vs], x,
                        jax.random.fold_in(batch_key, vs * len(micro) + i)),
                    vs + 1)
            lastk = jax.random.fold_in(batch_key, (nv - 1) * len(micro) + i)
            if compute_loss and self._layers._loss_fn is not None:
                out = self._program(nv - 1, "loss_fwd")(
                    chunk_params[nv - 1], x, self._transfer(my, nv - 1),
                    lastk)
            else:
                out = self._program(nv - 1, "fwd")(
                    chunk_params[nv - 1], x, lastk)
            total = out if total is None else total + out
        return Tensor(total / len(micro))


class PipelineParallelWithInterleave(PipelineParallel):
    """Interleaved virtual-pipeline schedule (reference
    pipeline_parallel.py:906): the layer list is cut into
    ``num_stages * num_virtual_stages`` chunks and chunk g is placed on
    stage sub-mesh g % num_stages, so each physical stage alternates
    between its model chunks — the bubble-shrinking property of the
    interleaved schedule under async dispatch. Construct the
    ``PipelineLayer`` with ``num_virtual_pipeline_stages`` to match."""

    def __init__(self, layers, hcg=None, strategy=None,
                 num_virtual_stages=None, devices=None):
        if num_virtual_stages is not None and \
                layers.get_num_chunks() != \
                layers.get_num_stages() * num_virtual_stages:
            raise ValueError(
                f"PipelineLayer was built with "
                f"{layers.get_num_chunks() // layers.get_num_stages()} "
                f"virtual stages, engine asked for {num_virtual_stages}")
        super().__init__(layers, hcg, strategy, devices=devices)
        self.num_virtual_stages = (
            num_virtual_stages
            or layers.get_num_chunks() // layers.get_num_stages())


class PipelineParallelWithInterleaveFthenB(PipelineParallelWithInterleave):
    """F-then-B interleaved schedule (reference pipeline_parallel.py:1489):
    every microbatch's forward completes before any backward starts, with
    backwards draining in reverse virtual-chunk order (the reference's
    ``_get_virtual_pp_rank(..., forward=False)`` reversal falls out of the
    dependency order here). Peak activation memory is the full ``m``
    stashes per stage — the trade the reference makes for a schedule
    whose collective-overlap windows are contiguous."""

    @staticmethod
    def _schedule_queue(vs: int, n_vstages: int, m: int) -> deque:
        return deque([("F", i) for i in range(m)]
                     + [("B", i) for i in range(m)])
