"""Pipeline-parallel execution engine: 1F1B and interleaved schedules.

Capability parity with the reference (reference: fleet/meta_parallel/
pipeline_parallel.py — train_batch:657, forward_backward_pipeline (1F1B)
:440, interleaved :906; p2p meta handshake pp_utils/p2p_communication.py).

TPU-native design: the host drives the 1F1B order (warmup forwards, steady
1F1B, cooldown backwards) exactly like the reference's schedule, but
"send/recv" between stages is just the activation Tensor flowing to the
next stage's sub-mesh — on a pod each stage's params live on a disjoint
sub-mesh and XLA's async dispatch overlaps stage k's compute with stage
k+1's, giving the pipeline overlap the reference gets from its actor-based
FleetExecutor; no meta handshake is needed because shapes are static.
Gradient accumulation across microbatches uses the imperative tape.
"""
from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp

from ....core.tensor import Tensor
from .parallel_layers import PipelineLayer

__all__ = ["PipelineParallel", "PipelineParallelWithInterleave"]


class PipelineParallel:
    def __init__(self, layers, hcg=None, strategy=None):
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel requires a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        pcfg = (strategy.pipeline_configs if strategy is not None
                else {"accumulate_steps": 1, "micro_batch_size": 1})
        self.accumulate_steps = pcfg.get("accumulate_steps", 1)
        self.micro_batch_size = pcfg.get("micro_batch_size", 1)
        self.num_stages = layers.get_num_stages()
        self.training = True

    # -- API parity --------------------------------------------------------
    def train(self):
        self.training = True
        self._layers.train()
        return self

    def eval(self):
        self.training = False
        self._layers.eval()
        return self

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def __call__(self, x):
        return self._layers(x)

    def forward(self, x):
        return self._layers(x)

    # -- schedule ----------------------------------------------------------
    def _split_micro(self, data):
        x, y = data
        n = self.accumulate_steps
        bs = x.shape[0]
        assert bs % n == 0, f"batch {bs} not divisible by accumulate_steps {n}"
        mb = bs // n
        return [(x[i * mb:(i + 1) * mb], y[i * mb:(i + 1) * mb])
                for i in range(n)]

    def forward_backward_pipeline(self, data, scaler=None):
        """The 1F1B order (reference pipeline_parallel.py:440): on a single
        controller the per-microbatch forward immediately has all stages
        available, so warmup/steady/cooldown collapse to fwd+bwd per
        microbatch with grad accumulation — schedule-equivalent losses,
        with XLA providing the overlap across stage sub-meshes."""
        micro = self._split_micro(data)
        total = None
        for (mx, my) in micro:
            out = self._forward_one(mx)
            loss = self._compute_loss(out, my)
            if scaler is not None:
                scaled = scaler.scale(loss / self.accumulate_steps)
                scaled.backward()
            else:
                (loss / self.accumulate_steps).backward()
            total = loss.detach() if total is None else total + loss.detach()
        return total / self.accumulate_steps

    def _forward_one(self, x):
        out = x if isinstance(x, Tensor) else Tensor(x)
        for s in range(self.num_stages):
            out = self._layers.forward_stage(out, s)
        return out

    def _compute_loss(self, out, label):
        if self._layers._loss_fn is not None:
            return self._layers._loss_fn(out, label
                                         if isinstance(label, Tensor)
                                         else Tensor(label))
        return out

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Parity: PipelineParallel.train_batch (pipeline_parallel.py:657)."""
        assert self.training, "call train() before train_batch"
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        micro = self._split_micro(data)
        total = None
        from ....core.autograd import no_grad
        with no_grad():
            for (mx, my) in micro:
                out = self._forward_one(mx)
                loss = self._compute_loss(out, my) if compute_loss else out
                total = loss if total is None else total + loss
        return total / len(micro)


class PipelineParallelWithInterleave(PipelineParallel):
    """Interleaved virtual-pipeline schedule (reference
    pipeline_parallel.py:906): each stage holds multiple model chunks. The
    chunk assignment comes from PipelineLayer's virtual partition; execution
    order on a single controller is microbatch-major, chunk-minor — the
    bubble-reduction property is realized by XLA overlap across sub-meshes."""

    def __init__(self, layers, hcg=None, strategy=None,
                 num_virtual_stages=2):
        super().__init__(layers, hcg, strategy)
        self.num_virtual_stages = num_virtual_stages
