"""Pipeline layer partitioning + MP RNG tracker.

Capability parity with the reference (reference: fleet/meta_parallel/
parallel_layers/pp_layers.py — LayerDesc:56, SharedLayerDesc:76,
PipelineLayer:237; random.py RNGStatesTracker).
"""
from __future__ import annotations

import re
from typing import Callable, List, Optional, Sequence, Union

from ....core.random import RNGStatesTracker, get_rng_state_tracker  # noqa: F401
from ....nn.layer.layers import Layer

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer",
           "RNGStatesTracker", "get_rng_state_tracker"]


class LayerDesc:
    """Lazy layer spec so stages only build their own layers
    (parity: pp_layers.py:56)."""

    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_cls, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_cls(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """Layer shared between stages — e.g. tied embeddings
    (parity: pp_layers.py:76). Single-controller SPMD note: the shared
    parameter is one global array, so the reference's
    allreduce_shared_weight_gradients over the pp group is automatic."""

    def __init__(self, key, layer_cls, *inputs, forward_func=None,
                 shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Partition N layer descs into M stages (parity: pp_layers.py
    SegmentLayers): uniform by count, or by named-layer boundaries
    (seg_method='layer:DecoderLayer')."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.descs = layers_desc
        self.num_parts = num_parts
        self.method = method

    def do_segment(self) -> List[int]:
        n = len(self.descs)
        if self.method.startswith("layer:"):
            name = self.method.split(":", 1)[1]
            marks = [i for i, d in enumerate(self.descs)
                     if self._name_of(d) == name]
            if len(marks) >= self.num_parts:
                # distribute marked layers evenly across stages
                per = len(marks) // self.num_parts
                rem = len(marks) % self.num_parts
                bounds = [0]
                idx = 0
                for s in range(self.num_parts):
                    take = per + (1 if s < rem else 0)
                    idx += take
                    bounds.append(marks[idx - 1] + 1 if idx > 0 else 0)
                bounds[-1] = n
                return bounds
        per = n // self.num_parts
        rem = n % self.num_parts
        bounds = [0]
        for s in range(self.num_parts):
            bounds.append(bounds[-1] + per + (1 if s < rem else 0))
        return bounds

    @staticmethod
    def _name_of(d):
        if isinstance(d, LayerDesc):
            return d.layer_cls.__name__
        return type(d).__name__


class PipelineLayer(Layer):
    """A model defined as a flat layer list partitioned into pipeline stages
    (parity: pp_layers.py:237). Single-controller SPMD holds every stage
    (each on its own sub-mesh on a pod); ``forward`` runs them in order, and
    the PipelineParallel engine drives the microbatch schedule."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 num_virtual_pipeline_stages=None, **kwargs):
        super().__init__()
        self._loss_fn = loss_fn
        self._num_stages = num_stages or (
            topology.get_dim("pipe") if topology else 1)
        self._num_virtual = num_virtual_pipeline_stages or 1
        self._recompute_interval = recompute_interval
        self.descs = list(layers)
        # with virtual pipeline stages the layer list is cut into
        # num_stages*v chunks; chunk g runs on physical stage g % num_stages
        # as its (g // num_stages)-th model chunk (reference pp_layers.py:237
        # _construct_shared_comm / virtual partition)
        bounds = SegmentLayers(self.descs,
                               self._num_stages * self._num_virtual,
                               seg_method).do_segment()
        self.segment_parts = bounds
        self._shared = {}
        from ....nn.layer.container import LayerList
        built = []
        for d in self.descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name not in self._shared:
                    self._shared[d.layer_name] = (d.build_layer(), d)
                built.append(self._shared[d.layer_name][0])
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            elif callable(d):
                built.append(_FnLayer(d))
            else:
                raise TypeError(f"bad pipeline item {d!r}")
        self.run_function = LayerList(built)
        n_parts = self._num_stages * self._num_virtual
        self._stage_layer_ranges = [
            (bounds[i], bounds[i + 1]) for i in range(n_parts)]
        # set by the PipelineParallel engine: per-chunk NamedSharding so the
        # stateful forward() can hop activations between stage sub-meshes
        self._stage_shardings = None

    def get_num_stages(self):
        return self._num_stages

    def get_num_chunks(self):
        """Total virtual chunks (= num_stages when not interleaved)."""
        return self._num_stages * self._num_virtual

    def stage_layers(self, stage_id: int):
        lo, hi = self._stage_layer_ranges[stage_id]
        return [self.run_function[i] for i in range(lo, hi)]

    def forward_stage(self, x, stage_id: int):
        """Run one chunk (used by the 1F1B engine). Items that are
        SharedLayerDesc with a forward_func use it (tied-embedding heads)."""
        lo, hi = self._stage_layer_ranges[stage_id]
        for i in range(lo, hi):
            layer = self.run_function[i]
            desc = self.descs[i]
            if isinstance(desc, SharedLayerDesc) and desc.forward_func is not None:
                x = desc.forward_func(layer, x)
            else:
                x = layer(x)
        return x

    def forward(self, x):
        fetch = getattr(self, "_engine_fetch", None)
        for s in range(self.get_num_chunks()):
            x = self._hop(x, s)
            if fetch is None:
                x = self.forward_stage(x, s)
            else:
                # engine attached: chunk params (incl. shared/tied weights
                # whose canonical copy lives on another sub-mesh) are
                # fetched onto this chunk's sub-mesh before running
                from ....nn.layer.layers import _swapped_state
                with _swapped_state(self, fetch(s)):
                    x = self.forward_stage(x, s)
        return x

    def _hop(self, x, chunk: int):
        """Eager cross-sub-mesh activation transfer for the stateful
        ``forward`` path once the engine has placed chunk params on
        disjoint sub-meshes (committed arrays on different devices cannot
        meet in one eager op)."""
        if not self._stage_shardings:
            return x
        import jax

        from ....core.tensor import Tensor
        sh = self._stage_shardings[chunk]
        arr = x._data if isinstance(x, Tensor) else x
        if getattr(arr, "sharding", None) == sh:
            return x
        moved = jax.device_put(arr, sh)
        return Tensor(moved) if isinstance(x, Tensor) else moved


class _FnLayer(Layer):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, x):
        return self._fn(x)
