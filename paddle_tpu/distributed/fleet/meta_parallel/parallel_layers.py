"""Pipeline layer partitioning + MP RNG tracker.

Capability parity with the reference (reference: fleet/meta_parallel/
parallel_layers/pp_layers.py — LayerDesc:56, SharedLayerDesc:76,
PipelineLayer:237; random.py RNGStatesTracker).
"""
from __future__ import annotations

import re
from typing import Callable, List, Optional, Sequence, Union

from ....core.random import RNGStatesTracker, get_rng_state_tracker  # noqa: F401
from ....nn.layer.layers import Layer

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer",
           "RNGStatesTracker", "get_rng_state_tracker"]


class LayerDesc:
    """Lazy layer spec so stages only build their own layers
    (parity: pp_layers.py:56)."""

    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_cls, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_cls(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """Layer shared between stages — e.g. tied embeddings
    (parity: pp_layers.py:76). Single-controller SPMD note: the shared
    parameter is one global array, so the reference's
    allreduce_shared_weight_gradients over the pp group is automatic."""

    def __init__(self, key, layer_cls, *inputs, forward_func=None,
                 shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Partition N layer descs into M stages (parity: pp_layers.py
    SegmentLayers): uniform by count, by named-layer boundaries
    (seg_method='layer:DecoderLayer'), an explicit bounds list
    (reference pp_layers.py:112), or 'auto' — the stage-split PLANNER
    (VERDICT r3 missing #1): stages balanced by per-layer parameter
    counts (the proxy for both stage memory and stage compute) via an
    optimal contiguous-partition DP, so a model with a fat embedding or
    LM head gets real balance instead of an equal layer count."""

    def __init__(self, layers_desc, num_parts, method="uniform",
                 built_layers=None):
        self.descs = layers_desc
        self.num_parts = num_parts
        self.method = method
        self.built = built_layers

    def do_segment(self) -> List[int]:
        n = len(self.descs)
        if isinstance(self.method, list):
            bounds = list(self.method)
            assert bounds[0] == 0, "seg_method[0] should be 0"
            for a, b in zip(bounds, bounds[1:]):
                assert a <= b, f"seg_method must be nondecreasing: {bounds}"
            if len(bounds) == self.num_parts:
                bounds.append(n)
            assert len(bounds) == self.num_parts + 1, (
                f"seg_method list of {len(bounds)} bounds cannot cut "
                f"{self.num_parts} stages")
            assert bounds[-1] == n, \
                f"seg_method must end at {n}: {bounds}"
            assert all(0 <= b <= n for b in bounds), (
                f"seg_method bounds must lie in [0, {n}]: {bounds}")
            return bounds
        if self.method in ("auto", "param"):
            return self._balanced_bounds(self._param_weights())
        if self.method.startswith("layer:"):
            name = self.method.split(":", 1)[1]
            marks = [i for i, d in enumerate(self.descs)
                     if self._name_of(d) == name]
            if len(marks) >= self.num_parts:
                # distribute marked layers evenly across stages
                per = len(marks) // self.num_parts
                rem = len(marks) % self.num_parts
                bounds = [0]
                idx = 0
                for s in range(self.num_parts):
                    take = per + (1 if s < rem else 0)
                    idx += take
                    bounds.append(marks[idx - 1] + 1 if idx > 0 else 0)
                bounds[-1] = n
                return bounds
        per = n // self.num_parts
        rem = n % self.num_parts
        bounds = [0]
        for s in range(self.num_parts):
            bounds.append(bounds[-1] + per + (1 if s < rem else 0))
        return bounds

    @staticmethod
    def _name_of(d):
        if isinstance(d, LayerDesc):
            return d.layer_cls.__name__
        return type(d).__name__

    def _param_weights(self) -> List[int]:
        """Per-desc weights for 'auto': parameter counts of the built
        layers (floor 1 so paramless fn-layers still occupy a slot).
        Shared (tied) layers count once — their later occurrences reuse
        the same weights-living-on-the-first-stage object."""
        assert self.built is not None and len(self.built) == len(self.descs)
        import numpy as _np
        seen = set()
        ws = []
        for lyr in self.built:
            if id(lyr) in seen:
                ws.append(1)
                continue
            seen.add(id(lyr))
            params = list(lyr.parameters()) if hasattr(lyr, "parameters") \
                else []
            ws.append(max(1, sum(int(_np.prod(p.shape)) for p in params)))
        return ws

    def _balanced_bounds(self, w: List[int]) -> List[int]:
        """Optimal contiguous partition of weights ``w`` into num_parts
        stages minimizing the max stage weight (O(n^2 k) DP — n is a
        layer count, tiny)."""
        n, k = len(w), self.num_parts
        assert n >= k, f"{n} layers cannot fill {k} stages"
        pre = [0]
        for x in w:
            pre.append(pre[-1] + x)

        INF = float("inf")
        # dp[j][i]: min possible max-stage-weight splitting w[:i] into j
        dp = [[INF] * (n + 1) for _ in range(k + 1)]
        cut = [[0] * (n + 1) for _ in range(k + 1)]
        dp[0][0] = 0.0
        for j in range(1, k + 1):
            for i in range(j, n + 1):
                # stage j takes w[t:i]; earlier stages need >= j-1 items
                for t in range(j - 1, i):
                    c = max(dp[j - 1][t], pre[i] - pre[t])
                    if c < dp[j][i]:
                        dp[j][i], cut[j][i] = c, t
        bounds = [n]
        i = n
        for j in range(k, 0, -1):
            i = cut[j][i]
            bounds.append(i)
        return bounds[::-1]


class PipelineLayer(Layer):
    """A model defined as a flat layer list partitioned into pipeline stages
    (parity: pp_layers.py:237). Single-controller SPMD holds every stage
    (each on its own sub-mesh on a pod); ``forward`` runs them in order, and
    the PipelineParallel engine drives the microbatch schedule."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 num_virtual_pipeline_stages=None, **kwargs):
        super().__init__()
        self._loss_fn = loss_fn
        self._num_stages = num_stages or (
            topology.get_dim("pipe") if topology else 1)
        self._num_virtual = num_virtual_pipeline_stages or 1
        self._recompute_interval = recompute_interval
        self.descs = list(layers)
        self._shared = {}
        from ....nn.layer.container import LayerList
        built = []
        for d in self.descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name not in self._shared:
                    self._shared[d.layer_name] = (d.build_layer(), d)
                built.append(self._shared[d.layer_name][0])
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            elif callable(d):
                built.append(_FnLayer(d))
            else:
                raise TypeError(f"bad pipeline item {d!r}")
        self.run_function = LayerList(built)
        # with virtual pipeline stages the layer list is cut into
        # num_stages*v chunks; chunk g runs on physical stage g % num_stages
        # as its (g // num_stages)-th model chunk (reference pp_layers.py:237
        # _construct_shared_comm / virtual partition). Layers are built
        # FIRST so seg_method='auto' can balance stages by real parameter
        # counts (the stage-split planner).
        bounds = SegmentLayers(self.descs,
                               self._num_stages * self._num_virtual,
                               seg_method, built_layers=built).do_segment()
        self.segment_parts = bounds
        n_parts = self._num_stages * self._num_virtual
        self._stage_layer_ranges = [
            (bounds[i], bounds[i + 1]) for i in range(n_parts)]
        # set by the PipelineParallel engine: per-chunk NamedSharding so the
        # stateful forward() can hop activations between stage sub-meshes
        self._stage_shardings = None

    def get_num_stages(self):
        return self._num_stages

    def get_num_chunks(self):
        """Total virtual chunks (= num_stages when not interleaved)."""
        return self._num_stages * self._num_virtual

    def stage_layers(self, stage_id: int):
        lo, hi = self._stage_layer_ranges[stage_id]
        return [self.run_function[i] for i in range(lo, hi)]

    def forward_stage(self, x, stage_id: int):
        """Run one chunk (used by the 1F1B engine). Items that are
        SharedLayerDesc with a forward_func use it (tied-embedding heads)."""
        lo, hi = self._stage_layer_ranges[stage_id]
        for i in range(lo, hi):
            layer = self.run_function[i]
            desc = self.descs[i]
            if isinstance(desc, SharedLayerDesc) and desc.forward_func is not None:
                x = desc.forward_func(layer, x)
            else:
                x = layer(x)
        return x

    def forward(self, x):
        fetch = getattr(self, "_engine_fetch", None)
        for s in range(self.get_num_chunks()):
            x = self._hop(x, s)
            if fetch is None:
                x = self.forward_stage(x, s)
            else:
                # engine attached: chunk params (incl. shared/tied weights
                # whose canonical copy lives on another sub-mesh) are
                # fetched onto this chunk's sub-mesh before running
                from ....nn.layer.layers import _swapped_state
                with _swapped_state(self, fetch(s)):
                    x = self.forward_stage(x, s)
        return x

    def _hop(self, x, chunk: int):
        """Eager cross-sub-mesh activation transfer for the stateful
        ``forward`` path once the engine has placed chunk params on
        disjoint sub-meshes (committed arrays on different devices cannot
        meet in one eager op)."""
        if not self._stage_shardings:
            return x
        import jax

        from ....core.tensor import Tensor
        sh = self._stage_shardings[chunk]
        arr = x._data if isinstance(x, Tensor) else x
        if getattr(arr, "sharding", None) == sh:
            return x
        moved = jax.device_put(arr, sh)
        return Tensor(moved) if isinstance(x, Tensor) else moved


class _FnLayer(Layer):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, x):
        return self._fn(x)
