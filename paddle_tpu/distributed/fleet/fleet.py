"""fleet: the manual hybrid-parallel front end.

Capability parity with the reference (reference: python/paddle/distributed/
fleet/fleet.py:167 init, :603 _init_hybrid_parallel_env; model.py:141-176
distributed_model; DistributedStrategy at
fleet/base/distributed_strategy.py:175).

TPU-native: fleet.init builds the 5-axis hybrid device mesh
[data, pipe, sharding, sep, model] as ONE jax Mesh; distributed_model wraps
by parallel mode (TP layers already carry shardings; PP wraps with the
pipeline engine); distributed_optimizer wraps with HybridParallelOptimizer.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..communication_impl import Group, _set_world_group
from ..parallel import DataParallel, init_parallel_env
from .topology import CommunicateTopology, HybridCommunicateGroup

__all__ = ["DistributedStrategy", "Fleet", "fleet", "init",
           "distributed_model", "distributed_optimizer", "get_hybrid_communicate_group"]


class DistributedStrategy:
    """Hierarchical strategy config (parity: fleet.DistributedStrategy —
    the protobuf-backed config; plain attrs here)."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1,
            "order": ["dp", "pp", "sharding", "sep", "mp"],
        }
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1}
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1}
        # long-context attention strategy over the sep axis (SURVEY §5.7):
        # "ring" (flash kernel per ring step), "ulysses" (all_to_all head
        # swap), or "gather" (replicate sequence, local kernel — the
        # reference's only mode, segment_parallel.py)
        self.sep_configs = {"attention": "ring"}
        self.tensor_parallel_configs = {}
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32

    def __repr__(self):
        return f"DistributedStrategy(hybrid={self.hybrid_configs})"


class ParallelMode:
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4


class Fleet:
    def __init__(self):
        self._strategy: Optional[DistributedStrategy] = None
        self._hcg: Optional[HybridCommunicateGroup] = None
        self._topology: Optional[CommunicateTopology] = None
        self._is_initialized = False

    # -- init --------------------------------------------------------------
    def init(self, role_maker=None, is_collective=True, strategy=None,
             log_level="INFO"):
        import jax
        init_parallel_env()
        self._strategy = strategy or DistributedStrategy()
        hc = self._strategy.hybrid_configs
        dp, mp = hc.get("dp_degree", 1), hc.get("mp_degree", 1)
        pp = hc.get("pp_degree", 1)
        shd = hc.get("sharding_degree", 1)
        sep = hc.get("sep_degree", 1)
        total = dp * mp * pp * shd * sep
        ndev = jax.device_count()
        if total == 1:
            dp = ndev  # pure DP over all devices by default
            total = ndev
        if total != ndev:
            # allow smaller logical topologies on more devices by padding dp
            if ndev % total == 0:
                dp *= ndev // total
                total = ndev
            else:
                raise ValueError(
                    f"hybrid degrees product {total} != device count {ndev}")
        self._topology = CommunicateTopology(
            ["data", "pipe", "sharding", "sep", "model"],
            [dp, pp, shd, sep, mp])
        self._hcg = HybridCommunicateGroup(self._topology)
        self._is_initialized = True
        # seed the model-parallel RNG tracker (reference mpu/random.py)
        from ...core.random import model_parallel_random_seed
        model_parallel_random_seed(seed=int(os.environ.get("FLAGS_seed", "1024")))
        return self

    def is_first_worker(self):
        return True

    def worker_index(self):
        from ..parallel import get_rank
        return get_rank()

    def worker_num(self):
        from ..parallel import get_world_size
        return get_world_size()

    def get_hybrid_communicate_group(self):
        return self._hcg

    @property
    def strategy(self):
        return self._strategy

    # -- wrapping ----------------------------------------------------------
    def distributed_model(self, model):
        """Wrap by parallel mode (parity: fleet/model.py:141-176)."""
        if self._hcg is None:
            self.init()
        hc = self._strategy.hybrid_configs if self._strategy else {}
        # mode order mirrors reference fleet/model.py:141-176:
        # pp > mp > sep > sharding > dp
        if self._hcg.get_pipe_parallel_world_size() > 1:
            from .meta_parallel.pipeline_parallel import PipelineParallel
            return PipelineParallel(model, self._hcg, self._strategy)
        if self._hcg.get_model_parallel_world_size() > 1:
            from .meta_parallel.tensor_parallel import TensorParallel
            return TensorParallel(model, self._hcg, self._strategy)
        if self._hcg.get_sep_parallel_world_size() > 1:
            from .meta_parallel.segment_parallel import SegmentParallel
            return SegmentParallel(model, self._hcg, self._strategy)
        if self._hcg.get_sharding_parallel_world_size() > 1:
            from .meta_parallel.sharding_parallel import ShardingParallel
            return ShardingParallel(model, self._hcg, self._strategy)
        return DataParallel(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        from .meta_optimizers.hybrid_parallel_optimizer import \
            HybridParallelOptimizer
        if self._hcg is None:
            self.init()
        return HybridParallelOptimizer(optimizer, self._hcg,
                                       self._strategy or DistributedStrategy())

    # -- io passthroughs ---------------------------------------------------
    def save_persistables(self, *args, **kwargs):
        pass

    def barrier_worker(self):
        from ..communication_impl import barrier
        barrier()

    def stop_worker(self):
        pass


fleet = Fleet()


def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    return fleet.init(role_maker, is_collective, strategy, log_level)


def distributed_model(model):
    return fleet.distributed_model(model)


def distributed_optimizer(optimizer, strategy=None):
    return fleet.distributed_optimizer(optimizer, strategy)


def get_hybrid_communicate_group():
    return fleet.get_hybrid_communicate_group()
