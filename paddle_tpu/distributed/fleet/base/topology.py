"""Path-faithful module (parity: fleet/base/topology.py)."""
from ..topology import CommunicateTopology, HybridCommunicateGroup  # noqa: F401

__all__ = ["CommunicateTopology", "HybridCommunicateGroup"]
