"""fleet.base namespace (parity: python/paddle/distributed/fleet/base/)."""
from . import topology  # noqa: F401
