"""Fleet datasets — the PS-mode data pipeline (parity:
python/paddle/distributed/fleet/dataset/dataset.py over the C++ MultiSlot
dataset core).

The reference streams text files through a ``pipe_command`` into per-slot
records consumed by downstream trainers. Here the engine is
Python/NumPy: files are piped through the same ``pipe_command`` contract
(a shell command reading the file on stdin, emitting MultiSlot text on
stdout), parsed into per-slot NumPy arrays, and iterated as feed dicts —
the form both the static Executor and the eager PS loop consume.

MultiSlot text format (the reference's MultiSlotDataFeed): each line is
one example; for each slot in ``use_var`` order it carries
``<n> v_1 ... v_n``. int64 slots hold sparse feature ids, float32 slots
hold dense values.
"""
from __future__ import annotations

import subprocess
import threading
from typing import List, Optional

import numpy as np

__all__ = ["DatasetBase", "InMemoryDataset", "QueueDataset",
           "FileInstantDataset", "BoxPSDataset"]


def _parse_multislot_py(text: str, slot_dtypes, path: str = "mem"):
    """Pure-Python MultiSlot parser (fallback when the native build is
    unavailable) — validates exactly like csrc/multislot.cpp so the same
    malformed input raises the same error regardless of toolchain."""
    records = []
    for line_no, line in enumerate(text.splitlines(), 1):
        toks = line.split()
        if not toks:
            continue
        rec, i = [], 0
        for s, dt in enumerate(slot_dtypes):
            if i >= len(toks):
                raise ValueError(
                    f"MultiSlot parse error in {path}: line {line_no}: "
                    f"missing count for slot {s}")
            try:
                n = int(toks[i])
            except ValueError:
                raise ValueError(
                    f"MultiSlot parse error in {path}: line {line_no}: "
                    f"bad count for slot {s}") from None
            if n < 0:
                raise ValueError(
                    f"MultiSlot parse error in {path}: line {line_no}: "
                    f"bad count for slot {s}")
            i += 1
            vals = toks[i:i + n]
            if len(vals) != n:
                raise ValueError(
                    f"MultiSlot parse error in {path}: line {line_no}: "
                    f"slot {s} expects {n} values, got {len(vals)}")
            i += n
            try:
                rec.append(np.asarray(
                    vals, np.float32 if dt == "float32" else np.int64))
            except ValueError:
                raise ValueError(
                    f"MultiSlot parse error in {path}: line {line_no}: "
                    f"bad {'float' if dt == 'float32' else 'int'} in "
                    f"slot {s}") from None
        if i != len(toks):
            raise ValueError(
                f"MultiSlot parse error in {path}: line {line_no}: "
                f"trailing tokens after {len(slot_dtypes)} slots")
        records.append(rec)
    return records


def _parse_multislot(raw: bytes, slot_dtypes, path: str):
    """Parse MultiSlot bytes with the native C++ tokenizer (the reference
    keeps this loop in C++ worker threads, data_feed.cc); falls back to
    Python if the toolchain is unavailable."""
    import ctypes

    try:
        from ...core.native import load_native
        lib = load_native("multislot")
    except Exception:
        return _parse_multislot_py(raw.decode(), slot_dtypes, path)

    class _MSResult(ctypes.Structure):
        _fields_ = [("n_records", ctypes.c_long),
                    ("n_slots", ctypes.c_long),
                    ("lengths", ctypes.POINTER(ctypes.c_long)),
                    ("ivals", ctypes.POINTER(ctypes.c_longlong)),
                    ("fvals", ctypes.POINTER(ctypes.c_float)),
                    ("n_ivals", ctypes.c_long),
                    ("n_fvals", ctypes.c_long),
                    ("err", ctypes.c_char * 256)]

    lib.multislot_parse.restype = ctypes.POINTER(_MSResult)
    lib.multislot_parse.argtypes = [ctypes.c_char_p, ctypes.c_long,
                                    ctypes.c_int,
                                    ctypes.POINTER(ctypes.c_int)]
    lib.multislot_free.argtypes = [ctypes.POINTER(_MSResult)]

    ns = len(slot_dtypes)
    dts = (ctypes.c_int * ns)(*[1 if d == "float32" else 0
                                for d in slot_dtypes])
    res = lib.multislot_parse(raw, len(raw), ns, dts)
    try:
        rr = res.contents
        if rr.n_records < 0:
            raise ValueError(
                f"MultiSlot parse error in {path}: "
                f"{rr.err.decode(errors='replace')}")
        n_rec = int(rr.n_records)
        lens = np.ctypeslib.as_array(rr.lengths,
                                     shape=(n_rec * ns,)).copy() \
            if n_rec else np.zeros((0,), np.int64)
        ipool = np.ctypeslib.as_array(rr.ivals,
                                      shape=(max(int(rr.n_ivals), 1),)
                                      ).copy()[:int(rr.n_ivals)]
        fpool = np.ctypeslib.as_array(rr.fvals,
                                      shape=(max(int(rr.n_fvals), 1),)
                                      ).copy()[:int(rr.n_fvals)]
        records = []
        io = fo = 0
        for rec_i in range(n_rec):
            rec = []
            for s, dt in enumerate(slot_dtypes):
                ln = int(lens[rec_i * ns + s])
                if dt == "float32":
                    rec.append(fpool[fo:fo + ln])
                    fo += ln
                else:   # ipool is already int64 (c_longlong): slice view
                    rec.append(ipool[io:io + ln])
                    io += ln
            records.append(rec)
        return records
    finally:
        lib.multislot_free(res)


class DatasetBase:
    """Common init/filelist plumbing (reference dataset.py:24)."""

    def __init__(self):
        self.proto_desc = {"pipe_command": "cat", "batch_size": 1,
                           "thread_num": 1}
        self.filelist: List[str] = []
        self.use_var: list = []
        self._slot_dtypes: List[str] = []
        self._slot_names: List[str] = []

    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command="cat", input_type=0, fs_name="", fs_ugi="",
             download_cmd="cat", **kwargs):
        self._set_batch_size(batch_size)
        self._set_thread(thread_num)
        self._set_pipe_command(pipe_command)
        if use_var is not None:
            self._set_use_var(use_var)

    def _set_pipe_command(self, pipe_command):
        self.proto_desc["pipe_command"] = pipe_command

    def _set_batch_size(self, batch_size):
        self.proto_desc["batch_size"] = int(batch_size)

    def _set_thread(self, thread_num):
        self.proto_desc["thread_num"] = max(int(thread_num), 1)

    def set_filelist(self, filelist):
        self.filelist = list(filelist)

    def _set_use_var(self, var_list):
        """Slots, in feed order. Accepts static Variables, Tensors, or
        (name, dtype) pairs."""
        self.use_var = list(var_list)
        self._slot_names, self._slot_dtypes = [], []
        for v in self.use_var:
            if isinstance(v, tuple):
                name, dt = v
            else:
                name = getattr(v, "name", str(v))
                dt = str(getattr(v, "dtype", "int64"))
            dt = dt.split(".")[-1]
            self._slot_names.append(name)
            self._slot_dtypes.append("float32" if "float" in dt else "int64")

    # -- engine ------------------------------------------------------------
    def _read_file(self, path: str):
        """Run ``pipe_command`` over one file. With ``use_var`` set, parse
        MultiSlot lines into per-example slot lists; without it, records
        are the raw lines (the line-stream mode downstream DataLoaders
        consume). A filter pipe matching nothing (exit 1, empty output —
        grep's contract) yields zero records; other failures raise."""
        cmd = self.proto_desc["pipe_command"]
        with open(path, "rb") as f:
            r = subprocess.run(cmd, shell=True, stdin=f,
                               capture_output=True)
        if r.returncode != 0 and not (r.returncode == 1 and not r.stdout):
            raise RuntimeError(
                f"pipe_command {cmd!r} failed (exit {r.returncode}) on "
                f"{path}: {r.stderr.decode(errors='replace')[-300:]}")
        if not self._slot_names:
            return [ln for ln in r.stdout.decode().splitlines()
                    if ln.strip()]
        records = _parse_multislot(r.stdout, self._slot_dtypes, path)
        return records

    def _batches_from(self, records):
        if not self._slot_names:   # raw-line mode: yield lines directly
            yield from records
            return
        bs = self.proto_desc["batch_size"]
        if not records:
            import logging
            logging.getLogger(__name__).error(
                "MultiSlotDataset: file yielded ZERO records — the "
                "pipeline will look empty; check parsers/pipe_command")
            return
        tail = len(records) % bs
        if tail:
            # drop-last is the PS trainer contract (fixed batch shapes for
            # the jitted step), but a silent drop made a misconfigured
            # pipeline look empty (advisor r3): log it, loudly when it is
            # EVERYTHING
            import logging
            (logging.getLogger(__name__).warning if len(records) >= bs
             else logging.getLogger(__name__).error)(
                "MultiSlotDataset: dropping %d tail record(s) not filling "
                "a batch of %d (%d record(s) total)%s", tail, bs,
                len(records),
                "" if len(records) >= bs else " — ZERO batches will be "
                "yielded; check batch_size vs file size")
        for lo in range(0, len(records) - bs + 1, bs):
            chunk = records[lo:lo + bs]
            feed = {}
            for si, name in enumerate(self._slot_names):
                rows = [r[si] for r in chunk]
                width = max(len(r) for r in rows)
                dt = rows[0].dtype
                arr = np.zeros((len(rows), width), dt)
                for ri, r in enumerate(rows):
                    arr[ri, :len(r)] = r
                feed[name] = arr
            yield feed

    def _finish_to_run(self):
        pass


class InMemoryDataset(DatasetBase):
    """Load-then-shuffle dataset (reference dataset.py:352)."""

    def __init__(self):
        super().__init__()
        self._memory: list = []
        self._preload_thread: Optional[threading.Thread] = None
        self._epoch_seed = 0

    def init(self, **kwargs):
        super().init(**kwargs)

    def update_settings(self, **kwargs):
        super().init(**kwargs)

    def load_into_memory(self, is_shuffle=False):
        self._memory = []
        for path in self.filelist:
            self._memory.extend(self._read_file(path))
        if is_shuffle:
            self.local_shuffle()

    def preload_into_memory(self, thread_num=None):
        """Async load (reference preload_into_memory/wait_preload_done)."""
        # a second preload while one is in flight would race two loader
        # threads into self._memory and drop the first thread's handle
        # unjoined (the wave-3 GL706/GL80x sweep's leak shape) — finish
        # the outstanding one first
        self.wait_preload_done()
        self._preload_thread = threading.Thread(
            target=self.load_into_memory, daemon=True)
        self._preload_thread.start()

    def wait_preload_done(self):
        if self._preload_thread is not None:
            # graft-lint: disable=GL302 -- this API's contract IS the
            # indefinite wait (reference wait_preload_done blocks until
            # the preload finishes; the loader thread is daemon)
            self._preload_thread.join()
            self._preload_thread = None

    def local_shuffle(self):
        rng = np.random.RandomState(self._epoch_seed)
        self._epoch_seed += 1
        rng.shuffle(self._memory)

    def global_shuffle(self, fleet=None, thread_num=12):
        """Across-trainer shuffle. Single-controller substrate: every rank
        sees the global array store, so a seeded permutation IS the global
        shuffle; with a fleet handle the seed is agreed via its util
        barrier (reference exchanges examples over the PS network)."""
        if fleet is not None and hasattr(fleet, "barrier_worker"):
            fleet.barrier_worker()
        self.local_shuffle()

    def release_memory(self):
        self._memory = []

    def get_memory_data_size(self, fleet=None):
        return len(self._memory)

    def get_shuffle_data_size(self, fleet=None):
        return len(self._memory)

    def __iter__(self):
        return self._batches_from(self._memory)


class QueueDataset(DatasetBase):
    """Streaming dataset: files are parsed on the fly, nothing is
    retained (reference dataset.py:1295)."""

    def __iter__(self):
        for path in self.filelist:
            yield from self._batches_from(self._read_file(path))


class FileInstantDataset(QueueDataset):
    """(reference dataset.py:1340 — QueueDataset variant whose reader
    consumes whole files per instant; same streaming semantics here)"""


class BoxPSDataset(InMemoryDataset):
    """(reference dataset.py:1365 — InMemoryDataset + BoxPS accelerator
    hooks; the pass begin/end hooks are no-ops on this substrate)"""

    def begin_pass(self):
        pass

    def end_pass(self, need_save_delta=False):
        pass

    def wait_feed_pass_done(self):
        pass

    def slots_shuffle(self, slots):
        self.local_shuffle()
