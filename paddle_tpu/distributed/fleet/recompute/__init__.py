"""Activation recomputation (gradient checkpointing).

Capability parity with the reference (reference: fleet/recompute/
recompute.py — RecomputeFunction PyLayer with RNG-state replay :108,
recompute() API :404, recompute_sequential :542, offload variant
recompute_hybrid.py).

TPU-native: on the functional/jit path this is ``jax.checkpoint`` — XLA
rematerializes inside one program (strictly better than the reference's
replay machinery). On the imperative tape path we implement true
recompute-on-backward: forward runs under no_grad saving only inputs +
RNG (seed, offset); backward replays the forward with the restored RNG
state to rebuild the vjp — the reference's RNG-replay contract.
"""
from __future__ import annotations

from typing import Sequence

import jax

from ....core import random as _random
from ....core.autograd import TapeNode, is_tape_active, no_grad, tape_paused
from ....core.tensor import Tensor

__all__ = ["recompute", "recompute_sequential", "checkpoint"]


def _any_traced(args) -> bool:
    for a in args:
        if isinstance(a, Tensor) and isinstance(a._data, jax.core.Tracer):
            return True
    return False


_POLICIES = {
    None: None, "full": None, "nothing_saveable": None,
    # selective remat: save matmul/dot outputs, recompute only cheap
    # elementwise work — ~0 extra matmul FLOPs vs full remat's +1 forward
    # (the fwd FLOPs are ~2/6 of a train step, so full per-layer remat
    # costs ~33% throughput; selective costs ~0 at higher memory).
    # "selective" is an alias of dots_saveable — NOT the
    # no-batch-dims variant, which re-runs every batched matmul
    # (attention BMMs) and forfeits exactly the FLOPs this exists to keep
    "dots_saveable": "dots_saveable",
    "selective": "dots_saveable",
    "dots_with_no_batch_dims_saveable": "dots_with_no_batch_dims_saveable",
    "everything_saveable": "everything_saveable",
}


def _resolve_policy(policy):
    if callable(policy):
        return policy
    if policy not in _POLICIES:
        raise ValueError(
            f"unknown recompute policy {policy!r}; one of "
            f"{sorted(k for k in _POLICIES if isinstance(k, str))}")
    name = _POLICIES[policy]
    return getattr(jax.checkpoint_policies, name) if name else None


def _remat_functional(function, args, kwargs, policy=None):
    """Functional/jit path: route the call through ``jax.checkpoint`` so XLA
    rematerializes the segment's activations on the backward pass. Layer
    parameters are closed-over tracers — they stay residuals (params are
    live for the optimizer anyway); only the explicit activation args bound
    the remat segment. ``policy`` selects WHAT to save (reference
    recompute saves everything-at-boundaries; 'dots_saveable'/'selective'
    keep matmul outputs so the backward re-runs only elementwise work)."""
    tensor_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    arrays = [args[i]._data for i in tensor_idx]
    sg = [args[i].stop_gradient for i in tensor_idx]
    meta = {}

    def pure(*arrs):
        call = list(args)
        for j, i in enumerate(tensor_idx):
            call[i] = Tensor(arrs[j], stop_gradient=sg[j])
        out = function(*call, **kwargs)
        single = not isinstance(out, (tuple, list))
        meta["single"] = single
        outs = (out,) if single else tuple(out)
        meta["is_tensor"] = [isinstance(o, Tensor) for o in outs]
        return tuple(o._data if isinstance(o, Tensor) else o for o in outs)

    pol = _resolve_policy(policy)
    res = (jax.checkpoint(pure, policy=pol) if pol is not None
           else jax.checkpoint(pure))(*arrays)
    outs = [Tensor(r, stop_gradient=False) if t else r
            for r, t in zip(res, meta["is_tensor"])]
    return outs[0] if meta["single"] else tuple(outs)


def recompute(function, *args, **kwargs):
    """paddle.distributed.fleet.utils.recompute parity. ``use_reentrant``
    accepted and ignored (single behavior). ``policy`` (jit path only)
    picks the jax.checkpoint saveable policy; the eager tape path always
    replays the whole segment (the reference behavior)."""
    kwargs.pop("use_reentrant", None)
    preserve_rng = kwargs.pop("preserve_rng_state", True)
    policy = kwargs.pop("policy", None)

    if not is_tape_active():
        if _any_traced(args):
            # under a jit/vjp trace (create_train_step, DistModel, the
            # pipeline chunk programs): real gradient checkpointing
            return _remat_functional(function, args, kwargs, policy)
        # plain eager no-grad call: recompute has nothing to save
        return function(*args, **kwargs)

    # record RNG state so dropout masks replay identically (reference
    # RecomputeFunction: CUDA seed/offset capture; here (seed, offset))
    gen_state = _random.default_generator.peek_state() if preserve_rng else None

    tensor_args = [a for a in args if isinstance(a, Tensor)]
    diff_inputs = [t for t in tensor_args if not t.stop_gradient]

    with no_grad():
        outputs = function(*args, **kwargs)
    single = not isinstance(outputs, (tuple, list))
    out_list = (outputs,) if single else tuple(outputs)

    if not diff_inputs:
        return outputs

    def vjp_fn(cts):
        # replay forward WITH grad tracking on detached inputs
        if gen_state is not None:
            saved = _random.default_generator.peek_state()
            _random.default_generator.set_state(gen_state)
        try:
            detached = []
            mapping = {}
            for a in args:
                if isinstance(a, Tensor) and not a.stop_gradient:
                    d = Tensor(a._data, stop_gradient=False)
                    mapping[id(a)] = d
                    detached.append(d)
                elif isinstance(a, Tensor):
                    detached.append(a.detach())
                else:
                    detached.append(a)
            replay = function(*detached, **kwargs)
            rlist = (replay,) if not isinstance(replay, (tuple, list)) \
                else tuple(replay)
            from ....core.autograd import _run_backward
            targets = [mapping[id(t)] for t in diff_inputs]
            # accumulate_leaf=True: parameters captured by the function get
            # their grads accumulated here (reference RecomputeFunction's
            # backward does the same via its replayed graph)
            tg = _run_backward(list(rlist),
                               [Tensor(c, stop_gradient=True) for c in cts],
                               retain_graph=False, targets=targets,
                               accumulate_leaf=True)
            return tuple(tg.get(id(t), None) if tg.get(id(t)) is None
                         else tg[id(t)]._data
                         if isinstance(tg.get(id(t)), Tensor) else tg[id(t)]
                         for t in targets)
        finally:
            if gen_state is not None:
                _random.default_generator.set_state(saved)

    node = TapeNode("recompute", diff_inputs, vjp_fn,
                    [jax.ShapeDtypeStruct(o._data.shape, o._data.dtype)
                     for o in out_list])
    wrapped = []
    for i, o in enumerate(out_list):
        t = Tensor(o._data, stop_gradient=False)
        t._node = node
        t._out_idx = i
        wrapped.append(t)
    return wrapped[0] if single else tuple(wrapped)


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Recompute a Sequential in segments (reference :542)."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    layers = list(functions)
    n = len(layers)
    per = max(n // segments, 1)
    out = args[0]
    i = 0
    while i < n:
        chunk = layers[i:i + per]

        def seg(x, _chunk=chunk):
            for l in _chunk:
                x = l(x)
            return x
        out = recompute(seg, out)
        i += per
    return out


def checkpoint(function):
    """Functional-path decorator: jax.checkpoint for jitted training
    (XLA remat — the TPU answer to recompute_hybrid offload)."""
    return jax.checkpoint(function)
