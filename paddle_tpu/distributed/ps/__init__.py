"""TPU-native parameter-server mode.

The reference's PS stack (paddle/fluid/distributed/ps/ ~55k LoC C++
over brpc + python/paddle/distributed/ps/) is a parallel L4-L6 universe
for sparse models whose embedding tables exceed device memory. This
package is its TPU-native analog at the same capability points:

- host-RAM sparse tables with server-side optimizer accessors
  (SGD/Adagrad/Adam/CTR admission+eviction)   -> table.py, accessor.py
- a sharded TCP service + shard-routing client -> service.py, client.py
- sync / async(merge-queue) / geo-SGD(delta) communicators -> client.py
- role runtime + SparseEmbedding pull/push layer -> runtime.py

Design departure, on purpose: the reference splits dense math per-rank
around the PS; here the dense model is one jitted XLA program on the
TPU mesh and only the sparse edge crosses to the host — the same
boundary its heter-PS (GPU-cache) variant draws.
"""
from .accessor import (AdagradAccessor, AdamAccessor, CtrAccessor,
                       SGDAccessor, make_accessor)
from .client import Communicator, PSClient, PSError
from .runtime import (PSRuntime, SparseEmbedding, init_server, init_worker,
                      run_server, stop_worker)
from .service import PSServer
from .table import DenseTable, SparseTable

__all__ = [
    "SGDAccessor", "AdagradAccessor", "AdamAccessor", "CtrAccessor",
    "make_accessor", "SparseTable", "DenseTable", "PSServer", "PSClient",
    "PSError", "Communicator", "PSRuntime", "SparseEmbedding",
    "init_server", "run_server", "init_worker", "stop_worker",
]
