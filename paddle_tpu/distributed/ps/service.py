"""Parameter-server RPC service (brpc_ps_server analog).

The reference serves tables over brpc with protobuf request/response
(paddle/fluid/distributed/ps/service/brpc_ps_server.cc). Here the wire
format is a length-framed JSON header plus an ``np.savez`` payload —
no pickle on the wire, arrays deserialize through numpy's format only.
One thread per connection; tables do their own locking, so concurrent
trainers are safe (the reference's server is similarly reentrant per
table shard).
"""
from __future__ import annotations

import io
import json
import socket
import socketserver
import struct
import threading
from typing import Dict

import numpy as np

from .table import DenseTable, SparseTable

__all__ = ["PSServer", "send_msg", "recv_msg"]

_HDR = struct.Struct("!II")  # (json_len, npz_len)


def send_msg(sock: socket.socket, meta: dict, arrays: Dict[str, np.ndarray]
             ) -> None:
    j = json.dumps(meta).encode()
    buf = io.BytesIO()
    if arrays:
        np.savez(buf, **arrays)
    payload = buf.getvalue()
    sock.sendall(_HDR.pack(len(j), len(payload)) + j + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            raise ConnectionError("peer closed")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def recv_msg(sock: socket.socket):
    jlen, plen = _HDR.unpack(_recv_exact(sock, _HDR.size))
    meta = json.loads(_recv_exact(sock, jlen))
    arrays = {}
    if plen:
        data = np.load(io.BytesIO(_recv_exact(sock, plen)),
                       allow_pickle=False)
        arrays = {k: data[k] for k in data.files}
    return meta, arrays


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        srv: "PSServer" = self.server.ps  # type: ignore[attr-defined]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while True:
                meta, arrays = recv_msg(sock)
                out_meta, out_arrays = srv.dispatch(meta, arrays)
                send_msg(sock, out_meta, out_arrays)
                if meta.get("cmd") == "stop":
                    self.server.shutdown()
                    return
        except (ConnectionError, OSError):
            return


class _TCP(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class PSServer:
    """One PS shard: owns its slice of every sparse table plus the dense
    table, and serves pull/push/geo/save/load over TCP."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._tables: Dict[str, SparseTable] = {}
        self._tables_lock = threading.Lock()
        self._dense = DenseTable()
        self._srv = _TCP((host, port), _Handler)
        self._srv.ps = self  # type: ignore[attr-defined]
        self.host, self.port = self._srv.server_address[:2]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        self._thread.start()
        return self

    def stop(self):
        try:
            self._srv.shutdown()
            self._srv.server_close()
        except Exception:
            pass
        # reclaim the serve_forever thread (GL706): shutdown() returns
        # once the serve loop notices, but only the join proves the
        # worker is gone before the owner drops the server
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    # -- dispatch ------------------------------------------------------------
    def _table(self, meta) -> SparseTable:
        name = meta["table"]
        with self._tables_lock:  # check-then-create must be atomic across
            if name not in self._tables:  # concurrent trainer handlers
                from .accessor import make_accessor
                acc = make_accessor(meta.get("accessor", "adagrad"),
                                    **meta.get("accessor_kw", {}))
                self._tables[name] = SparseTable(
                    dim=int(meta["dim"]), accessor=acc,
                    initializer=meta.get("initializer", "normal"),
                    init_scale=float(meta.get("init_scale", 0.01)),
                    seed=int(meta.get("seed", 0)))
            return self._tables[name]

    def dispatch(self, meta: dict, arrays: Dict[str, np.ndarray]):
        try:
            return self._dispatch(meta, arrays)
        except Exception as e:  # noqa: BLE001 — the error must reach the
            # client as a reply, not as a dropped connection
            return {"ok": False,
                    "error": f"{type(e).__name__}: {e}"[:500]}, {}

    def _dispatch(self, meta: dict, arrays: Dict[str, np.ndarray]):
        cmd = meta.get("cmd")
        if cmd == "pull":
            rows = self._table(meta).pull(arrays["ids"])
            return {"ok": True}, {"rows": rows}
        if cmd == "push":
            self._table(meta).push(arrays["ids"], arrays["grads"])
            return {"ok": True}, {}
        if cmd == "push_delta":
            self._table(meta).add_to_rows(arrays["ids"], arrays["deltas"])
            return {"ok": True}, {}
        if cmd == "set_rows":
            self._table(meta).set_rows(arrays["ids"], arrays["rows"])
            return {"ok": True}, {}
        if cmd == "record_shows":
            self._table(meta).record_shows(
                arrays["ids"], arrays.get("shows"), arrays.get("clicks"))
            return {"ok": True}, {}
        if cmd == "shrink":
            with self._tables_lock:
                tables = list(self._tables.values())
            n = sum(t.shrink() for t in tables)
            return {"ok": True, "evicted": n}, {}
        if cmd == "dense_set":
            for k, v in arrays.items():
                self._dense.set(k, v)
            return {"ok": True}, {}
        if cmd == "dense_add":
            for k, v in arrays.items():
                self._dense.add(k, v)
            return {"ok": True}, {}
        if cmd == "dense_get":
            out = {}
            for k in meta.get("names", []):
                v = self._dense.get(k)
                if v is not None:
                    out[k] = v
            return {"ok": True, "names": sorted(out)}, out
        if cmd == "save":
            with self._tables_lock:
                tables = dict(self._tables)
            blobs = {f"sparse_{n}": np.frombuffer(t.save(), np.uint8)
                     for n, t in tables.items()}
            blobs["dense"] = np.frombuffer(self._dense.save(), np.uint8)
            return {"ok": True, "tables": sorted(tables)}, blobs
        if cmd == "load":
            for name, blob in arrays.items():
                raw = blob.tobytes()
                if name == "dense":
                    self._dense.load(raw)
                elif name.startswith("sparse_"):
                    tname = name[len("sparse_"):]
                    # the server handles requests on concurrent threads
                    # (daemon_threads TCP): the existence check and the
                    # final lookup must go through the lock like every
                    # other _tables access, or a racing pull/push handler
                    # creating the same table tears this check-then-act
                    # (graft_lint GL202)
                    with self._tables_lock:
                        table = self._tables.get(tname)
                    if table is None:
                        # recover dim + accessor (kind AND hyperparameters)
                        # from the checkpoint itself
                        dim, acc, acc_kw = SparseTable.peek_meta(raw)
                        meta2 = dict(meta)
                        meta2.update(table=tname, dim=dim, accessor=acc,
                                     accessor_kw=acc_kw)
                        table = self._table(meta2)
                    table.load(raw)
            return {"ok": True}, {}
        if cmd == "stats":
            with self._tables_lock:
                tables = dict(self._tables)
            return {"ok": True,
                    "tables": {n: len(t) for n, t in tables.items()},
                    "dense": self._dense.names()}, {}
        if cmd == "stop":
            return {"ok": True}, {}
        return {"ok": False, "error": f"unknown cmd {cmd!r}"}, {}
