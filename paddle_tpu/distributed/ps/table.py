"""Host-memory parameter tables for the TPU-native parameter-server mode.

The reference's PS keeps giant sparse embedding tables server-side
(paddle/fluid/distributed/ps/table/memory_sparse_table.cc: sharded hash
maps of feature id -> embedding + optimizer slots) because they exceed
any accelerator's memory. The same constraint holds on TPU — a
100B-feature table cannot live in HBM — so the TPU-native design keeps
the identical split: dense math stays in one jitted XLA program on
device, and the sparse tables live here, in a growable numpy arena in
host RAM, updated by vectorized accessors on push.

Layout: open-addressed ``id -> row`` dict into one contiguous
``(capacity, dim)`` float32 arena plus aligned optimizer-slot arenas —
pulls and pushes are pure gather/scatter over the arena, no per-row
Python objects (the reference's per-shard ``std::unordered_map`` of
pointers trades the same way).
"""
from __future__ import annotations

import io
import threading
from typing import Dict, Optional

import numpy as np

from .accessor import CtrAccessor, make_accessor

__all__ = ["SparseTable", "DenseTable", "merge_by_id"]


def merge_by_id(ids: np.ndarray, vals: np.ndarray):
    """Sum-aggregate rows of ``vals`` that share a feature id. Returns
    (unique_ids, aggregated) — the one dedup idiom every push-style path
    must share (duplicate ids per batch are the norm in CTR workloads)."""
    uniq, inv = np.unique(ids, return_inverse=True)
    agg = np.zeros((len(uniq),) + vals.shape[1:], np.float32)
    np.add.at(agg, inv, vals)
    return uniq, agg


class SparseTable:
    """One logical sparse table (or one shard of it, server-side).

    ``pull`` initializes unseen features on demand (the reference's
    ``pull_sparse`` create-on-miss path); ``push`` aggregates duplicate
    ids then applies the accessor in one vectorized call.
    """

    def __init__(self, dim: int, accessor="adagrad",
                 initializer: str = "normal", init_scale: float = 0.01,
                 seed: int = 0, capacity: int = 1024):
        self.dim = int(dim)
        if isinstance(accessor, str):
            self.accessor_name = accessor
            self.accessor = make_accessor(accessor)
        else:
            self.accessor = accessor
            from . import accessor as _amod
            self.accessor_name = next(
                (k for k, cls in _amod._ACCESSORS.items()
                 if type(accessor) is cls), "custom")
        # CTR admission: un-admitted features accumulate shows here and
        # only earn an embedding row past admit_threshold
        self._pending_shows: Dict[int, float] = {}
        self._initializer = initializer
        self._scale = float(init_scale)
        self._rng = np.random.RandomState(seed)
        self._index: Dict[int, int] = {}
        self._free: list[int] = []
        self._next_row = 0  # arena high-water mark
        self._rows = np.zeros((int(capacity), self.dim), np.float32)
        self._slots = self.accessor.init_slots(int(capacity), self.dim)
        self._lock = threading.Lock()

    # -- internals -----------------------------------------------------------
    def _grow_locked(self, need: int):
        # _locked suffix: caller must hold self._lock (graft_lint
        # lock-discipline convention)
        cap = self._rows.shape[0]
        new_cap = max(cap * 2, cap + need)
        grown = np.zeros((new_cap, self.dim), np.float32)
        grown[:cap] = self._rows
        self._rows = grown
        for k, v in self._slots.items():
            g = np.zeros((new_cap,) + v.shape[1:], v.dtype)
            g[:cap] = v
            self._slots[k] = g

    def _ensure_locked(self, ids: np.ndarray) -> np.ndarray:
        """Map ids -> arena row indices, initializing misses."""
        idx = np.empty(len(ids), np.int64)
        missing = []
        for i, fid in enumerate(ids):
            j = self._index.get(int(fid))
            if j is None:
                missing.append(i)
                idx[i] = -1
            else:
                idx[i] = j
        if missing:
            need = max(0, len(missing) - len(self._free))
            if self._next_row + need > self._rows.shape[0]:
                self._grow_locked(self._next_row + need - self._rows.shape[0])
            for i in missing:
                fid = int(ids[i])
                j = self._index.get(fid)  # duplicate miss in this batch
                if j is not None:
                    idx[i] = j
                    continue
                # evicted rows are reused before the arena grows
                if self._free:
                    j = self._free.pop()
                else:
                    j = self._next_row
                    self._next_row += 1
                self._index[fid] = j
                idx[i] = j
                if self._initializer == "normal":
                    self._rows[j] = self._rng.normal(
                        0.0, self._scale, self.dim).astype(np.float32)
                else:
                    self._rows[j] = 0.0
                for v in self._slots.values():
                    v[j] = 0
        return idx

    # -- public API ----------------------------------------------------------
    def __len__(self):
        with self._lock:
            return len(self._index)

    def _gated(self) -> bool:
        return isinstance(self.accessor, CtrAccessor)

    def pull(self, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        with self._lock:
            if self._gated():
                # CTR admission (reference ctr_accessor.cc): features not
                # yet past admit_threshold read as zeros and get no row
                out = np.zeros((len(ids), self.dim), np.float32)
                known = [i for i, f in enumerate(ids)
                         if int(f) in self._index]
                if known:
                    rows_idx = [self._index[int(ids[i])] for i in known]
                    out[known] = self._rows[rows_idx]
                return out
            idx = self._ensure_locked(ids)
            return self._rows[idx].copy()

    def push(self, ids, grads) -> None:
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), self.dim)
        with self._lock:
            if self._gated():
                # drop gradients for un-admitted features (they have no row)
                keep = np.asarray([int(f) in self._index for f in ids], bool)
                if not keep.any():
                    return
                ids, grads = ids[keep], grads[keep]
            uniq, agg = merge_by_id(ids, grads)
            idx = self._ensure_locked(uniq)
            rows = self._rows[idx]
            slots = {k: v[idx] for k, v in self._slots.items()}
            self.accessor.update(rows, slots, agg)
            self._rows[idx] = rows
            for k, v in self._slots.items():
                v[idx] = slots[k]

    def set_rows(self, ids, values) -> None:
        """Direct assignment (checkpoint load / geo-SGD delta apply)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        values = np.asarray(values, np.float32).reshape(len(ids), self.dim)
        with self._lock:
            idx = self._ensure_locked(ids)
            self._rows[idx] = values

    def add_to_rows(self, ids, deltas) -> None:
        """Accumulate raw deltas (geo-SGD: workers send weight diffs, not
        gradients — reference communicator GeoCommunicator::Send)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        deltas = np.asarray(deltas, np.float32).reshape(len(ids), self.dim)
        uniq, agg = merge_by_id(ids, deltas)
        with self._lock:
            idx = self._ensure_locked(uniq)
            self._rows[idx] += agg

    def record_shows(self, ids, shows=None, clicks=None):
        if not isinstance(self.accessor, CtrAccessor):
            return
        ids = np.asarray(ids, np.int64).reshape(-1)
        shows = np.ones(len(ids), np.float32) if shows is None else \
            np.asarray(shows, np.float32).reshape(-1)
        clicks_a = None if clicks is None else \
            np.asarray(clicks, np.float32).reshape(-1)
        # duplicate ids per batch are the norm: aggregate first, or the
        # gather-increment-scatter below would keep only the last copy
        orig_ids = ids
        ids, shows = merge_by_id(orig_ids, shows)
        if clicks_a is not None:
            _, clicks_a = merge_by_id(orig_ids, clicks_a)
        with self._lock:
            # admission: un-admitted features accumulate pending shows and
            # only materialize a row once past admit_threshold
            admitted_i, carried = [], {}
            for i, f in enumerate(ids):
                fid = int(f)
                if fid in self._index:
                    admitted_i.append(i)
                    continue
                tally = self._pending_shows.get(fid, 0.0) + float(shows[i])
                if tally >= self.accessor.admit_threshold:
                    self._pending_shows.pop(fid, None)
                    admitted_i.append(i)  # _ensure below creates the row
                    carried[i] = tally - float(shows[i])
                else:
                    self._pending_shows[fid] = tally
            if not admitted_i:
                return
            sel = np.asarray(admitted_i, np.int64)
            shows_eff = shows[sel].copy()
            for pos, i in enumerate(admitted_i):
                shows_eff[pos] += carried.get(i, 0.0)  # pre-admission shows
            idx = self._ensure_locked(ids[sel])
            slots = {k: v[idx] for k, v in self._slots.items()}
            self.accessor.record_shows(
                slots, shows_eff,
                None if clicks_a is None else clicks_a[sel])
            for k, v in self._slots.items():
                v[idx] = slots[k]

    def shrink(self) -> int:
        """Decay CTR stats and evict stale features; returns evicted count
        (reference memory_sparse_table.cc::Shrink)."""
        if not isinstance(self.accessor, CtrAccessor):
            return 0
        with self._lock:
            if not self._index:
                return 0
            ids = np.fromiter(self._index.keys(), np.int64,
                              len(self._index))
            idx = np.fromiter(self._index.values(), np.int64,
                              len(self._index))
            slots = {k: v[idx] for k, v in self._slots.items()}
            self.accessor.decay(slots)
            evict = self.accessor.should_evict(slots)
            for k, v in self._slots.items():
                v[idx] = slots[k]
            for fid, j in zip(ids[evict], idx[evict]):
                del self._index[int(fid)]
                self._free.append(int(j))
            return int(evict.sum())

    # -- checkpoint ----------------------------------------------------------
    def save(self) -> bytes:
        with self._lock:
            ids = np.fromiter(self._index.keys(), np.int64,
                              len(self._index))
            idx = np.fromiter(self._index.values(), np.int64,
                              len(self._index))
            import json as _json
            acc_meta = _json.dumps(
                {"name": self.accessor_name,
                 "config": getattr(self.accessor, "config", dict)()})
            buf = io.BytesIO()
            np.savez(buf, ids=ids, rows=self._rows[idx],
                     accessor=np.frombuffer(acc_meta.encode(), np.uint8),
                     **{f"slot_{k}": v[idx] for k, v in self._slots.items()})
            return buf.getvalue()

    @staticmethod
    def peek_meta(blob: bytes):
        """(dim, accessor_name, accessor_config) of a checkpoint blob — a
        fresh server must rebuild the accessor with the SAME kind and
        hyperparameters it was saved with (code-review r3: a defaulted
        accessor would KeyError on the slot set or silently change lr)."""
        import json as _json
        data = np.load(io.BytesIO(blob))
        name, cfg = "adagrad", {}
        if "accessor" in data:
            raw = data["accessor"].tobytes().decode()
            try:
                meta = _json.loads(raw)
                name, cfg = meta["name"], meta.get("config", {})
            except ValueError:  # pre-config blobs stored the bare name
                name = raw
        return int(data["rows"].shape[1]), name, cfg

    def load(self, blob: bytes) -> None:
        data = np.load(io.BytesIO(blob))
        ids = data["ids"]
        slot_keys = {k[len("slot_"):] for k in data.files
                     if k.startswith("slot_")}
        with self._lock:
            if slot_keys != set(self._slots):
                raise ValueError(
                    f"checkpoint slots {sorted(slot_keys)} do not match "
                    f"this table's accessor '{self.accessor_name}' slots "
                    f"{sorted(self._slots)} — construct the table with "
                    "the accessor it was saved with")
            self._index.clear()
            self._free = []
            self._pending_shows.clear()
            n = len(ids)
            if n > self._rows.shape[0]:
                self._grow_locked(n - self._rows.shape[0])
            self._rows[:n] = data["rows"]
            self._index.update({int(f): i for i, f in enumerate(ids)})
            self._next_row = n
            for k in self._slots:
                self._slots[k][:n] = data[f"slot_{k}"]


class DenseTable:
    """Named dense blocks (the reference's dense tables hold non-sparse
    params server-side in PS mode; here they are a host-side mirror used
    by sync/geo communicators and PS checkpoints)."""

    def __init__(self):
        self._params: Dict[str, np.ndarray] = {}
        self._lock = threading.Lock()

    def set(self, name: str, value) -> None:
        with self._lock:
            self._params[name] = np.asarray(value, np.float32).copy()

    def get(self, name: str) -> Optional[np.ndarray]:
        with self._lock:
            v = self._params.get(name)
            return None if v is None else v.copy()

    def add(self, name: str, delta) -> None:
        with self._lock:
            d = np.asarray(delta, np.float32)
            if name in self._params:
                self._params[name] = self._params[name] + d
            else:
                self._params[name] = d.copy()

    def names(self):
        with self._lock:
            return sorted(self._params)

    def save(self) -> bytes:
        with self._lock:
            buf = io.BytesIO()
            np.savez(buf, **self._params)
            return buf.getvalue()

    def load(self, blob: bytes) -> None:
        data = np.load(io.BytesIO(blob))
        with self._lock:
            self._params = {k: data[k].copy() for k in data.files}
