"""Parameter-server client + communicator (brpc_ps_client / Communicator
analog).

Routing: feature id -> server ``fid % n_servers`` (the reference shards
by id hash across server instances — brpc_ps_client.cc::ShardNum). The
communicator reproduces the reference's three training modes
(paddle/fluid/distributed/ps/service/communicator/communicator.cc):

- **sync**: every push is sent and applied before the next pull;
- **async**: pushes land in a merge queue drained by a background
  thread — duplicate ids in queued batches are pre-aggregated before
  send (AsyncCommunicator::MergeSparseGrads);
- **geo**: workers train on a local replica and periodically ship
  weight *deltas* (GeoCommunicator) — the only mode where the server
  applies raw diffs instead of running the optimizer.
"""
from __future__ import annotations

import logging
import queue
import socket
import threading
import time
from typing import Dict, List, Sequence

import numpy as np

_log = logging.getLogger(__name__)

from .service import recv_msg, send_msg
from .table import SparseTable

__all__ = ["PSClient", "Communicator"]


class PSError(RuntimeError):
    """Server-side failure relayed through the reply channel."""


class _Conn:
    def __init__(self, endpoint: str):
        host, port = endpoint.rsplit(":", 1)
        self.sock = socket.create_connection((host, int(port)), timeout=60)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.lock = threading.Lock()

    def call(self, meta: dict, arrays: Dict[str, np.ndarray]):
        with self.lock:
            send_msg(self.sock, meta, arrays)
            out_meta, out_arrays = recv_msg(self.sock)
        if not out_meta.get("ok", False):
            raise PSError(out_meta.get("error", "unknown server error"))
        return out_meta, out_arrays


class PSClient:
    """Shard-routing client over one socket per server. Per-shard RPCs of
    one logical pull/push go out concurrently (the reference's brpc client
    issues shard requests in parallel; serialized round trips would put
    n_servers x RTT on the training hot path)."""

    def __init__(self, endpoints: Sequence[str], table_defaults=None,
                 op_timeout_s: float = 120.0):
        from concurrent.futures import ThreadPoolExecutor
        self._conns = [_Conn(e) for e in endpoints]
        self.n = len(self._conns)
        self._defaults = dict(table_defaults or {})
        # bound on one sharded pull/push fan-in: must exceed _Conn's
        # 60 s socket timeout so per-socket errors surface first
        self._op_timeout_s = float(op_timeout_s)
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, self.n),
            thread_name_prefix="ps-client") if self.n > 1 else None

    def _fanout(self, calls):
        """Run [(conn, meta, arrays), ...] concurrently; returns results
        in order, raising the first failure after all complete. The
        fan-in is bounded: a wedged shard surfaces as PSError instead
        of parking the training step forever."""
        if self._pool is None or len(calls) <= 1:
            return [c.call(m, a) for c, m, a in calls]
        from concurrent.futures import TimeoutError as _FutTimeout
        futs = [self._pool.submit(c.call, m, a) for c, m, a in calls]
        # one deadline for the whole fan-in, not per future: n_servers
        # cascading slow shards must not stack n x op_timeout_s
        end = time.monotonic() + self._op_timeout_s
        try:
            return [f.result(timeout=max(0.0, end - time.monotonic()))
                    for f in futs]
        except _FutTimeout:
            for f in futs:
                f.cancel()
            raise PSError(
                f"parameter-server RPC gave no reply within "
                f"{self._op_timeout_s:.1f}s (wedged server?)") from None

    def _meta(self, cmd: str, table: str, dim: int, **kw) -> dict:
        m = {"cmd": cmd, "table": table, "dim": int(dim)}
        m.update(self._defaults.get(table, {}))
        m.update(kw)
        return m

    def _route(self, ids: np.ndarray):
        shard = ids % self.n
        return [np.nonzero(shard == s)[0] for s in range(self.n)]

    # -- sparse --------------------------------------------------------------
    def pull(self, table: str, ids, dim: int) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        out = np.empty((len(ids), dim), np.float32)
        routed = [(s, sel) for s, sel in enumerate(self._route(ids))
                  if len(sel)]
        results = self._fanout(
            [(self._conns[s], self._meta("pull", table, dim),
              {"ids": ids[sel]}) for s, sel in routed])
        for (s, sel), (_, arrs) in zip(routed, results):
            out[sel] = arrs["rows"]
        return out

    def push(self, table: str, ids, grads, dim: int) -> None:
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), dim)
        self._fanout(
            [(self._conns[s], self._meta("push", table, dim),
              {"ids": ids[sel], "grads": grads[sel]})
             for s, sel in enumerate(self._route(ids)) if len(sel)])

    def push_delta(self, table: str, ids, deltas, dim: int) -> None:
        ids = np.asarray(ids, np.int64).reshape(-1)
        deltas = np.asarray(deltas, np.float32).reshape(len(ids), dim)
        self._fanout(
            [(self._conns[s], self._meta("push_delta", table, dim),
              {"ids": ids[sel], "deltas": deltas[sel]})
             for s, sel in enumerate(self._route(ids)) if len(sel)])

    # -- dense ---------------------------------------------------------------
    def dense_set(self, params: Dict[str, np.ndarray], server: int = 0):
        self._conns[server].call({"cmd": "dense_set"}, params)

    def dense_add(self, deltas: Dict[str, np.ndarray], server: int = 0):
        self._conns[server].call({"cmd": "dense_add"}, deltas)

    def dense_get(self, names: List[str], server: int = 0):
        _, arrs = self._conns[server].call(
            {"cmd": "dense_get", "names": list(names)}, {})
        return arrs

    # -- maintenance ---------------------------------------------------------
    def shrink(self) -> int:
        return sum(c.call({"cmd": "shrink"}, {})[0].get("evicted", 0)
                   for c in self._conns)

    def save(self) -> List[Dict[str, np.ndarray]]:
        return [c.call({"cmd": "save"}, {})[1] for c in self._conns]

    def load(self, blobs: List[Dict[str, np.ndarray]]) -> None:
        if len(blobs) != self.n:
            # rows were saved under fid % n_saved routing: loading them
            # into a different shard count would scatter them where pulls
            # can never find them — fail loudly instead
            raise ValueError(
                f"snapshot has {len(blobs)} shards but this cluster has "
                f"{self.n} servers; restore onto a matching server count")
        for c, b in zip(self._conns, blobs):
            c.call({"cmd": "load"}, b)

    def stats(self):
        return [c.call({"cmd": "stats"}, {})[0] for c in self._conns]

    def stop_servers(self):
        for c in self._conns:
            try:
                c.call({"cmd": "stop"}, {})
            except Exception:
                pass

    def close(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        for c in self._conns:
            try:
                c.sock.close()
            except Exception:
                pass


class Communicator:
    """Training-mode driver over a PSClient.

    sync: ``push`` forwards immediately. async: pushes are queued,
    merged by id, and drained by a daemon thread every
    ``send_interval_s`` (or when ``queue_cap`` batches pile up). geo:
    ``local_step`` trains against a local ``SparseTable`` replica and
    every ``geo_steps`` ships row deltas to the servers.
    """

    def __init__(self, client: PSClient, mode: str = "sync",
                 send_interval_s: float = 0.05, queue_cap: int = 64,
                 geo_steps: int = 8):
        if mode not in ("sync", "async", "geo"):
            raise ValueError(f"unknown communicator mode {mode!r}")
        self.client = client
        self.mode = mode
        self.geo_steps = int(geo_steps)
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_cap)
        self._interval = float(send_interval_s)
        self._stop = threading.Event()
        self._thread = None
        self._local: Dict[str, SparseTable] = {}
        self._base: Dict[str, Dict[int, np.ndarray]] = {}
        self._steps: Dict[str, int] = {}

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self.mode == "async":
            self._thread = threading.Thread(target=self._drain_loop,
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self.mode == "geo":
            # ship every table's outstanding local deltas — a worker that
            # exits mid-window must not lose up to geo_steps-1 updates
            for name, tbl in list(self._local.items()):
                self.geo_flush(name, tbl.dim)
        else:
            self.flush()

    # -- sync / async push ---------------------------------------------------
    def push(self, table: str, ids, grads, dim: int) -> None:
        if self.mode == "sync":
            self.client.push(table, ids, grads, dim)
        elif self.mode == "geo":
            # generic entry point in geo mode: the local-train path (a
            # bounded queue with no drain thread would deadlock instead)
            self.geo_push(table, ids, grads, dim)
        else:
            self._q.put((table, np.asarray(ids, np.int64).reshape(-1),
                         np.asarray(grads, np.float32), int(dim)))

    def flush(self):
        """Merge and send everything still queued (async mode). On a send
        failure the merged batch is re-queued (best effort) so a transient
        server outage does not silently drop gradients."""
        pending: Dict[tuple, list] = {}
        while True:
            try:
                table, ids, grads, dim = self._q.get_nowait()
            except queue.Empty:
                break
            pending.setdefault((table, dim), []).append((ids, grads))
        first_err = None
        for (table, dim), items in pending.items():
            ids = np.concatenate([i for i, _ in items])
            grads = np.concatenate(
                [g.reshape(len(i), dim) for i, g in items])
            # merge duplicate ids before hitting the wire
            from .table import merge_by_id
            uniq, agg = merge_by_id(ids, grads)
            # push shard by shard: a partial fan-out failure must re-queue
            # ONLY the failed shard's slice — re-sending the whole merged
            # batch would double-apply gradients on the healthy shards
            for sel in [np.nonzero(uniq % self.client.n == s)[0]
                        for s in range(self.client.n)]:
                if not len(sel):
                    continue
                try:
                    self.client.push(table, uniq[sel], agg[sel], dim)
                except Exception as e:
                    first_err = first_err or e
                    try:  # keep this shard's slice for the next drain tick
                        self._q.put_nowait(
                            (table, uniq[sel], agg[sel], dim))
                    except queue.Full:
                        _log.warning(
                            "ps: dropping %d merged grad rows for table %r"
                            " (send failed and queue is full)",
                            len(sel), table)
        if first_err is not None:
            raise first_err

    def _drain_loop(self):
        while not self._stop.is_set():
            time.sleep(self._interval)
            try:
                self.flush()
            except Exception as e:
                if self._stop.is_set():
                    return
                _log.warning("ps: async flush failed (will retry): %r", e)

    # -- geo mode ------------------------------------------------------------
    def _local_table(self, table: str, dim: int) -> SparseTable:
        if table not in self._local:
            from .accessor import make_accessor
            defaults = self.client._defaults.get(table, {})
            acc = make_accessor(defaults.get("accessor", "adagrad"),
                                **defaults.get("accessor_kw", {}))
            self._local[table] = SparseTable(
                dim=dim, accessor=acc,
                initializer=defaults.get("initializer", "normal"),
                init_scale=float(defaults.get("init_scale", 0.01)),
                seed=int(defaults.get("seed", 0)))
            self._base[table] = {}
            self._steps[table] = 0
        return self._local[table]

    def geo_pull(self, table: str, ids, dim: int) -> np.ndarray:
        """Pull from the local replica, faulting unseen ids in from the
        servers and recording their base values for delta computation."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        local = self._local_table(table, dim)
        base = self._base[table]
        new = np.asarray([f for f in np.unique(ids) if int(f) not in base],
                         np.int64)
        if len(new):
            rows = self.client.pull(table, new, dim)
            local.set_rows(new, rows)
            for f, r in zip(new, rows):
                base[int(f)] = r.copy()
        return local.pull(ids)

    def geo_push(self, table: str, ids, grads, dim: int) -> None:
        """Apply the optimizer locally; every ``geo_steps`` ship deltas."""
        # fault ids into the base map first: deltas are diffs against the
        # server's rows, and an id pushed without a prior geo_pull would
        # otherwise never appear in any flush
        self.geo_pull(table, ids, dim)
        local = self._local_table(table, dim)
        local.push(ids, grads)
        self._steps[table] += 1
        if self._steps[table] % self.geo_steps == 0:
            self.geo_flush(table, dim)

    def geo_flush(self, table: str, dim: int) -> None:
        base = self._base.get(table)
        if not base:
            return
        local = self._local_table(table, dim)
        ids = np.asarray(sorted(base), np.int64)
        cur = local.pull(ids)
        prev = np.stack([base[int(f)] for f in ids])
        deltas = cur - prev
        sent = np.abs(deltas).sum(axis=1) > 0
        if sent.any():
            self.client.push_delta(table, ids[sent], deltas[sent], dim)
        # refresh the replica from the servers (other workers' deltas)
        rows = self.client.pull(table, ids, dim)
        local.set_rows(ids, rows)
        for f, r in zip(ids, rows):
            base[int(f)] = r.copy()
