"""Row accessors: the per-feature optimizer applied on sparse push.

The reference's PS applies the optimizer *on the server* when gradients
are pushed (accessors in paddle/fluid/distributed/ps/table/
sparse_accessor.h, ctr_accessor.cc — SGD/Adagrad/Adam rules plus CTR
show/click statistics driving feature admission and eviction). The
TPU-native analog keeps that contract: the dense model trains on-device
inside one jitted step, while embedding rows too large for HBM live in
host RAM and are updated here, vectorized over the pushed row block.

All accessors operate on ``(rows, slots, grads)`` numpy blocks — one
call per pushed batch, no per-row Python loops.
"""
from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

__all__ = ["SGDAccessor", "AdagradAccessor", "AdamAccessor", "CtrAccessor",
           "make_accessor"]


class SGDAccessor:
    """Plain SGD on pushed rows (reference sparse_sgd_rule.cc StdAdaGrad's
    naive mode)."""

    slot_names: Tuple[str, ...] = ()

    def __init__(self, learning_rate: float = 0.05):
        self.lr = float(learning_rate)

    def config(self) -> dict:
        """Constructor kwargs — persisted in checkpoints so a fresh server
        rebuilds the accessor with the same hyperparameters."""
        return {"learning_rate": self.lr}

    def init_slots(self, n: int, dim: int) -> Dict[str, np.ndarray]:
        return {}

    def update(self, rows: np.ndarray, slots: Dict[str, np.ndarray],
               grads: np.ndarray) -> None:
        rows -= self.lr * grads


class AdagradAccessor:
    """Per-element Adagrad (reference sparse_sgd_rule.cc SparseAdaGradSGDRule)."""

    slot_names = ("g2sum",)

    def __init__(self, learning_rate: float = 0.05, epsilon: float = 1e-8):
        self.lr = float(learning_rate)
        self.eps = float(epsilon)

    def config(self) -> dict:
        return {"learning_rate": self.lr, "epsilon": self.eps}

    def init_slots(self, n: int, dim: int) -> Dict[str, np.ndarray]:
        return {"g2sum": np.zeros((n, dim), np.float32)}

    def update(self, rows, slots, grads):
        g2 = slots["g2sum"]
        g2 += grads * grads
        rows -= self.lr * grads / (np.sqrt(g2) + self.eps)


class AdamAccessor:
    """Adam with per-row step counts (reference sparse_sgd_rule.cc
    SparseAdamSGDRule: beta1/beta2 powers tracked per feature)."""

    slot_names = ("m", "v", "step")

    def __init__(self, learning_rate: float = 0.001, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8):
        self.lr = float(learning_rate)
        self.b1, self.b2 = float(beta1), float(beta2)
        self.eps = float(epsilon)

    def config(self) -> dict:
        return {"learning_rate": self.lr, "beta1": self.b1,
                "beta2": self.b2, "epsilon": self.eps}

    def init_slots(self, n, dim):
        return {"m": np.zeros((n, dim), np.float32),
                "v": np.zeros((n, dim), np.float32),
                "step": np.zeros((n, 1), np.float32)}

    def update(self, rows, slots, grads):
        m, v, step = slots["m"], slots["v"], slots["step"]
        step += 1.0
        m *= self.b1
        m += (1 - self.b1) * grads
        v *= self.b2
        v += (1 - self.b2) * grads * grads
        bc1 = 1.0 - self.b1 ** step
        bc2 = 1.0 - self.b2 ** step
        rows -= self.lr * (m / bc1) / (np.sqrt(v / bc2) + self.eps)


class CtrAccessor:
    """CTR-style accessor: wraps a base rule and keeps per-feature
    show/click statistics with exponential decay, driving entry admission
    (a feature earns its embedding only after enough shows) and eviction
    of stale features (reference ctr_accessor.cc: show_click_decay_rate,
    delete_threshold, delta_score).
    """

    def __init__(self, base=None, show_decay: float = 0.98,
                 admit_threshold: float = 1.0,
                 delete_threshold: float = 0.25):
        self.base = base or AdagradAccessor()
        self.slot_names = self.base.slot_names + ("show", "click")
        self.show_decay = float(show_decay)
        self.admit_threshold = float(admit_threshold)
        self.delete_threshold = float(delete_threshold)

    def config(self) -> dict:
        return {"show_decay": self.show_decay,
                "admit_threshold": self.admit_threshold,
                "delete_threshold": self.delete_threshold}

    def init_slots(self, n, dim):
        s = self.base.init_slots(n, dim)
        s["show"] = np.zeros((n, 1), np.float32)
        s["click"] = np.zeros((n, 1), np.float32)
        return s

    def update(self, rows, slots, grads):
        base_slots = {k: slots[k] for k in self.base.slot_names}
        self.base.update(rows, base_slots, grads)

    def record_shows(self, slots, shows, clicks=None):
        slots["show"] += np.asarray(shows, np.float32).reshape(-1, 1)
        if clicks is not None:
            slots["click"] += np.asarray(clicks, np.float32).reshape(-1, 1)

    def decay(self, slots):
        slots["show"] *= self.show_decay
        slots["click"] *= self.show_decay

    def should_evict(self, slots) -> np.ndarray:
        """Boolean mask over rows whose decayed score dropped below the
        delete threshold."""
        score = slots["show"] + 2.0 * slots["click"]
        return (score < self.delete_threshold).reshape(-1)


_ACCESSORS = {"sgd": SGDAccessor, "adagrad": AdagradAccessor,
              "adam": AdamAccessor, "ctr": CtrAccessor}


def make_accessor(name: str, **kwargs):
    try:
        return _ACCESSORS[name.lower()](**kwargs)
    except KeyError:
        raise ValueError(
            f"unknown accessor {name!r}; one of {sorted(_ACCESSORS)}")
