"""PS-mode runtime: the ``the_one_ps.py`` analog.

Reference: python/paddle/distributed/ps/the_one_ps.py wires the fleet
role (TRAINING_ROLE / PADDLE_PSERVERS_IP_PORT_LIST env contract) to
brpc servers and rewrites embedding lookups into distributed
pull/push pairs. The TPU-native runtime keeps the same user surface —
``init_server()/run_server()`` on PSERVER nodes, ``init_worker()`` on
trainers, a ``SparseEmbedding`` layer whose forward pulls host-side
rows and whose backward pushes gradients — while the dense model
around it stays an ordinary jitted-on-TPU module. The pull/push sits
at the step edge, exactly where host<->device transfer has to happen
anyway for host-RAM tables.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ...autograd import PyLayer
from ...core.tensor import Tensor
from .client import Communicator, PSClient
from .service import PSServer
from .table import SparseTable

__all__ = ["SparseEmbedding", "PSRuntime", "init_server", "run_server",
           "init_worker", "stop_worker"]


class _PullPush(PyLayer):
    """Pull rows on forward; push row grads on backward. The float
    ``hook`` input exists only so the tape records a backward edge —
    integer ids carry no gradient."""

    @staticmethod
    def forward(ctx, ids: Tensor, hook: Tensor, layer=None):
        flat = np.asarray(ids._data).reshape(-1)
        rows = layer._pull(flat)
        ctx.ids = flat
        ctx.layer = layer
        out = rows.reshape(tuple(ids.shape) + (layer.dim,))
        return Tensor(jnp.asarray(out))

    @staticmethod
    def backward(ctx, grad: Tensor):
        g = np.asarray(grad._data, np.float32).reshape(
            len(ctx.ids), ctx.layer.dim)
        ctx.layer._push(ctx.ids, g)
        return None  # no grad for the hook scalar


class SparseEmbedding:
    """Distributed embedding over a PS table (reference:
    paddle.static.nn.sparse_embedding / the fleet-rewritten
    lookup_table). Backend is chosen by ``bind``: a local in-process
    table (single host), or a PSClient/Communicator (sync, async, geo).
    """

    def __init__(self, name: str, dim: int, accessor: str = "adagrad",
                 init_scale: float = 0.01, seed: int = 0, **accessor_kw):
        self.name = name
        self.dim = int(dim)
        # accessor_kw rides along so PS-mode servers build the accessor
        # with the user's hyperparameters, not the defaults
        self.table_config = {"accessor": accessor,
                             "init_scale": init_scale, "seed": seed,
                             "accessor_kw": dict(accessor_kw)}
        self._accessor_kw = accessor_kw
        self._local: Optional[SparseTable] = None
        self._comm: Optional[Communicator] = None
        # default backend: a private local table (works out of the box)
        self._ensure_local()

    def _ensure_local(self):
        if self._local is None:
            from .accessor import make_accessor
            acc = make_accessor(self.table_config["accessor"],
                                **self._accessor_kw)
            self._local = SparseTable(
                self.dim, accessor=acc,
                init_scale=self.table_config["init_scale"],
                seed=self.table_config["seed"])

    def bind(self, comm: Communicator):
        """Route pulls/pushes through a communicator (PS mode)."""
        self._comm = comm
        comm.client._defaults.setdefault(self.name, {}).update(
            self.table_config)
        return self

    # -- table ops -----------------------------------------------------------
    def _pull(self, flat_ids: np.ndarray) -> np.ndarray:
        if self._comm is None:
            return self._local.pull(flat_ids)
        if self._comm.mode == "geo":
            return self._comm.geo_pull(self.name, flat_ids, self.dim)
        return self._comm.client.pull(self.name, flat_ids, self.dim)

    def _push(self, flat_ids: np.ndarray, grads: np.ndarray) -> None:
        if self._comm is None:
            self._local.push(flat_ids, grads)
        elif self._comm.mode == "geo":
            self._comm.geo_push(self.name, flat_ids, grads, self.dim)
        else:
            self._comm.push(self.name, flat_ids, grads, self.dim)

    def __call__(self, ids: Tensor) -> Tensor:
        if not isinstance(ids, Tensor):
            ids = Tensor(jnp.asarray(np.asarray(ids), jnp.int64))
        hook = Tensor(jnp.zeros((), jnp.float32), stop_gradient=False)
        return _PullPush.apply(ids, hook, layer=self)


class PSRuntime:
    """Role-aware entry points driven by the launch env contract
    (PADDLE_PSERVERS_IP_PORT_LIST, TRAINING_ROLE, PADDLE_TRAINER_ID —
    reference python/paddle/distributed/ps/the_one_ps.py + fleet env)."""

    def __init__(self, endpoints: Optional[Sequence[str]] = None,
                 role: Optional[str] = None):
        env_eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self.endpoints = list(endpoints) if endpoints else \
            [e for e in env_eps.split(",") if e]
        self.role = (role or os.environ.get("TRAINING_ROLE",
                                            "TRAINER")).upper()
        self.server: Optional[PSServer] = None
        self.client: Optional[PSClient] = None
        self.communicator: Optional[Communicator] = None

    # -- server side ---------------------------------------------------------
    def init_server(self, index: Optional[int] = None) -> PSServer:
        idx = index if index is not None else \
            int(os.environ.get("PADDLE_PSERVER_ID", "0"))
        host, port = self.endpoints[idx].rsplit(":", 1)
        self.server = PSServer(host, int(port)).start()
        return self.server

    def run_server(self):
        """Block until a client sends stop (reference fleet.run_server)."""
        self.server._thread.join()

    # -- worker side ---------------------------------------------------------
    def init_worker(self, mode: str = "sync", **comm_kw) -> Communicator:
        self.client = PSClient(self.endpoints)
        self.communicator = Communicator(self.client, mode=mode,
                                         **comm_kw).start()
        return self.communicator

    def stop_worker(self, stop_servers: bool = False):
        if self.communicator is not None:
            self.communicator.stop()
        if self.client is not None:
            if stop_servers:
                self.client.stop_servers()
            self.client.close()


_runtime: Optional[PSRuntime] = None


def _rt() -> PSRuntime:
    global _runtime
    if _runtime is None:
        _runtime = PSRuntime()
    return _runtime


def init_server(endpoints=None, index=None):
    global _runtime
    if endpoints is not None:
        _runtime = PSRuntime(endpoints=endpoints)
    return _rt().init_server(index)


def run_server():
    _rt().run_server()


def init_worker(endpoints=None, mode: str = "sync", **kw):
    global _runtime
    if endpoints is not None:
        _runtime = PSRuntime(endpoints=endpoints)
    return _rt().init_worker(mode=mode, **kw)


def stop_worker(stop_servers: bool = False):
    _rt().stop_worker(stop_servers)
