"""Host-coordination store (parity: TCPStore,
paddle/phi/core/distributed/store/tcp_store.h:121 + Python
``core.create_or_get_global_tcp_store``).

The server and client are native C++ (``csrc/kv_store.cpp``) loaded via
ctypes; this module adds the rank-0-hosts-the-server convention, barrier(),
and a process-global singleton — the control-plane rendezvous used by the
launcher, elastic manager, and checkpoint coordinator. Data-plane
collectives never touch this store (they are XLA programs over ICI/DCN).
"""
from __future__ import annotations

import ctypes
import os
import socket
import threading
import time
from typing import Optional

from ..core.native import load_native

__all__ = ["TCPStore", "KVServer", "create_or_get_global_tcp_store"]

_MAXVAL = 1 << 26


def _lib():
    lib = load_native("kv_store")
    lib.kv_server_start.restype = ctypes.c_void_p
    lib.kv_server_start.argtypes = [ctypes.c_int]
    lib.kv_server_port.restype = ctypes.c_int
    lib.kv_server_port.argtypes = [ctypes.c_void_p]
    lib.kv_server_stop.argtypes = [ctypes.c_void_p]
    lib.kv_client_connect.restype = ctypes.c_void_p
    lib.kv_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                      ctypes.c_int]
    lib.kv_client_close.argtypes = [ctypes.c_void_p]
    lib.kv_client_shutdown.argtypes = [ctypes.c_void_p]
    for fn, extra in [("kv_client_set", [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_char_p, ctypes.c_uint32]),
                      ("kv_client_get", [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_char_p, ctypes.c_uint32]),
                      ("kv_client_add", [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_int64,
                                         ctypes.POINTER(ctypes.c_int64)]),
                      ("kv_client_wait", [ctypes.c_void_p, ctypes.c_char_p,
                                          ctypes.c_int64]),
                      ("kv_client_del", [ctypes.c_void_p, ctypes.c_char_p]),
                      ("kv_client_numkeys", [ctypes.c_void_p]),
                      ("kv_client_ping", [ctypes.c_void_p]),
                      ("kv_client_lease_set",
                       [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                        ctypes.c_uint32, ctypes.c_int64]),
                      ("kv_client_watch",
                       [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
                        ctypes.c_int64, ctypes.c_char_p, ctypes.c_uint32,
                        ctypes.POINTER(ctypes.c_int64),
                        ctypes.POINTER(ctypes.c_int32)])]:
        getattr(lib, fn).restype = ctypes.c_int64
        getattr(lib, fn).argtypes = extra
    return lib


class KVServer:
    """Standalone native KV server (the launcher master runs one)."""

    def __init__(self, port: int = 0):
        self._lib = _lib()
        self._h = self._lib.kv_server_start(port)
        if not self._h:
            raise RuntimeError(f"KVServer: cannot bind port {port}")
        self.port = self._lib.kv_server_port(self._h)

    def stop(self):
        if self._h:
            self._lib.kv_server_stop(self._h)
            self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class TCPStore:
    """Client (plus embedded server on the master) with the reference
    TCPStore API: set/get/add/wait/delete_key/num_keys + barrier."""

    def __init__(self, host: str, port: int, is_master: bool = False,
                 world_size: int = 1, timeout: float = 300.0):
        self._lib = _lib()
        self._server: Optional[KVServer] = None
        self.world_size = world_size
        self.timeout = timeout
        if is_master:
            self._server = KVServer(port)
            port = self._server.port
        self.host, self.port = host, port
        self._local = threading.local()
        self._all_conns: list = []
        self._conns_lock = threading.Lock()
        self._closed = False
        # fail fast if the master is unreachable
        self._lib.kv_client_ping(self._conn())

    # one native client handle serializes requests; blocking wait() from
    # one thread must not block another thread's set() — per-thread conns,
    # all tracked for close()
    def _conn(self):
        if self._closed:
            raise RuntimeError("TCPStore is closed")
        c = getattr(self._local, "c", None)
        if c is None:
            ip = socket.gethostbyname(self.host)
            c = self._lib.kv_client_connect(ip.encode(), self.port,
                                            int(self.timeout * 1000))
            if not c:
                raise TimeoutError(
                    f"TCPStore: cannot reach master at {self.host}:"
                    f"{self.port} within {self.timeout}s")
            self._local.c = c
            with self._conns_lock:
                self._all_conns.append(c)
        return c

    def set(self, key: str, value) -> None:
        if isinstance(value, str):
            value = value.encode()
        r = self._lib.kv_client_set(self._conn(), key.encode(), value,
                                    len(value))
        if r < 0:
            raise RuntimeError(f"TCPStore.set({key}) failed: {r}")

    def get(self, key: str, wait: bool = True) -> bytes:
        if wait:
            self.wait(key)
        # two-phase: small buffer first (rendezvous values are bytes-sized),
        # exact retry only for large values
        for size in (4096, _MAXVAL):
            buf = ctypes.create_string_buffer(size)
            n = self._lib.kv_client_get(self._conn(), key.encode(), buf,
                                        size)
            if n == -1:
                raise KeyError(key)
            if n < 0:
                raise RuntimeError(f"TCPStore.get({key}) failed: {n}")
            if n <= size:
                return buf.raw[:n]
        raise RuntimeError(f"TCPStore.get({key}): value exceeds {_MAXVAL}B")

    def add(self, key: str, amount: int = 1) -> int:
        out = ctypes.c_int64(0)
        r = self._lib.kv_client_add(self._conn(), key.encode(), amount,
                                    ctypes.byref(out))
        if r < 0:
            raise RuntimeError(f"TCPStore.add({key}) failed: {r}")
        return int(out.value)

    def wait(self, key: str, timeout: Optional[float] = None) -> None:
        t = self.timeout if timeout is None else timeout
        r = self._lib.kv_client_wait(self._conn(), key.encode(),
                                     int(t * 1000))
        if r == -2:
            raise TimeoutError(f"TCPStore.wait({key}): timed out after {t}s")
        if r < 0:
            raise RuntimeError(f"TCPStore.wait({key}) failed: {r}")

    def lease_set(self, key: str, value, ttl: float) -> None:
        """Set ``key`` with a server-side TTL: unless renewed by another
        lease_set within ``ttl`` seconds, the server expires it (the etcd
        lease analog — elastic heartbeats ride on this, so a dead node's
        key vanishes without any watcher-side clock bookkeeping)."""
        if isinstance(value, str):
            value = value.encode()
        r = self._lib.kv_client_lease_set(self._conn(), key.encode(), value,
                                          len(value), int(ttl * 1000))
        if r < 0:
            raise RuntimeError(f"TCPStore.lease_set({key}) failed: {r}")

    def watch(self, key: str, last_version: int = 0,
              timeout: Optional[float] = None):
        """Block until the key's version exceeds ``last_version`` — any
        set / add / lease_set / delete / lease expiry bumps it. Returns
        ``(version, value_bytes_or_None)``; raises TimeoutError on timeout
        (a sub-millisecond timeout still means "poll once", never "wait
        forever"). Pass the returned version back in to resume watching."""
        t = self.timeout if timeout is None else timeout
        ver = ctypes.c_int64(0)
        present = ctypes.c_int32(0)
        buf = ctypes.create_string_buffer(4096)
        n = self._lib.kv_client_watch(self._conn(), key.encode(),
                                      last_version, max(1, int(t * 1000)),
                                      buf, len(buf), ctypes.byref(ver),
                                      ctypes.byref(present))
        if n == -2:
            raise TimeoutError(
                f"TCPStore.watch({key}): no change past version "
                f"{last_version} within {t}s")
        if n < 0:
            raise RuntimeError(f"TCPStore.watch({key}) failed: {n}")
        if not present.value:
            return int(ver.value), None
        if n > len(buf):
            # oversized value: re-read in full (the version still tells the
            # caller which change woke them; a racing overwrite just means
            # an even fresher value)
            try:
                return int(ver.value), self.get(key, wait=False)
            except KeyError:
                return int(ver.value), None
        return int(ver.value), buf.raw[:n]

    def delete_key(self, key: str) -> bool:
        return self._lib.kv_client_del(self._conn(), key.encode()) > 0

    def num_keys(self) -> int:
        return int(self._lib.kv_client_numkeys(self._conn()))

    def barrier(self, name: str = "default", timeout: Optional[float] = None
                ) -> None:
        """All world_size participants rendezvous (add + wait pattern).
        Reusable: arrival number n maps to generation (n-1)//world_size,
        each generation gets its own done-key."""
        n = self.add(f"__barrier/{name}/count", 1)
        gen = (n - 1) // self.world_size
        if n % self.world_size == 0:
            self.set(f"__barrier/{name}/done/{gen}", b"1")
        self.wait(f"__barrier/{name}/done/{gen}", timeout)

    def close(self):
        """Shut down every connection (unblocking any thread mid-request
        with a clean error) without freeing native handles other threads
        may still be touching; the server, if hosted here, stops fully."""
        self._closed = True
        with self._conns_lock:
            for c in self._all_conns:
                self._lib.kv_client_shutdown(c)
            self._all_conns.clear()
        self._local = threading.local()
        if self._server is not None:
            self._server.stop()
            self._server = None


_global_store: Optional[TCPStore] = None
_global_lock = threading.Lock()


def create_or_get_global_tcp_store() -> TCPStore:
    """Parity: python/paddle/distributed/parallel.py:1099 — the process
    global store from the PADDLE_MASTER / PADDLE_TRAINER_* env contract."""
    global _global_store
    with _global_lock:
        if _global_store is None:
            ep = os.environ.get("PADDLE_MASTER", "")
            if not ep:
                eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
                ep = eps.split(",")[0] if eps else "127.0.0.1:0"
            host, port = ep.rsplit(":", 1)
            rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
            world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
            hosted = os.environ.get("PADDLE_MASTER_HOSTED", "0") == "1"
            _global_store = TCPStore(
                host, int(port),
                is_master=(rank == 0 and not hosted),
                world_size=world)
        return _global_store
