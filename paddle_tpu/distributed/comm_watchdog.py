"""Host-side communication watchdog.

Capability parity with the reference's CommTaskManager
(reference: paddle/phi/core/distributed/comm_task_manager.cc:67 +
nccl_comm_task.cc): background threads poll in-flight collectives for
timeout and abort the job with a diagnosable error instead of hanging.

TPU-native design: collectives are compiled into XLA programs, so there is
no per-collective task object to poll — the observable hang surface is a
device sync (``block_until_ready`` / host barrier) that never returns
(e.g. a peer host died mid all-reduce on a pod, or the TPU tunnel
dropped). The watchdog runs the sync on a worker thread with a deadline;
on expiry it fires the hang callback (elastic integration: mark the node
unhealthy so the launcher relaunches) and raises ``CommTimeoutError``.
"""
from __future__ import annotations

import time
from typing import Callable, Optional

import jax

from ..core import flags as _flags

__all__ = ["CommTimeoutError", "CommTaskManager",
           "get_comm_task_manager", "set_comm_task_manager"]

_flags.define_flag("comm_timeout_s", 0.0,
                   "watchdog deadline (seconds) for device syncs/barriers; "
                   "0 disables")


class CommTimeoutError(RuntimeError):
    """A device sync did not complete within the watchdog deadline
    (the reference aborts via the comm task's error state)."""


class CommTaskManager:
    def __init__(self, timeout_s: Optional[float] = None,
                 on_hang: Optional[Callable[[str, float], None]] = None):
        self._timeout = timeout_s
        self._on_hang = on_hang
        self._hang_count = 0
        self._pool = None  # one persistent watchdog worker, not per-call

    def _submit(self, fn):
        from concurrent.futures import ThreadPoolExecutor
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="comm-watchdog")
        return self._pool.submit(fn)

    @property
    def hang_count(self) -> int:
        return self._hang_count

    def _deadline(self, timeout_s):
        if timeout_s is not None:
            return timeout_s
        if self._timeout is not None:
            return self._timeout
        return float(_flags.get_flag("comm_timeout_s") or 0.0)

    def wait(self, value, desc: str = "collective",
             timeout_s: Optional[float] = None, waiter=None):
        """Block until ``value``'s device work completes, bounded by the
        deadline. ``waiter`` overrides the sync callable (tests / custom
        transports). Deadline <= 0 degrades to an unbounded sync."""
        deadline = self._deadline(timeout_s)
        sync = waiter if waiter is not None \
            else (lambda: jax.block_until_ready(value))
        if deadline <= 0:
            return sync()
        injected = _injected_hang(desc)
        if injected is not None:
            # fault harness: this sync "hangs" like a dead peer — only
            # consulted under a deadline, so it can never wedge a wait
            sync = injected

        from concurrent.futures import TimeoutError as FuturesTimeout
        start = time.monotonic()
        fut = self._submit(sync)
        try:
            return fut.result(deadline)  # device errors re-raise here
        except FuturesTimeout:
            self._hang_count += 1
            elapsed = time.monotonic() - start
            # the worker is stuck inside the sync: abandon this pool so the
            # next wait gets a fresh worker instead of queueing behind it
            pool, self._pool = self._pool, None
            pool.shutdown(wait=False)
            if self._on_hang is not None:
                try:
                    self._on_hang(desc, elapsed)
                except Exception:
                    pass
            self._notify_elastic(desc)
            raise CommTimeoutError(
                f"'{desc}' did not complete within {deadline:.1f}s "
                f"(waited {elapsed:.1f}s) — a peer may be down or the "
                "device link hung (reference: CommTaskManager watchdog)"
            ) from None

    def barrier(self, desc: str = "barrier",
                timeout_s: Optional[float] = None):
        """Deadline-bounded host barrier: a trivial device round-trip."""
        import jax.numpy as jnp
        return self.wait(jnp.zeros(()) + 0, desc=desc, timeout_s=timeout_s)

    def close(self) -> None:
        """Release the watchdog worker pool. Never waits: a worker stuck
        inside a hung sync would block a clean shutdown forever — the
        pool is abandoned exactly like the hang path abandons it."""
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    def __enter__(self) -> "CommTaskManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _notify_elastic(self, desc: str) -> None:
        """Elastic integration (reference: watchdog error propagation aborts
        training so the elastic manager relaunches): flag the local agent
        unhealthy if one is running."""
        try:
            from .fleet.elastic.manager import notify_comm_hang
        except Exception:
            return
        try:
            notify_comm_hang(desc)
        except Exception:
            pass


def _injected_hang(desc: str):
    """Fault-harness hook: a parked waiter when a sync-hang is armed for
    ``desc``, else None. Import is lazy and failure-proof — the watchdog
    must work even if the resilience package is unavailable."""
    try:
        from .resilience.faults import get_fault_injector
    except Exception:
        return None
    inj = get_fault_injector()
    if not inj.armed:
        return None
    return inj.sync_hang_waiter(desc)


_GLOBAL = CommTaskManager()


def get_comm_task_manager() -> CommTaskManager:
    return _GLOBAL


def set_comm_task_manager(m: CommTaskManager) -> None:
    global _GLOBAL
    _GLOBAL = m
