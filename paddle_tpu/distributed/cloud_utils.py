"""Cloud cluster helpers (parity: python/paddle/distributed/cloud_utils.py
— resolve the trainer cluster from PaddleCloud-style environment
variables; used by launchers running under a cloud scheduler). The
Cluster/Pod/Trainer shapes mirror the reference's launch_utils
structures (rank/addr/port/devices), self-contained here.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional

__all__ = ["Cluster", "Pod", "Trainer", "get_cloud_cluster",
           "get_cluster_and_pod"]


@dataclass
class Trainer:
    endpoint: str = ""
    rank: int = 0
    gpus: List[int] = field(default_factory=list)


@dataclass
class Pod:
    rank: int = 0
    addr: str = ""
    port: int = 0
    devices: List[int] = field(default_factory=list)
    trainers: List[Trainer] = field(default_factory=list)

    def endpoint(self) -> str:
        return f"{self.addr}:{self.port}"


@dataclass
class Cluster:
    hdfs: Optional[object] = None
    pods: List[Pod] = field(default_factory=list)

    def trainers_endpoints(self) -> List[str]:
        return [t.endpoint for p in self.pods for t in p.trainers]

    def world_size(self) -> int:
        return sum(len(p.trainers) for p in self.pods)


def _get_trainers_num():
    return int(os.getenv("PADDLE_TRAINERS_NUM", "1"))


def get_cloud_cluster(args_node_ips=None, args_node_ip=None, args_port=None,
                      selected_devices=None):
    """Build the (cluster, pod) pair from the cloud env contract
    (PADDLE_TRAINERS / POD_IP / PADDLE_PORT), falling back to the CLI
    args (reference cloud_utils.py:27)."""
    node_ips = os.getenv("PADDLE_TRAINERS")
    node_ips = (node_ips.split(",") if node_ips
                else (args_node_ips.split(",")
                      if isinstance(args_node_ips, str) else
                      list(args_node_ips or ["127.0.0.1"])))
    node_ip = os.getenv("POD_IP", args_node_ip or node_ips[0])
    port = int(os.getenv("PADDLE_PORT", args_port or 6170))
    devices = [int(d) for d in (selected_devices or [0])]

    cluster = Cluster()
    this_pod = None
    rank_base = 0
    for rank, ip in enumerate(node_ips):
        pod = Pod(rank=rank, addr=ip, port=port, devices=list(devices))
        for i, d in enumerate(devices):
            pod.trainers.append(Trainer(
                endpoint=f"{ip}:{port + i}", rank=rank_base + i, gpus=[d]))
        rank_base += len(devices)
        cluster.pods.append(pod)
        if ip == node_ip:
            this_pod = pod
    return cluster, this_pod or cluster.pods[0]


def get_cluster_and_pod(args):
    """(reference cloud_utils.py:114)"""
    return get_cloud_cluster(
        getattr(args, "cluster_node_ips", None),
        getattr(args, "node_ip", None),
        getattr(args, "started_port", None),
        getattr(args, "selected_devices", None))
