"""Crash-consistent checkpoint commits.

Protocol (every durable mutation goes through the injectable ``Fs``
layer, so the fault harness can kill the save at any byte offset)::

    <root>/
      step_12/            committed: COMMITTED marker + merged metadata
      step_14.tmp/        staging: being written, or torn by a crash
      latest              pointer file, atomically replaced last

    write order (coordinator):
      1  step_N.tmp/shard_r*.npz, meta_r*.json   (per-rank writers)
      2  step_N.tmp/extras.pkl, metadata.json    (merge of rank tables)
      3  step_N.tmp/COMMITTED                    (marker written LAST)
      4  rename step_N.tmp -> step_N             (atomic dir rename)
      5  latest.tmp -> latest                    (atomic pointer flip)

A kill anywhere before 4 leaves a ``.tmp`` staging dir that is NEVER
eligible for resume (``latest_checkpoint`` only considers ``step_N``
dirs); a kill between 4 and 5 leaves a committed ``step_N`` that the
descending scan finds without the pointer. Either way the previous
committed checkpoint survives intact.

``latest_checkpoint`` re-validates the manifest on every resolve (marker
parses, uid matches the merged table, every referenced shard file
exists) and falls back to the previous committed step on corruption —
the pointer file is a human/ops hint, never trusted over validation.
"""
from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Optional, Tuple

from ..checkpoint.metadata import Metadata
from ..checkpoint.save_state_dict import (coordinator_finalize,
                                          write_rank_files)
from ..checkpoint.utils import snapshot_state_dict
from .faults import get_fs

__all__ = ["COMMITTED_MARKER", "FAILED_MARKER", "LATEST_POINTER",
           "HostSnapshot", "take_snapshot", "write_committed_checkpoint",
           "validate_checkpoint_dir", "latest_checkpoint",
           "list_committed_steps", "step_dir", "staging_dir",
           "CheckpointTransport", "LocalFsTransport", "load_for_serving"]

COMMITTED_MARKER = "COMMITTED"
FAILED_MARKER = "FAILED"
LATEST_POINTER = "latest"

_STEP_DIR_RE = re.compile(r"^step_(\d+)$")
_STAGING_DIR_RE = re.compile(r"^step_(\d+)\.tmp$")


def step_dir(step: int) -> str:
    return f"step_{int(step)}"


def staging_dir(step: int) -> str:
    return f"step_{int(step)}.tmp"


@dataclasses.dataclass
class HostSnapshot:
    """One rank's checkpoint data, already on host RAM: the write-behind
    thread needs no device access (and therefore no device sync) to make
    it durable."""
    chunks: dict        # npz_key -> np.ndarray
    meta: Metadata      # this rank's chunk table
    extras: dict        # non-tensor leaves (coordinator writes these)
    uid: int
    nbytes: int


def take_snapshot(state_dict, rank: int = 0, uid: int = 0) -> HostSnapshot:
    """Device→host snapshot (ONE batched ``jax.device_get`` — the only
    point the training loop blocks for a save)."""
    chunks, meta, extras = snapshot_state_dict(state_dict,
                                               f"shard_r{rank}.npz")
    nbytes = sum(int(a.nbytes) for a in chunks.values())
    return HostSnapshot(chunks, meta, extras, int(uid), nbytes)


def write_committed_checkpoint(snap: HostSnapshot, root: str, step: int,
                               *, rank: int = 0, ranks=(0,),
                               coordinator: int = 0, fs=None,
                               merge_timeout_s: float = 300.0) -> str:
    """Write ``snap`` into ``<root>/step_N.tmp`` and commit it (see the
    module docstring for the write order). Returns the committed dir.

    Non-coordinator ranks return after their shard+table writes; the
    coordinator merges, writes the marker, renames, and flips the
    pointer."""
    fs = fs or get_fs()
    staging = os.path.join(root, staging_dir(step))
    final = os.path.join(root, step_dir(step))
    fs.makedirs(root)
    if rank == coordinator and os.path.isdir(staging):
        # a previous crashed attempt at this very step: torn by
        # construction (no rename happened), safe to clear
        fs.rmtree(staging, label="gc-torn-staging")
    write_rank_files(staging, rank, snap.chunks, snap.meta, snap.uid,
                     fs=fs)
    if rank != coordinator:
        return final
    coordinator_finalize(staging, snap.extras, ranks, snap.uid, fs=fs,
                         merge_timeout_s=merge_timeout_s)
    marker = {
        "step": int(step),
        "uid": int(snap.uid),
        "world_size": len(ranks),
        "ranks": sorted(int(r) for r in ranks),
        "files": sorted(
            [f"shard_r{r}.npz" for r in ranks]
            + [f"meta_r{r}.json" for r in ranks]
            + ["metadata.json", "extras.pkl"]),
    }
    tmp = os.path.join(staging, f".{COMMITTED_MARKER}.tmp")
    fs.write_bytes(tmp, json.dumps(marker).encode(), label="marker.tmp")
    fs.replace(tmp, os.path.join(staging, COMMITTED_MARKER),
               label="marker")
    if os.path.isdir(final):
        # re-save of an already-committed step (uid collision / retry):
        # clear the old dir so the rename below can land
        fs.rmtree(final, label="gc-stale-final")
    fs.replace(staging, final, label="commit-rename")
    ptmp = os.path.join(root, f".{LATEST_POINTER}.tmp")
    fs.write_bytes(ptmp, step_dir(step).encode(), label="pointer.tmp")
    fs.replace(ptmp, os.path.join(root, LATEST_POINTER), label="pointer")
    return final


def validate_checkpoint_dir(path: str,
                            expect_step: Optional[int] = None
                            ) -> Tuple[bool, str]:
    """Is ``path`` a crash-consistent committed checkpoint? Checks the
    COMMITTED manifest (parses, step matches the dir name, uid matches
    the merged table) and that every shard file the merged table
    references exists. Returns (ok, reason)."""
    if os.path.exists(os.path.join(path, FAILED_MARKER)):
        return False, "FAILED marker present"
    marker_p = os.path.join(path, COMMITTED_MARKER)
    if not os.path.exists(marker_p):
        return False, "no COMMITTED marker"
    try:
        with open(marker_p) as f:
            marker = json.load(f)
    except (json.JSONDecodeError, OSError) as e:
        return False, f"COMMITTED marker unreadable: {e}"
    if expect_step is not None and marker.get("step") != int(expect_step):
        return False, (f"marker step {marker.get('step')} != dir step "
                       f"{expect_step}")
    meta_p = os.path.join(path, "metadata.json")
    try:
        with open(meta_p) as f:
            meta_json = json.load(f)
    except (json.JSONDecodeError, OSError) as e:
        return False, f"metadata.json unreadable: {e}"
    if meta_json.get("uid") != marker.get("uid"):
        return False, (f"uid mismatch: metadata {meta_json.get('uid')} "
                       f"!= marker {marker.get('uid')}")
    for fn in marker.get("files", []):
        if not os.path.exists(os.path.join(path, fn)):
            return False, f"manifest file missing: {fn}"
    meta = Metadata.from_json(meta_json)
    for name, tm in meta.state_dict_metadata.items():
        for _, idx in tm.chunks:
            if not os.path.exists(os.path.join(path, idx.file_name)):
                return False, (f"shard file missing: {idx.file_name} "
                               f"(referenced by {name!r})")
    return True, "ok"


def list_committed_steps(root: str):
    """Candidate committed dirs, ``[(step, name)]`` newest first —
    ``.tmp`` staging dirs are never candidates."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    out = []
    for name in names:
        m = _STEP_DIR_RE.match(name)
        if m and os.path.isdir(os.path.join(root, name)):
            out.append((int(m.group(1)), name))
    out.sort(reverse=True)
    return out


def list_staging_dirs(root: str):
    """``[(step, name)]`` of staging dirs (torn unless a write is in
    flight), newest first."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    out = []
    for name in names:
        m = _STAGING_DIR_RE.match(name)
        if m and os.path.isdir(os.path.join(root, name)):
            out.append((int(m.group(1)), name))
    out.sort(reverse=True)
    return out


def latest_checkpoint(root: str) -> Optional[Tuple[int, str]]:
    """Newest committed, VALIDATED checkpoint under ``root`` as
    ``(step, path)``, or None. Walks committed dirs newest-first and
    falls back past any that fail manifest validation (torn by a crash,
    corrupted on disk) — a torn save can therefore never be resumed
    from, only the previous committed one."""
    for step, name in list_committed_steps(root):
        path = os.path.join(root, name)
        ok, _why = validate_checkpoint_dir(path, expect_step=step)
        if ok:
            return step, path
    return None


class CheckpointTransport:
    """Where committed checkpoints live, behind three methods.

    The commit protocol above assumes one shared filesystem (rank files
    meet in ``step_N.tmp``, resume reads ``step_N`` in place). This seam
    is what lets a SERVING host on another machine consume the same
    committed checkpoints training writes: ``resolve_latest`` finds the
    newest validated step, ``fetch`` makes one committed step dir
    locally readable, ``list_steps`` enumerates candidates. The local-fs
    default is the identity transport; an object-store backend (download
    into a local cache dir, validate, return the cache path) implements
    the same three methods — that backend is the ROADMAP remainder, the
    seam is what lands here."""

    def list_steps(self, root: str):
        """Candidate committed steps under ``root``: ``[(step, name)]``
        newest first."""
        raise NotImplementedError

    def resolve_latest(self, root: str) -> Optional[Tuple[int, str]]:
        """Newest committed VALIDATED checkpoint under ``root`` as
        ``(step, path)`` — ``path`` is transport-scoped until
        ``fetch``ed."""
        raise NotImplementedError

    def fetch(self, path: str) -> str:
        """Make the committed checkpoint at transport-scoped ``path``
        readable on the local filesystem; returns the local dir."""
        raise NotImplementedError


class LocalFsTransport(CheckpointTransport):
    """The shared-filesystem default: paths are already local."""

    def list_steps(self, root: str):
        return list_committed_steps(root)

    def resolve_latest(self, root: str) -> Optional[Tuple[int, str]]:
        return latest_checkpoint(root)

    def fetch(self, path: str) -> str:
        return path


def load_for_serving(path: str, target, *,
                     transport: Optional[CheckpointTransport] = None
                     ) -> int:
    """Cold-start (or hot-swap) serving weights from a committed
    training checkpoint.

    ``path`` is either a checkpoint ROOT (the newest committed,
    validated step is resolved — torn saves are skipped, exactly like
    training resume) or one specific committed step dir (validated
    before loading). ``target`` is a ``Layer`` — its live state-dict
    tensors are loaded in place, so a serving host can swap weights
    between steps without rebuilding servers — or a plain state dict.
    Uses the same reshard-on-load path training resume uses, so a
    single-host server restores shards a multi-host trainer wrote.
    Name contract: the checkpoint must hold the names ``target``
    exposes — a checkpoint of ``model.state_dict()`` loads into the
    model directly; for a ``run_steps``-layout checkpoint
    (``{"params": ..., "opt_state": ...}``) pass
    ``target={"params": model.state_dict()}``.
    Returns the loaded step. Raises ``FileNotFoundError`` when nothing
    committed exists and ``ValueError`` for a torn/invalid step dir."""
    transport = transport or LocalFsTransport()
    base = os.path.basename(os.path.normpath(str(path)))
    m = _STEP_DIR_RE.match(base)
    if m:
        step = int(m.group(1))
        local = transport.fetch(str(path))
        ok, why = validate_checkpoint_dir(local, expect_step=step)
        if not ok:
            raise ValueError(
                f"checkpoint {path!r} is not a committed save: {why}")
    else:
        found = transport.resolve_latest(str(path))
        if found is None:
            raise FileNotFoundError(
                f"no committed checkpoint under {path!r}")
        step, remote = found
        local = transport.fetch(remote)
    sd = target.state_dict() if callable(getattr(target, "state_dict",
                                                 None)) else target
    # fail loudly on a name-contract mismatch BEFORE loading:
    # load_state_dict tolerates missing keys (reference behavior), so a
    # run_steps-layout checkpoint loaded into a bare model would
    # otherwise "succeed" with zero tensors restored — and a whole fleet
    # serving random weights still passes bitwise-parity drills
    try:
        with open(os.path.join(local, "metadata.json")) as f:
            saved = set(json.load(f).get("state_dict_metadata", {}))
    except (OSError, json.JSONDecodeError) as e:
        raise ValueError(
            f"checkpoint {path!r} metadata unreadable: {e}") from e
    from ..checkpoint.utils import flatten_state_dict
    flat, _mapping = flatten_state_dict(sd)
    if saved and not (saved & set(flat)):
        raise ValueError(
            "checkpoint/target name mismatch: none of the "
            f"{len(saved)} saved tensors match the target's "
            f"{len(flat)} names (saved e.g. "
            f"{sorted(saved)[:3]}, target e.g. "
            f"{sorted(flat)[:3]}) — save model.state_dict(), or pass "
            "target={'params': model.state_dict()} for a "
            "run_steps-layout checkpoint")
    from ..checkpoint.load_state_dict import load_state_dict
    load_state_dict(sd, local)
    return step


def read_latest_pointer(root: str) -> Optional[str]:
    """The ``latest`` pointer's target dir name (a hint for humans and
    dashboards; resume resolution always goes through
    ``latest_checkpoint``'s validation instead)."""
    try:
        with open(os.path.join(root, LATEST_POINTER)) as f:
            return f.read().strip() or None
    except OSError:
        return None
