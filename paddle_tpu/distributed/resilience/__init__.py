"""paddle_tpu.distributed.resilience — preemption-tolerant training.

The loop the rest of ``distributed/`` leaves open, closed: async
checkpointing with crash-consistent commits (``AsyncCheckpointer`` +
the ``commit`` protocol), interval/rotation/GC/resume management
(``CheckpointManager``), and the deterministic fault-injection harness
(``faults``) the tests drive — kill-at-nth-write, sync-hang into the
comm watchdog, heartbeat-drop into the elastic manager.

Recovery story: ``models.trainer.run_steps(checkpoint_manager=,
on_fault=)`` — a ``CommTimeoutError`` flows watchdog →
``notify_comm_hang`` → elastic restart signal, and the fault handler
restores ``latest_checkpoint`` with reshard-on-restore into the (possibly
shrunk) new world, resuming within one checkpoint interval.
"""
from .async_ckpt import (AsyncCheckpointer,  # noqa: F401
                         CheckpointWriteError,
                         default_async_checkpointer)
from .commit import (COMMITTED_MARKER, FAILED_MARKER,  # noqa: F401
                     LATEST_POINTER, CheckpointTransport, HostSnapshot,
                     LocalFsTransport, latest_checkpoint,
                     list_committed_steps, load_for_serving,
                     read_latest_pointer, staging_dir, step_dir,
                     take_snapshot, validate_checkpoint_dir,
                     write_committed_checkpoint)
from .faults import (FaultInjector, Fs, InjectedCrash,  # noqa: F401
                     fault_injection, get_fault_injector, get_fs)
from .manager import CheckpointManager  # noqa: F401
from .metrics import ResilienceMetrics  # noqa: F401
