"""Async distributed checkpointing: snapshot to host RAM, write behind.

The training loop pays exactly one cost per save — the device→host
snapshot (one batched ``device_get`` of this rank's replica-0 shards) —
and the write-behind thread does every disk write, through the
crash-consistent commit protocol in ``commit.py``.

Double-buffered and bounded: ``save()`` first waits for the previous
write to finish (surfacing its error if it failed), so host RAM holds at
most ONE pending checkpoint copy no matter how small the save interval —
a slow disk backpressures the save cadence instead of blowing up RSS.

Background-writer failures are never swallowed: they re-raise as
``CheckpointWriteError`` from the NEXT ``save()``/``wait()``/``poll()``
on the training thread.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Optional

from ..checkpoint.save_state_dict import (coordinator_finalize,
                                          resolve_participants,
                                          write_rank_files)
from .commit import take_snapshot, write_committed_checkpoint

__all__ = ["AsyncCheckpointer", "CheckpointWriteError",
           "default_async_checkpointer"]

_STOP = object()


class CheckpointWriteError(RuntimeError):
    """A write-behind checkpoint job failed. Raised on the training
    thread at the next save/wait/poll — the failed step's staging dir
    stays torn (never resumable); the previous committed checkpoint is
    untouched."""


class _Job:
    __slots__ = ("fn", "done", "error")

    def __init__(self, fn: Callable[[], None]):
        self.fn = fn
        self.done = threading.Event()
        self.error: Optional[BaseException] = None

    def run(self) -> None:
        try:
            self.fn()
        except BaseException as e:
            # InjectedCrash (a BaseException) included: the simulated
            # kill leaves the staging dir torn, exactly like a real one
            self.error = e
        finally:
            # drop the closure NOW: it captures the HostSnapshot, and any
            # lingering reference (worker local, _inflight) would keep a
            # second full checkpoint copy in host RAM past completion
            self.fn = None
            self.done.set()


class AsyncCheckpointer:
    """One write-behind worker + a one-slot job queue (see module
    docstring). Not thread-safe for concurrent ``save()`` calls — it
    belongs to one training loop, the ``CheckpointManager``'s."""

    def __init__(self, metrics=None):
        self._metrics = metrics
        self._queue: "queue.Queue" = queue.Queue(maxsize=1)
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._inflight: Optional[_Job] = None
        if metrics is not None:
            metrics.set_depth_gauge(self._queue.qsize)

    # -- worker ------------------------------------------------------------
    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._write_loop, name="ckpt-write-behind",
                    daemon=True)
                self._thread.start()

    def _write_loop(self) -> None:
        while True:
            try:
                job = self._queue.get(timeout=1.0)
            except queue.Empty:
                continue  # periodic wake keeps shutdown prompt (GL302)
            if job is _STOP:
                return
            job.run()

    def _submit(self, job: _Job) -> None:
        self._ensure_thread()
        with self._lock:
            self._inflight = job
        self._queue.put(job)

    # -- error surfacing ---------------------------------------------------
    def _take_done_job(self, block: bool) -> Optional[_Job]:
        with self._lock:
            job = self._inflight
            if job is None:
                return None
            if not block and not job.done.is_set():
                return None
            self._inflight = None
        job.done.wait()
        return job

    def _surface(self, job: Optional[_Job]) -> None:
        if job is None or job.error is None:
            return
        if self._metrics is not None:
            self._metrics.inc("write_errors")
        raise CheckpointWriteError(
            f"background checkpoint write failed: {job.error}"
        ) from job.error

    def wait(self) -> None:
        """Block until the in-flight write finishes; raise its error."""
        self._surface(self._take_done_job(block=True))

    def poll(self) -> None:
        """Non-blocking: raise the in-flight write's error if it already
        failed (lets every ``maybe_save`` — saving or not — surface
        background failures promptly)."""
        self._surface(self._take_done_job(block=False))

    # -- saves -------------------------------------------------------------
    def save(self, state_dict, root: str, step: int, *, uid=None,
             process_group=None, coordinator_rank: int = 0,
             merge_timeout_s: float = 300.0,
             on_commit: Optional[Callable[[int, str], None]] = None
             ) -> bool:
        """Snapshot now, commit behind (protocol in ``commit.py``).
        ``on_commit(step, path)`` runs on the write-behind thread after
        the pointer flip. Returns False when this process is not a
        participant."""
        parts = resolve_participants(process_group, coordinator_rank)
        if parts is None:
            return False
        rank, ranks, coordinator = parts
        self.wait()  # the one-in-flight bound + error surfacing
        snap = self._snapshot(state_dict, rank,
                              step if uid is None else uid)
        metrics = self._metrics

        def job():
            t0 = time.perf_counter()
            final = write_committed_checkpoint(
                snap, root, step, rank=rank, ranks=ranks,
                coordinator=coordinator, merge_timeout_s=merge_timeout_s)
            # only the coordinator's return means COMMITTED (other ranks
            # return after their shard writes, before the marker exists)
            # — commit metrics elsewhere would report commits that may
            # never have happened
            if metrics is not None and rank == coordinator:
                metrics.observe("commit_s", time.perf_counter() - t0)
                metrics.inc("commits")
                metrics.set_last_committed_step(step)
            if on_commit is not None:
                on_commit(step, final)

        self._submit(_Job(job))
        return True

    def save_legacy(self, state_dict, path: str, *, uid: int, rank: int,
                    ranks, coordinator: int) -> None:
        """The ``save_state_dict(async_save=True)`` path: identical final
        layout to the sync save (no staging/commit protocol — flat dir,
        pre-existing contract), but snapshotted now and written behind.
        An atexit hook waits for durability before interpreter exit."""
        self.wait()
        snap = self._snapshot(state_dict, rank, uid)

        def job():
            write_rank_files(path, rank, snap.chunks, snap.meta, snap.uid)
            if rank == coordinator:
                coordinator_finalize(path, snap.extras, ranks, snap.uid)

        self._submit(_Job(job))
        _register_atexit_wait(self)

    def _snapshot(self, state_dict, rank: int, uid: int):
        t0 = time.perf_counter()
        snap = take_snapshot(state_dict, rank=rank, uid=uid)
        if self._metrics is not None:
            self._metrics.observe("snapshot_s", time.perf_counter() - t0)
            self._metrics.inc("snapshots")
        return snap

    # -- lifecycle ---------------------------------------------------------
    def close(self, wait: bool = True) -> None:
        """Drain (surfacing any pending error when ``wait=True``) and
        stop the write-behind thread. The thread is stopped even when
        the pending error raises — close() must never leak it."""
        try:
            if wait:
                self.wait()
        finally:
            with self._lock:
                thread, self._thread = self._thread, None
            if thread is not None and thread.is_alive():
                self._queue.put(_STOP)
                thread.join(timeout=10.0)

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close(wait=exc[0] is None)


_default: Optional[AsyncCheckpointer] = None
_default_lock = threading.Lock()
_atexit_registered = False


def default_async_checkpointer() -> AsyncCheckpointer:
    """Shared checkpointer behind bare ``save_state_dict(async_save=True)``
    calls; its atexit hook blocks until the last write is durable."""
    global _default
    with _default_lock:
        if _default is None:
            _default = AsyncCheckpointer()
        return _default


def _register_atexit_wait(ckpt: AsyncCheckpointer) -> None:
    global _atexit_registered
    with _default_lock:
        if _atexit_registered:
            return
        _atexit_registered = True
    import atexit

    def _drain():
        try:
            ckpt.wait()
        except Exception as e:
            import sys
            print(f"paddle_tpu: async checkpoint write failed at exit: "
                  f"{e}", file=sys.stderr)

    atexit.register(_drain)
