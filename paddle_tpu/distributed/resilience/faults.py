"""Deterministic fault-injection harness for preemption-tolerance tests.

Four injector families, all armed on one process-global ``FaultInjector``
(tests drive it via ``FaultInjector.scoped()`` — or the legacy
``fault_injection()`` wrapper — which restores the prior state on exit so
a failing test can't leak an armed fault into the next):

- **kill-at-nth-write** — every durable checkpoint mutation funnels
  through the ``Fs`` layer below; the injector crashes the "process"
  (raises ``InjectedCrash``, a ``BaseException`` so production
  ``except Exception`` cleanup can't accidentally survive a simulated
  SIGKILL) immediately before the nth write, optionally after flushing
  half the bytes — a genuinely torn file at a byte offset, not a tidy
  missing one.
- **sync-hang** — ``CommTaskManager.wait`` consults the injector: an
  armed matching description swaps the device sync for a parked wait, so
  the watchdog deadline fires exactly like a peer dying mid-collective.
  The parked waiter blocks on an Event with a bounded timeout and
  ``reset()`` releases it — an injected hang can never wedge interpreter
  exit behind a stuck watchdog worker.
- **heartbeat-drop** — the elastic ``_beat_loop`` skips lease renewals
  for armed node ids, so peers observe the node dead without killing it.
- **backend faults** — the serving router's in-process backends consult
  ``backend_action()`` on every operation (submit, probe, per-token
  liveness check): ``arm_backend_kill`` makes a backend dead from now on
  (every op fails, simulating host death mid-request), ``arm_backend_slow``
  delays each op, ``arm_backend_hang`` blackholes it (ops block until the
  caller's bounded timeout — the probe-timeout path), and
  ``arm_backend_flap`` alternates dead/alive phases every ``period``
  consultations. ``heal_backend`` clears one backend's faults so breaker
  half-open recovery drills can bring it back.
- **socket faults** — the wire-level siblings of the backend faults,
  consulted by the serving transport's fault proxy
  (``serving.transport.FaultProxy``) per accepted connection and per
  forwarded chunk, so the PR 10 drills re-run across REAL sockets:
  ``arm_socket_blackhole`` (new connects refused, established
  connections park every byte until heal — the host that stops
  answering without closing anything), ``arm_socket_reset`` (next
  forwarded chunk hard-closes the connection with an RST — death
  mid-stream), ``arm_socket_trickle`` (bytes dribble through at a
  bounded rate — the pathological slow link), and ``arm_socket_flap``
  (accepts alternate refuse/allow phases every ``period`` connection
  attempts — the flapping link). ``heal_socket`` clears one proxy's
  fault and releases parked forwarders.

``arm_slow_disk`` is the latency sibling of the kill injector: it delays
every ``Fs`` write, which is how tests prove the write-behind thread —
not the training loop — absorbs disk time.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Callable, Optional

__all__ = ["InjectedCrash", "FaultInjector", "Fs", "get_fault_injector",
           "get_fs", "fault_injection"]


class InjectedCrash(BaseException):
    """Simulated process death mid-write (fault-injection only).

    Deliberately NOT an ``Exception``: a real SIGKILL gives cleanup code
    no chance to run, so generic ``except Exception`` recovery in the
    write path must not be able to "survive" an injected kill either."""


class Fs:
    """The durable-mutation layer for checkpoint writes.

    Every byte that reaches disk during a checkpoint save goes through
    one of these ops, each a named write boundary the injector can kill
    at. Disarmed cost is one locked flag check per file operation — per
    save, a handful."""

    def __init__(self, injector: Optional["FaultInjector"] = None):
        self._injector = injector

    def _check(self, label: str, path: str, data: Optional[bytes] = None):
        inj = self._injector or get_fault_injector()
        if inj.armed:
            inj.on_write(label, path, data)
        else:
            inj.count_write()

    def makedirs(self, path: str, label: str = "mkdir") -> None:
        self._check(label, path)
        os.makedirs(path, exist_ok=True)

    def write_bytes(self, path: str, data: bytes, label: str = "write"
                    ) -> None:
        self._check(label, path, data)
        with open(path, "wb") as f:
            f.write(data)

    def write_stream(self, path: str, writer, label: str = "write"
                     ) -> None:
        """Streaming write: ``writer(fileobj)`` produces the payload
        directly into the file — no full in-RAM materialization for
        multi-GB shard archives. Only when a kill is armed is the
        payload buffered first, so the injector can tear it at a byte
        offset like any other boundary."""
        inj = self._injector or get_fault_injector()
        if inj.armed:
            import io as _io
            buf = _io.BytesIO()
            writer(buf)
            inj.on_write(label, path, buf.getvalue())  # may crash/tear
            with open(path, "wb") as f:
                f.write(buf.getvalue())
        else:
            inj.count_write()
            with open(path, "wb") as f:
                writer(f)

    def replace(self, src: str, dst: str, label: str = "replace") -> None:
        self._check(label, dst)
        os.replace(src, dst)

    def remove(self, path: str, label: str = "remove") -> None:
        self._check(label, path)
        try:
            os.remove(path)
        except FileNotFoundError:
            pass

    def rmtree(self, path: str, label: str = "rmtree") -> None:
        self._check(label, path)
        import shutil
        shutil.rmtree(path, ignore_errors=True)


class FaultInjector:
    """Process-global, deterministic fault arming (see module docstring).

    ``writes_seen`` counts every ``Fs`` boundary crossed since the last
    ``reset()`` — tests run one clean save to enumerate the boundaries,
    then re-run with ``arm_kill_at_write(n)`` for every n."""

    _HANG_MAX_S = 60.0  # parked waiters always wake: never wedge exit

    def __init__(self):
        self._lock = threading.Lock()
        self._hang_release = threading.Event()
        self._reset_locked()

    def _reset_locked(self):
        self._kill_at: Optional[int] = None
        self._kill_partial = True
        self._write_count = 0
        self._slow_disk_s = 0.0
        self._hang_match: Optional[str] = None
        self._hang_after = 0
        self._hang_times = 0
        self._hang_seen = 0
        self._dropped_heartbeats: set = set()
        self._backend_faults: dict = {}
        self._socket_faults: dict = {}
        self.crashes = 0
        self.hangs_fired = 0
        self.heartbeats_dropped = 0
        self.backend_ops_faulted = 0
        self.socket_ops_faulted = 0

    def reset(self) -> None:
        """Disarm everything and release any parked hang waiters."""
        with self._lock:
            self._hang_release.set()
            self._hang_release = threading.Event()
            self._reset_locked()

    # every field reset()/scoped() must cover; a new fault kind that adds
    # state registers it here so scopes can't leak it
    _SCOPED_FIELDS = ("_kill_at", "_kill_partial", "_write_count",
                      "_slow_disk_s", "_hang_match", "_hang_after",
                      "_hang_times", "_hang_seen", "crashes", "hangs_fired",
                      "heartbeats_dropped", "backend_ops_faulted",
                      "socket_ops_faulted")

    @contextlib.contextmanager
    def scoped(self):
        """``with get_fault_injector().scoped() as inj: inj.arm_...()`` —
        snapshots the injector on entry, enters the scope disarmed with
        zeroed counters (so ``writes_seen`` and friends are deterministic
        inside), and restores the snapshot on exit, releasing any hang
        waiters parked inside the scope. A failing test can never leak an
        armed fault into the next test, and nesting a scope inside an
        armed outer scope hands the outer arming back intact on exit."""
        with self._lock:
            saved = {f: getattr(self, f) for f in self._SCOPED_FIELDS}
            saved["_dropped_heartbeats"] = set(self._dropped_heartbeats)
            saved["_backend_faults"] = {k: dict(v) for k, v in
                                        self._backend_faults.items()}
            saved["_socket_faults"] = {k: dict(v) for k, v in
                                       self._socket_faults.items()}
            self._hang_release.set()
            self._hang_release = threading.Event()
            self._reset_locked()
        try:
            yield self
        finally:
            with self._lock:
                self._hang_release.set()
                self._hang_release = threading.Event()
                for f, v in saved.items():
                    setattr(self, f, v)

    @property
    def armed(self) -> bool:
        with self._lock:
            return (self._kill_at is not None or self._slow_disk_s > 0.0
                    or self._hang_match is not None
                    or bool(self._dropped_heartbeats)
                    or bool(self._backend_faults)
                    or bool(self._socket_faults))

    @property
    def writes_seen(self) -> int:
        with self._lock:
            return self._write_count

    # -- kill-at-nth-write -------------------------------------------------
    def arm_kill_at_write(self, n: int, partial: bool = True) -> None:
        """Crash at the nth (0-based) ``Fs`` boundary crossed from now.
        ``partial=True`` flushes half the payload first when the boundary
        carries bytes — the torn-file case."""
        with self._lock:
            self._kill_at = int(n)
            self._kill_partial = partial
            self._write_count = 0

    def arm_slow_disk(self, seconds: float) -> None:
        """Delay every ``Fs`` write by ``seconds`` (injected slow disk)."""
        with self._lock:
            self._slow_disk_s = float(seconds)

    def count_write(self) -> None:
        with self._lock:
            self._write_count += 1

    def on_write(self, label: str, path: str,
                 data: Optional[bytes] = None) -> None:
        with self._lock:
            n = self._write_count
            self._write_count += 1
            kill = self._kill_at is not None and n >= self._kill_at
            delay = self._slow_disk_s
            partial = self._kill_partial
            if kill:
                self.crashes += 1
        if delay > 0.0:
            time.sleep(delay)
        if kill:
            if data is not None and partial and len(data) > 1:
                # flush a prefix so the surviving file is torn at a byte
                # offset, not merely absent
                with open(path, "wb") as f:
                    f.write(data[:len(data) // 2])
            raise InjectedCrash(
                f"injected kill at write #{n} ({label}: {path})")

    # -- sync-hang ---------------------------------------------------------
    def arm_sync_hang(self, match: str = "", after: int = 0,
                      times: int = 1) -> None:
        """Hang device syncs whose watchdog description contains
        ``match``: skip the first ``after`` matching waits, then hang the
        next ``times`` of them."""
        with self._lock:
            self._hang_match = match
            self._hang_after = int(after)
            self._hang_times = int(times)
            self._hang_seen = 0

    def sync_hang_waiter(self, desc: str) -> Optional[Callable[[], None]]:
        """The waiter ``CommTaskManager.wait`` should run instead of the
        real sync, or None when this wait is not being hung."""
        with self._lock:
            if self._hang_match is None or self._hang_match not in desc:
                return None
            seen = self._hang_seen
            self._hang_seen += 1
            if seen < self._hang_after:
                return None
            if seen >= self._hang_after + self._hang_times:
                return None
            self.hangs_fired += 1
            release = self._hang_release
        return lambda: release.wait(self._HANG_MAX_S)

    # -- serving-router backend faults -------------------------------------
    def arm_backend_kill(self, backend_id: str) -> None:
        """The backend is dead from now on: every consulted operation
        fails, including in-flight decode streams at their next liveness
        check — host death mid-request."""
        with self._lock:
            self._backend_faults[str(backend_id)] = {"mode": "kill"}

    def arm_backend_slow(self, backend_id: str, seconds: float) -> None:
        """Delay every consulted operation by ``seconds`` (a slow but
        live backend — degrades, never dies)."""
        with self._lock:
            self._backend_faults[str(backend_id)] = {
                "mode": "slow", "seconds": float(seconds)}

    def arm_backend_hang(self, backend_id: str) -> None:
        """Blackhole the backend: consulted operations block until the
        caller's own bounded timeout expires (probe timeout / request
        deadline), exactly like a host that stops answering without
        closing connections."""
        with self._lock:
            self._backend_faults[str(backend_id)] = {"mode": "hang"}

    def arm_backend_flap(self, backend_id: str, period: int = 3) -> None:
        """Alternate dead/alive phases every ``period`` consultations,
        starting dead — the link-flap pattern that exercises breaker
        reopen and retry-budget behavior."""
        with self._lock:
            self._backend_faults[str(backend_id)] = {
                "mode": "flap", "period": max(1, int(period)), "count": 0}

    def heal_backend(self, backend_id: str) -> None:
        """Clear one backend's fault (and release its parked hang
        waiters) — the recovery half of a breaker open→half-open→closed
        drill."""
        with self._lock:
            self._backend_faults.pop(str(backend_id), None)
            self._hang_release.set()
            self._hang_release = threading.Event()

    def backend_action(self, backend_id: str):
        """What an armed fault does to this backend operation:
        ``None`` (healthy), ``("kill",)`` (fail now), ``("slow", s)``
        (delay then proceed), or ``("hang", waiter)`` where
        ``waiter(timeout)`` parks the op and returns True iff the fault
        was cleared (heal/reset) before the timeout."""
        with self._lock:
            st = self._backend_faults.get(str(backend_id))
            if st is None:
                return None
            mode = st["mode"]
            if mode == "flap":
                n = st["count"]
                st["count"] = n + 1
                if (n // st["period"]) % 2 == 0:   # dead phase first
                    self.backend_ops_faulted += 1
                    return ("kill",)
                return None
            if mode == "kill":
                self.backend_ops_faulted += 1
                return ("kill",)
            if mode == "slow":
                return ("slow", st["seconds"])
            self.backend_ops_faulted += 1
            release = self._hang_release
        return ("hang",
                lambda timeout: release.wait(
                    min(float(timeout), self._HANG_MAX_S)))

    # -- wire-level socket faults (consulted by transport.FaultProxy) ------
    def arm_socket_blackhole(self, proxy_id: str) -> None:
        """Blackhole the wire: new connection attempts are refused and
        every byte on established connections parks until heal — the
        host that stops answering without closing anything (the
        socket-level sibling of ``arm_backend_hang``)."""
        with self._lock:
            self._socket_faults[str(proxy_id)] = {"mode": "blackhole"}

    def arm_socket_reset(self, proxy_id: str) -> None:
        """Hard-close every connection at its next forwarded chunk (RST,
        not FIN) and refuse new ones — death mid-stream, the
        socket-level sibling of ``arm_backend_kill``."""
        with self._lock:
            self._socket_faults[str(proxy_id)] = {"mode": "reset"}

    def arm_socket_trickle(self, proxy_id: str,
                           bytes_per_s: float) -> None:
        """Dribble forwarded bytes through at ``bytes_per_s`` — the
        pathologically slow link (degrades, never dies)."""
        with self._lock:
            self._socket_faults[str(proxy_id)] = {
                "mode": "trickle", "bps": max(1.0, float(bytes_per_s))}

    def arm_socket_flap(self, proxy_id: str, period: int = 3) -> None:
        """Alternate refuse/allow phases every ``period`` connection
        attempts, starting refused — the flapping link (established
        connections are left alone; only connects flap)."""
        with self._lock:
            self._socket_faults[str(proxy_id)] = {
                "mode": "flap", "period": max(1, int(period)), "count": 0}

    def heal_socket(self, proxy_id: str) -> None:
        """Clear one proxy's socket fault and release its parked
        forwarders — the recovery half of a wire drill."""
        with self._lock:
            self._socket_faults.pop(str(proxy_id), None)
            self._hang_release.set()
            self._hang_release = threading.Event()

    def socket_action(self, proxy_id: str, op: str):
        """What an armed socket fault does to one proxy operation.
        ``op`` is ``"accept"`` (a new inbound connection), ``"io"``
        (one forwarded chunk), or ``"io-retry"`` (re-consult while a
        chunk is parked — counted as the SAME faulted op, so
        ``socket_ops_faulted`` stays one-per-operation like its
        backend sibling). Returns ``None`` (healthy), ``("refuse",)``
        (hard-close the connection now), ``("trickle", bytes_per_s)``
        (forward at a bounded rate), or ``("hang", waiter)`` where
        ``waiter(timeout)`` parks the forwarder and returns True iff
        the fault was cleared (heal/reset) before the timeout."""
        with self._lock:
            st = self._socket_faults.get(str(proxy_id))
            if st is None:
                return None
            mode = st["mode"]
            if mode == "flap":
                if op != "accept":
                    return None     # only connects flap
                n = st["count"]
                st["count"] = n + 1
                if (n // st["period"]) % 2 == 0:   # refused phase first
                    self.socket_ops_faulted += 1
                    return ("refuse",)
                return None
            if mode == "reset":
                self.socket_ops_faulted += 1
                return ("refuse",)
            if mode == "trickle":
                return None if op == "accept" else ("trickle", st["bps"])
            # blackhole: refuse connects, park established-io until heal
            if op != "io-retry":
                self.socket_ops_faulted += 1
            if op == "accept":
                return ("refuse",)
            release = self._hang_release
        return ("hang",
                lambda timeout: release.wait(
                    min(float(timeout), self._HANG_MAX_S)))

    # -- heartbeat-drop ----------------------------------------------------
    def arm_heartbeat_drop(self, node_id: str) -> None:
        """Suppress elastic lease renewals for ``node_id`` — peers see it
        dead after the heartbeat timeout while the process lives on."""
        with self._lock:
            self._dropped_heartbeats.add(str(node_id))

    def heartbeat_allowed(self, node_id: str) -> bool:
        with self._lock:
            if node_id in self._dropped_heartbeats:
                self.heartbeats_dropped += 1
                return False
            return True


_INJECTOR = FaultInjector()
_FS = Fs(_INJECTOR)


def get_fault_injector() -> FaultInjector:
    return _INJECTOR


def get_fs() -> Fs:
    """The default durable-write layer (consults the global injector)."""
    return _FS


@contextlib.contextmanager
def fault_injection():
    """Legacy wrapper over ``FaultInjector.scoped()``: a clean slate on
    entry, prior state restored (parked hang waiters released) on exit.
    New tests should use ``get_fault_injector().scoped()`` directly."""
    with get_fault_injector().scoped() as inj:
        yield inj
