"""CheckpointManager: interval saves, rotation, GC, resume resolution.

The training-loop face of the resilience stack (``run_steps`` drives it
via ``checkpoint_manager=``): ``maybe_save(step, state)`` snapshots on
interval and commits asynchronously; ``restore(state)`` resolves the
newest VALIDATED committed checkpoint (falling back past torn ones) and
loads it with the existing reshard-on-restore, so a shrunk world resumes
from shards saved by a larger one.
"""
from __future__ import annotations

import os
import threading
from typing import Optional, Tuple

from ..checkpoint.load_state_dict import load_state_dict
from ..checkpoint.save_state_dict import resolve_participants
from .async_ckpt import AsyncCheckpointer
from .commit import (latest_checkpoint, list_committed_steps,
                     list_staging_dirs, step_dir, take_snapshot,
                     validate_checkpoint_dir, write_committed_checkpoint)
from .faults import get_fs
from .metrics import ResilienceMetrics

__all__ = ["CheckpointManager"]


class CheckpointManager:
    """Owns one checkpoint root directory.

    Knobs: ``interval`` (save every N steps through ``maybe_save``),
    ``keep_n`` (committed checkpoints retained, newest first; None keeps
    all), ``async_save`` (snapshot-then-write-behind vs fully blocking
    saves), ``merge_timeout_s`` (coordinator wait for straggler rank
    tables). Metrics surface as ``profiler.resilience_stats()[name]``.

    Construction GCs leftovers of a previous crash (torn ``.tmp``
    staging dirs, FAILED-marked and unvalidatable step dirs), so a
    relaunched worker starts from a clean root.
    """

    def __init__(self, root, interval: int = 1,
                 keep_n: Optional[int] = None, async_save: bool = True,
                 process_group=None, coordinator_rank: int = 0,
                 merge_timeout_s: float = 300.0,
                 name: Optional[str] = None):
        self.root = str(root)
        self.interval = int(interval)
        self.keep_n = keep_n
        self._pg = process_group
        self._coordinator_rank = coordinator_rank
        self._merge_timeout_s = float(merge_timeout_s)
        self.name = name or f"ckpt:{os.path.basename(self.root) or 'root'}"
        self._metrics = ResilienceMetrics(self.name)
        try:
            from ..comm_watchdog import get_comm_task_manager
            self._metrics.set_hang_count_fn(
                lambda: get_comm_task_manager().hang_count)
        except Exception:
            pass
        from ... import profiler
        profiler.register_resilience_source(self.name, self._metrics)
        self._ckpt = AsyncCheckpointer(self._metrics) if async_save \
            else None
        self._state_lock = threading.Lock()
        self._inflight_step: Optional[int] = None
        self._last_saved_step: Optional[int] = None
        self._closed = False
        self.gc()

    @property
    def metrics(self) -> ResilienceMetrics:
        return self._metrics

    # -- saving ------------------------------------------------------------
    def maybe_save(self, step: int, state_dict) -> bool:
        """Save iff ``step`` lands on the interval (and wasn't already
        saved). Non-saving calls still ``poll()`` the write-behind
        thread, so a background failure surfaces within one step."""
        if self._ckpt is not None:
            self._surfacing(self._ckpt.poll)
        if self.interval <= 0 or step % self.interval != 0:
            return False
        if step == self._last_saved_step:
            return False
        return self.save(step, state_dict)

    def _surfacing(self, fn):
        """Run a call that may surface a write-behind failure; on one,
        un-mark the in-flight step first — its staging dir is torn, and
        leaving it marked in-flight would shield it from GC forever."""
        try:
            return fn()
        except BaseException:
            with self._state_lock:
                self._inflight_step = None
            raise

    def save(self, step: int, state_dict,
             blocking: Optional[bool] = None) -> bool:
        """Checkpoint ``state_dict`` as committed step ``step``. Async
        unless constructed with ``async_save=False`` or called with
        ``blocking=True``. Returns False when this process is not a
        participant of the process group (nothing was saved)."""
        step = int(step)
        if self._ckpt is not None and not blocking:
            # marked in-flight BEFORE submit: a fast background commit
            # may fire _on_commit before save() returns
            with self._state_lock:
                self._inflight_step = step
            submitted = self._surfacing(lambda: self._ckpt.save(
                state_dict, self.root, step,
                process_group=self._pg,
                coordinator_rank=self._coordinator_rank,
                merge_timeout_s=self._merge_timeout_s,
                on_commit=self._on_commit))
            if not submitted:
                with self._state_lock:
                    self._inflight_step = None
                return False
        else:
            parts = resolve_participants(self._pg, self._coordinator_rank)
            if parts is None:
                return False
            rank, ranks, coordinator = parts
            import time as _time
            t0 = _time.perf_counter()
            snap = take_snapshot(state_dict, rank=rank, uid=step)
            self._metrics.observe("snapshot_s",
                                  _time.perf_counter() - t0)
            self._metrics.inc("snapshots")
            t1 = _time.perf_counter()
            final = write_committed_checkpoint(
                snap, self.root, step, rank=rank, ranks=ranks,
                coordinator=coordinator,
                merge_timeout_s=self._merge_timeout_s)
            if rank == coordinator:
                # only the coordinator's return means COMMITTED (other
                # ranks return after their shard writes, pre-marker)
                self._metrics.observe("commit_s",
                                      _time.perf_counter() - t1)
                self._metrics.inc("commits")
                self._metrics.set_last_committed_step(step)
            self._on_commit(step, final)
        self._last_saved_step = step
        return True

    def _on_commit(self, step: int, final: str) -> None:
        # runs on the write-behind thread for async saves
        with self._state_lock:
            if self._inflight_step == step:
                self._inflight_step = None
        self.gc()

    def wait(self) -> None:
        """Block until the in-flight write commits; raise its error."""
        if self._ckpt is not None:
            self._surfacing(self._ckpt.wait)

    def record_restart(self) -> None:
        """Count one fault recovery (``run_steps(on_fault=)`` calls this
        after a successful restore-and-resume)."""
        self._metrics.inc("restarts")

    # -- resolution / restore ----------------------------------------------
    def latest_checkpoint(self) -> Optional[Tuple[int, str]]:
        """Newest committed VALIDATED ``(step, path)``, or None."""
        return latest_checkpoint(self.root)

    def latest_step(self) -> Optional[int]:
        found = self.latest_checkpoint()
        return None if found is None else found[0]

    def restore(self, state_dict) -> Optional[int]:
        """Load the newest committed checkpoint into ``state_dict`` in
        place (reshard-on-restore: each leaf keeps its CURRENT sharding,
        data is overlap-read from the saved layout — a shrunk/regrown
        world restores transparently). Returns the step, or None when no
        committed checkpoint exists."""
        found = self.latest_checkpoint()
        if found is None:
            return None
        step, path = found
        load_state_dict(state_dict, path)
        return step

    # -- GC ----------------------------------------------------------------
    def gc(self) -> list:
        """Delete torn staging dirs, FAILED/unvalidatable step dirs, and
        committed checkpoints beyond ``keep_n`` (newest kept). The dir of
        an in-flight async save is never touched. Coordinator-only on
        multi-rank groups (one process must own deletions)."""
        parts = resolve_participants(self._pg, self._coordinator_rank)
        if parts is None:
            return []
        rank, _ranks, coordinator = parts
        if rank != coordinator:
            return []
        with self._state_lock:
            inflight = self._inflight_step
        fs = get_fs()
        removed = []
        for step, dname in list_staging_dirs(self.root):
            if step == inflight:
                continue
            fs.rmtree(os.path.join(self.root, dname), label="gc-torn")
            removed.append(dname)
        committed = []
        for step, dname in list_committed_steps(self.root):
            if step == inflight:
                continue
            path = os.path.join(self.root, dname)
            ok, _why = validate_checkpoint_dir(path, expect_step=step)
            if ok:
                committed.append((step, dname))
            else:
                fs.rmtree(path, label="gc-unvalidatable")
                removed.append(dname)
        if self.keep_n is not None and self.keep_n > 0:
            for step, dname in committed[self.keep_n:]:
                fs.rmtree(os.path.join(self.root, dname),
                          label="gc-rotate")
                removed.append(dname)
        if removed:
            self._metrics.inc("gc_removed", len(removed))
        return removed

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        """Drain the write-behind thread (raising any pending write
        error) and unregister metrics."""
        if self._closed:
            return
        self._closed = True
        try:
            if self._ckpt is not None:
                self._ckpt.close(wait=True)
        finally:
            from ... import profiler
            profiler.unregister_resilience_source(self.name,
                                                  self._metrics)

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __repr__(self) -> str:
        return (f"CheckpointManager(root={self.root!r}, "
                f"interval={self.interval}, keep_n={self.keep_n}, "
                f"last_committed={step_dir(self._last_saved_step) if self._last_saved_step is not None else None})")
