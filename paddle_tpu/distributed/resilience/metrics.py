"""Resilience observability: one metrics bundle per CheckpointManager,
surfaced through ``profiler.resilience_stats()`` / ``export_stats()``.

Counters: snapshots (device→host captures), commits (checkpoints made
durable), write_errors (background writer failures surfaced), restarts
(recoveries through ``run_steps(on_fault=)``), gc_removed (torn/stale
dirs deleted). Histograms: snapshot_s (the only training-loop block),
commit_s (staging-dir write through pointer flip, on the write-behind
thread). Gauges: write_behind_queue_depth, last_committed_step,
hang_count (mirrored from the comm watchdog at snapshot time).
"""
from __future__ import annotations

import threading

from ...profiler.metrics import MetricsBase

__all__ = ["ResilienceMetrics"]


class ResilienceMetrics(MetricsBase):
    COUNTERS = ("snapshots", "commits", "write_errors", "restarts",
                "gc_removed")
    HISTS = ("snapshot_s", "commit_s")
    TIMES = ()

    def __init__(self, name: str):
        super().__init__(name)
        self._gauge_lock = threading.Lock()
        self._last_committed_step = -1
        self._hang_count_fn = None

    def set_last_committed_step(self, step: int) -> None:
        with self._gauge_lock:
            self._last_committed_step = int(step)

    def set_hang_count_fn(self, fn) -> None:
        """Pull-type: read the comm watchdog's hang counter at snapshot
        time instead of duplicating state."""
        self._hang_count_fn = fn

    def snapshot(self) -> dict:
        with self._lock:
            out: dict = dict(self._counters)
            out["name"] = self.name
            for k, h in self._hists.items():
                out[k] = h.snapshot()
        with self._gauge_lock:
            out["last_committed_step"] = self._last_committed_step
        out["write_behind_queue_depth"] = self._read_gauge()
        fn = self._hang_count_fn
        if fn is not None:
            try:
                out["hang_count"] = int(fn())
            except Exception:
                out["hang_count"] = -1
        else:
            out["hang_count"] = 0
        return out
