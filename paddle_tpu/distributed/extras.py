"""Remaining paddle.distributed surface (parity: spawn, object
collectives, gloo env shims, TP split API, dataset entries, strategy).

reference: python/paddle/distributed/spawn.py, communication/*_object_list,
fleet/base/role_maker gloo paths, fleet/layers/mpu/mp_ops.py:700 (split),
distributed/entry_attr.py, auto_parallel/strategy.py.
"""
from __future__ import annotations

import multiprocessing
import os
import pickle

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = [
    "spawn", "scatter_object_list", "broadcast_object_list",
    "gloo_init_parallel_env", "gloo_barrier", "gloo_release", "split",
    "ParallelMode", "is_available", "get_backend", "shard_dataloader",
    "ReduceType", "Strategy", "CountFilterEntry", "ShowClickEntry",
    "ProbabilityEntry", "QueueDataset", "InMemoryDataset",
]


# -- process spawning ------------------------------------------------------

def _spawn_target(func, rank, nprocs, env, args):
    for k, v in env.items():
        os.environ[k] = v
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    func(*args)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Launch ``func`` in ``nprocs`` processes with the PADDLE_TRAINER_*
    env contract (parity: paddle.distributed.spawn — the reference forks
    one process per GPU; here one per requested worker, spawn-start to be
    fork-safe with JAX threads)."""
    if nprocs == -1:
        nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    ctx = multiprocessing.get_context("spawn")
    procs = []
    env = {k: v for k, v in os.environ.items()
           if k.startswith(("PADDLE_", "FLAGS_"))}
    for rank in range(nprocs):
        prc = ctx.Process(target=_spawn_target,
                          args=(func, rank, nprocs, env, args),
                          daemon=daemon)
        prc.start()
        procs.append(prc)

    class _Context:
        def __init__(self, ps):
            self.processes = ps

        def join(self, timeout=None):
            for p_ in self.processes:
                p_.join(timeout)
            bad = [i for i, p_ in enumerate(self.processes)
                   if p_.exitcode not in (0, None)]
            if bad:
                raise RuntimeError(
                    f"spawned ranks {bad} exited with nonzero status")
    c = _Context(procs)
    if join:
        c.join()
    return c


# -- object collectives ----------------------------------------------------

def _obj_to_tensor(obj):
    payload = np.frombuffer(pickle.dumps(obj), np.uint8)
    return Tensor(jnp.asarray(payload.copy()))


def _tensor_to_obj(t):
    return pickle.loads(np.asarray(t._data).tobytes())


def broadcast_object_list(object_list, src=0, group=None):
    """(parity: paddle.distributed.broadcast_object_list). On the global-
    array substrate every process sees identical values, so the broadcast
    is identity for the src's data; the API contract (in-place fill of
    object_list) is preserved."""
    from .communication_impl import broadcast
    out = []
    for obj in object_list:
        t = _obj_to_tensor(obj)
        t = broadcast(t, src=src, group=group)
        out.append(_tensor_to_obj(t))
    object_list[:] = out
    return object_list


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """(parity: paddle.distributed.scatter_object_list)."""
    from .parallel import get_rank, get_world_size
    world = get_world_size(group)
    rank = get_rank(group)
    if in_object_list is None:
        in_object_list = []
    if world <= 1:
        out_object_list[:] = list(in_object_list) or [None]
        return out_object_list
    if len(in_object_list) % world != 0:
        raise ValueError(
            f"scatter_object_list: {len(in_object_list)} objects not "
            f"divisible by world size {world}")
    per = len(in_object_list) // world
    chunk = in_object_list[rank * per:(rank + 1) * per]
    out_object_list[:] = chunk
    return out_object_list


# -- gloo shims ------------------------------------------------------------

def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """CPU rendezvous env init (parity: paddle.distributed
    .gloo_init_parallel_env — gloo is the reference's CPU backend; this
    build's host coordination uses the TCPStore)."""
    from .store import create_or_get_global_tcp_store
    os.environ.setdefault("PADDLE_TRAINER_ID", str(rank_id))
    os.environ.setdefault("PADDLE_TRAINERS_NUM", str(rank_num))
    host, port = server_endpoint.rsplit(":", 1)
    os.environ.setdefault("MASTER_ADDR", host)
    os.environ.setdefault("MASTER_PORT", port)
    create_or_get_global_tcp_store()


def gloo_barrier():
    """(parity: paddle.distributed.gloo_barrier)"""
    from .communication_impl import barrier
    barrier()


def gloo_release():
    """(parity: paddle.distributed.gloo_release) — host KV teardown."""


# -- TP split API ----------------------------------------------------------

def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Megatron-style distributed fc/embedding (parity:
    paddle.distributed.split, fleet/layers/mpu/mp_ops.py:700).

    operation='linear': axis=0 row-parallel / axis=1 column-parallel
    Linear over the model-parallel group; operation='embedding':
    vocab-parallel embedding. Returns a constructed layer applied to x.
    """
    from .fleet.layers.mpu.mp_layers import (ColumnParallelLinear,
                                             RowParallelLinear,
                                             VocabParallelEmbedding)
    if operation == "linear":
        in_f, out_f = size
        if axis == 1:
            layer = ColumnParallelLinear(in_f, out_f,
                                         weight_attr=weight_attr,
                                         has_bias=bias_attr is not False,
                                         gather_output=gather_out)
        else:
            layer = RowParallelLinear(in_f, out_f,
                                      weight_attr=weight_attr,
                                      has_bias=bias_attr is not False,
                                      input_is_parallel=False)
        return layer(x)
    if operation == "embedding":
        num_emb, emb_dim = size
        layer = VocabParallelEmbedding(num_emb, emb_dim,
                                       weight_attr=weight_attr)
        return layer(x)
    raise ValueError(f"unsupported operation {operation!r}")


# -- metadata / config -----------------------------------------------------

from .fleet.fleet import ParallelMode  # noqa: E402,F401


class ReduceType:
    """(parity: paddle.distributed.ReduceType — reduce kinds for Partial
    placements)"""
    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4
    kRedAny = 5
    kRedAll = 6


def is_available():
    """(parity: paddle.distributed.is_available)"""
    return True


def get_backend(group=None):
    """(parity: paddle.distributed.get_backend) — the collective backend
    on this substrate is XLA's compiled collectives over ICI/DCN."""
    return "XCCL"


class Strategy:
    """Auto-parallel strategy config (parity: paddle.distributed.Strategy,
    auto_parallel/strategy.py — nested toggle namespaces)."""

    class _Config:
        def __init__(self, defaults, overrides):
            self.__dict__.update(defaults)
            self.__dict__.update(overrides or {})

        def __repr__(self):
            return repr(self.__dict__)

    def __init__(self, config=None):
        cfg = config or {}
        self.sharding = Strategy._Config(
            dict(enable=False, stage=1, degree=8), cfg.get("sharding"))
        self.fused_passes = Strategy._Config(
            dict(enable=False, fused_passes_list=[]),
            cfg.get("fused_passes"))
        self.gradient_merge = Strategy._Config(
            dict(enable=False, k_steps=1, avg=True),
            cfg.get("gradient_merge"))
        self.pipeline = Strategy._Config(
            dict(enable=False, schedule_mode="1F1B", micro_batch_size=1,
                 accumulate_steps=1), cfg.get("pipeline"))
        self.amp = Strategy._Config(
            dict(enable=False, dtype="float16", level="O1"),
            cfg.get("amp"))
        self.recompute = Strategy._Config(
            dict(enable=False), cfg.get("recompute"))
        # degree-planner tuning (reference: Strategy's tuning config +
        # auto_tuner profile trials, auto_tuner/tuner.py:21): with
        # profile=True the planner times ONE real sharded step per
        # surviving (dp, tp) candidate and ranks by measurement instead of
        # the analytic cost alone
        self.tuning = Strategy._Config(
            dict(enable=False, profile=False), cfg.get("tuning"))


# -- dataset entry configs (PS-stack metadata; inventoried for parity) -----

class _EntryAttr:
    def _to_attr(self):
        raise NotImplementedError


class CountFilterEntry(_EntryAttr):
    """(parity: paddle.distributed.CountFilterEntry — sparse feature
    admission by click count; metadata object on this substrate)"""

    def __init__(self, count_filter):
        if count_filter < 0:
            raise ValueError("count_filter must be >= 0")
        self._count_filter = count_filter

    def _to_attr(self):
        return f"count_filter_entry:{self._count_filter}"


class ShowClickEntry(_EntryAttr):
    """(parity: paddle.distributed.ShowClickEntry)"""

    def __init__(self, show_name, click_name):
        self._show = show_name
        self._click = click_name

    def _to_attr(self):
        return f"show_click_entry:{self._show}:{self._click}"


class ProbabilityEntry(_EntryAttr):
    """(parity: paddle.distributed.ProbabilityEntry)"""

    def __init__(self, probability):
        if not 0 <= probability <= 1:
            raise ValueError("probability must be in [0, 1]")
        self._probability = probability

    def _to_attr(self):
        return f"probability_entry:{self._probability}"


# QueueDataset / InMemoryDataset / friends: ONE implementation — the
# fleet MultiSlot engine (fleet/dataset.py) backs both the
# paddle.distributed and paddle.distributed.fleet export paths (it
# degrades to raw-line streaming when init() gets no use_var).
from .fleet.dataset import (DatasetBase, InMemoryDataset,  # noqa: E402,F401
                            QueueDataset, FileInstantDataset,
                            BoxPSDataset)


def shard_dataloader(dataloader, meshes, input_keys=None, shard_dims=None,
                     is_dataset_splitted=False):
    """Wrap a DataLoader so each batch lands sharded on the given mesh(es)
    (parity: paddle.distributed.shard_dataloader,
    auto_parallel/api.py:1783)."""
    from .auto_parallel.api import shard_tensor
    from .process_mesh import Replicate, Shard

    meshes_list = meshes if isinstance(meshes, (list, tuple)) else [meshes]

    class _ShardedLoader:
        def __init__(self, dl):
            self._dl = dl

        def __len__(self):
            return len(self._dl)

        def _place(self, item, mesh, dim):
            if isinstance(item, (list, tuple)):
                return type(item)(self._place(v, mesh, dim) for v in item)
            if isinstance(item, dict):
                return {k: self._place(v, mesh, dim)
                        for k, v in item.items()}
            if isinstance(item, Tensor):
                placements = [Replicate()] * len(mesh.shape)
                if dim is not None:
                    axis = mesh.dim_names.index(dim) \
                        if isinstance(dim, str) else dim
                    placements[axis] = Shard(0)
                return shard_tensor(item, mesh, placements)
            return item

        def __iter__(self):
            mesh = meshes_list[0]
            dim = shard_dims if not isinstance(shard_dims, (list, tuple)) \
                else shard_dims[0]
            for batch in self._dl:
                yield self._place(batch, mesh, dim)
    return _ShardedLoader(dataloader)
