"""RPC (parity: python/paddle/distributed/rpc/ — init_rpc, rpc_sync,
rpc_async, get_worker_info, shutdown; reference transport is the brpc
parameter-server service).

TPU-native design: host-side control RPC rides the same TCPStore the
launcher/elastic stack already uses (SURVEY §5.8: host coordination via
the KV store) — each worker runs an agent thread that polls its request
queue, executes the pickled callable, and writes the pickled reply. Data
movement between chips stays in XLA collectives; this is the
control-plane sidecar, exactly the role the reference's RPC plays."""
from __future__ import annotations

import pickle
import threading
import time
import traceback
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ..store import TCPStore

__all__ = ["init_rpc", "shutdown", "rpc_sync", "rpc_async",
           "get_worker_info", "get_all_worker_infos", "WorkerInfo",
           "get_current_worker_info",
           # the blessed wire-RPC surface (serving.transport re-exports)
           "RemoteBackend", "BackendServer", "FaultProxy", "FrameReader",
           "send_msg", "WireError", "ConnectionClosedError", "FrameError",
           "WIRE_VERSION"]

# The serving wire transport (paddle_tpu/serving/transport/) is the one
# full-duplex, streaming RPC implementation in this codebase; its
# client/server primitives are re-exported here so there is a single
# blessed RPC surface (the TCPStore-backed rpc_sync/rpc_async above stay
# as the reference-parity control-plane API). Lazy via PEP 562: the
# serving stack imports jax at module load, and distributed.rpc must
# stay importable in minimal/control-plane contexts.
_WIRE_EXPORTS = ("RemoteBackend", "BackendServer", "FaultProxy",
                 "FrameReader", "send_msg", "WireError",
                 "ConnectionClosedError", "FrameError", "WIRE_VERSION")


def __getattr__(name):
    if name in _WIRE_EXPORTS:
        from ...serving import transport
        return getattr(transport, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")

_PREFIX = "__rpc"


@dataclass(frozen=True)
class WorkerInfo:
    name: str
    rank: int


class _Agent:
    def __init__(self, store: TCPStore, name: str, rank: int,
                 world_size: int):
        self.store = store
        self.name = name
        self.rank = rank
        self.world_size = world_size
        self._stop = threading.Event()
        self._served = 0
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name=f"rpc-agent:{name}")

    def start(self):
        self.store.set(f"{_PREFIX}/worker/{self.name}", str(self.rank))
        self.store.add(f"{_PREFIX}/registered", 1)
        self._thread.start()

    def _serve(self):
        qkey = f"{_PREFIX}/q/{self.name}"
        while not self._stop.is_set():
            try:
                pending = self.store.add(qkey, 0)
            except Exception:
                return
            if pending <= self._served:
                time.sleep(0.01)
                continue
            seq = self._served
            self._served += 1
            try:
                raw = self.store.get(f"{qkey}/{seq}")
                fn, args, kwargs = pickle.loads(raw)
                try:
                    result = (True, fn(*args, **(kwargs or {})))
                except Exception as e:  # ship the error to the caller
                    result = (False, f"{type(e).__name__}: {e}\n"
                                     f"{traceback.format_exc()}")
                self.store.set(f"{qkey}/{seq}/reply", pickle.dumps(result))
            except Exception:
                if not self._stop.is_set():
                    continue
                return

    def stop(self):
        self._stop.set()
        self._thread.join(1.0)


_STATE: Dict[str, Any] = {"store": None, "agent": None}


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None,
             master_endpoint: str = "127.0.0.1:0",
             store: Optional[TCPStore] = None) -> WorkerInfo:
    """Join the RPC world (parity: dist.rpc.init_rpc). rank 0 hosts the
    rendezvous store unless an existing store is passed."""
    if _STATE["agent"] is not None:
        raise RuntimeError("init_rpc already called; call shutdown() first")
    rank = rank if rank is not None else 0
    world_size = world_size or 1
    if store is None:
        host, port = master_endpoint.rsplit(":", 1)
        store = TCPStore(host, int(port), is_master=rank == 0,
                         world_size=world_size)
    agent = _Agent(store, name, rank, world_size)
    agent.start()
    _STATE.update(store=store, agent=agent)
    return WorkerInfo(name, rank)


def _require_agent() -> _Agent:
    agent = _STATE["agent"]
    if agent is None:
        raise RuntimeError("call init_rpc() first")
    return agent


def get_worker_info(name: Optional[str] = None) -> WorkerInfo:
    agent = _require_agent()
    if name is None or name == agent.name:
        return WorkerInfo(agent.name, agent.rank)
    raw = agent.store.get(f"{_PREFIX}/worker/{name}")
    return WorkerInfo(name, int(raw.decode()))


def get_all_worker_infos() -> List[WorkerInfo]:
    agent = _require_agent()
    n = agent.store.add(f"{_PREFIX}/registered", 0)
    del n  # names are not centrally enumerated; reference returns the map
    return [get_worker_info()]


class _Future:
    """Parity: the FutureWrapper rpc_async returns."""

    def __init__(self, store, qkey, seq, timeout):
        self._store = store
        self._key = f"{qkey}/{seq}/reply"
        self._timeout = timeout
        self._done = threading.Event()
        self._result = None
        self._thread = threading.Thread(target=self._poll, daemon=True)
        self._thread.start()

    def _poll(self):
        deadline = time.monotonic() + self._timeout
        try:
            while time.monotonic() < deadline:
                try:
                    raw = self._store.get(self._key, wait=False)
                except KeyError:
                    time.sleep(0.01)
                    continue
                if raw:
                    self._result = pickle.loads(raw)
                    return
                time.sleep(0.01)
            self._result = (False,
                            f"rpc reply timed out after {self._timeout}s")
        except Exception as e:  # noqa: BLE001 — a dying reply channel
            # (store closed under us, undecodable reply) must wake the
            # waiter with a typed error; before this finally, it killed
            # the poll thread with _done never set and wait() hung
            # forever (GL701's failure mode, found by the wave-3 sweep)
            self._result = (False, f"rpc reply channel failed: {e!r}")
        finally:
            self._done.set()

    def wait(self):
        # bounded even if the poll thread is itself wedged inside a
        # store call: one grace period past the rpc deadline
        if not self._done.wait(self._timeout + 5.0):
            raise RuntimeError(
                "rpc reply poll thread unresponsive "
                f"{self._timeout + 5.0:.1f}s past submission")
        self._thread.join(timeout=1.0)   # reclaim the poll thread
        ok, value = self._result
        if not ok:
            raise RuntimeError(f"remote call failed: {value}")
        return value

    def done(self) -> bool:
        return self._done.is_set()


def rpc_async(to: str, fn, args=(), kwargs=None,
              timeout: float = 30.0) -> _Future:
    agent = _require_agent()
    qkey = f"{_PREFIX}/q/{to}"
    payload = pickle.dumps((fn, tuple(args), kwargs or {}))
    # claim a sequence slot, publish the request, then bump the pending
    # counter the target agent polls
    seq = agent.store.add(f"{qkey}/next", 1) - 1
    agent.store.set(f"{qkey}/{seq}", payload)
    agent.store.add(qkey, 1)
    return _Future(agent.store, qkey, seq, timeout)


def rpc_sync(to: str, fn, args=(), kwargs=None, timeout: float = 30.0):
    return rpc_async(to, fn, args, kwargs, timeout).wait()


def shutdown(graceful: bool = True):
    agent = _STATE["agent"]
    if agent is not None:
        agent.stop()
    store = _STATE["store"]
    if store is not None and graceful:
        try:
            store.close()
        except Exception:
            pass
    _STATE.update(store=None, agent=None)


def get_current_worker_info():
    """(parity: paddle.distributed.rpc.get_current_worker_info) — the
    live agent's identity when init_rpc has run, env contract otherwise."""
    agent = _STATE.get("agent") if isinstance(_STATE, dict) else None
    if agent is not None:
        return WorkerInfo(agent.name, agent.rank)
    import os
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    name = os.environ.get("PADDLE_WORKER_NAME", f"worker{rank}")
    return WorkerInfo(name, rank)
