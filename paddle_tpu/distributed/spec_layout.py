"""Canonical PartitionSpecs for paddle_tpu parameters, activations, and
batches — the one sharding vocabulary shared by the trainer, the input
prefetcher, and checkpoint reshard.

Every multichip subsystem used to hand-roll its ``PartitionSpec``
literals; axis-name drift between them ("dp" here, "data" there) is
exactly the defect class graft_lint's GL10xx family polices. This
module is the enforcement target: a frozen :class:`SpecLayout` carries
the repo's axis names once (``dp`` for data/FSDP — FSDP overlays the
data axis, see ``llama_fsdp_spec`` — ``tp`` for tensor parallel,
``sep`` for sequence parallel, ``ep`` for experts) and every canonical
placement is a method returning a ``jax.sharding.PartitionSpec``.
Inline ``PartitionSpec`` literals that spell one of these canonical
forms are flagged by GL1006 (autofixable) in modules that bind a
layout.

jax is imported lazily inside the methods: constructing or passing a
``SpecLayout`` around (launcher config, control-plane processes) must
not pull the device runtime in.
"""
from __future__ import annotations

from dataclasses import dataclass

__all__ = ["SpecLayout", "default_layout"]

Axis = str


@dataclass(frozen=True)
class SpecLayout:
    """Axis names -> canonical PartitionSpecs. Instances are immutable
    and cheap; make one per mesh vocabulary (``SpecLayout()`` for the
    stock ``("dp", "tp")`` meshes, ``SpecLayout(data_axis="batch")`` for
    a renamed mesh) and route every placement through its methods."""

    data_axis: Axis = "dp"
    fsdp_axis: Axis = "dp"     # FSDP overlays the data axis in this repo
    tp_axis: Axis = "tp"
    seq_axis: Axis = "sep"
    expert_axis: Axis = "ep"

    @staticmethod
    def _ps(*entries):
        from jax.sharding import PartitionSpec
        return PartitionSpec(*entries)

    # -- parameter-free placements ------------------------------------

    def replicated(self):
        """Every device holds the full array (scalars, norms, biases)."""
        return self._ps()

    # -- batch placements ---------------------------------------------

    def batch(self, ndim: int = 1):
        """Leading batch dim over the data axis, rest replicated —
        the trainer's per-step input placement."""
        return self._ps(self.data_axis, *([None] * (ndim - 1)))

    def stacked_batch(self, ndim: int, batch_dim: int = 1):
        """Batch dim at ``batch_dim`` over the data axis — the scan
        trainer's [K, B, ...] (and [K, M, B, ...] with accumulation)
        input placement."""
        if not 0 <= batch_dim < ndim:
            raise ValueError(
                f"batch_dim {batch_dim} out of range for ndim {ndim}")
        return self._ps(*([None] * batch_dim), self.data_axis,
                        *([None] * (ndim - batch_dim - 1)))

    # -- parameter placements -----------------------------------------

    def fsdp_rows(self, ndim: int = 2):
        """Leading dim sharded over the FSDP axis (ZeRO-3 style
        parameter rows)."""
        return self._ps(self.fsdp_axis, *([None] * (ndim - 1)))

    def tp_rows(self, ndim: int = 2):
        """Leading dim over tensor parallel — row-parallel weights
        (the projection back from a TP-split activation)."""
        return self._ps(self.tp_axis, *([None] * (ndim - 1)))

    def tp_cols(self, ndim: int = 2):
        """Trailing dim over tensor parallel — column-parallel weights
        (QKV/MLP-up style fan-out)."""
        return self._ps(*([None] * (ndim - 1)), self.tp_axis)

    # -- activation placements ----------------------------------------

    def sequence(self, ndim: int = 4, seq_dim: int = 1):
        """Sequence dim over the sequence-parallel axis — ring/ulysses
        attention's [B, S, H, D] activation placement."""
        if not 0 <= seq_dim < ndim:
            raise ValueError(
                f"seq_dim {seq_dim} out of range for ndim {ndim}")
        return self._ps(*([None] * seq_dim), self.seq_axis,
                        *([None] * (ndim - seq_dim - 1)))

    def experts(self, ndim: int = 3):
        """Leading expert dim over the expert-parallel axis — MoE
        [E, d_in, d_out] expert-weight placement."""
        return self._ps(self.expert_axis, *([None] * (ndim - 1)))


_DEFAULT: SpecLayout = SpecLayout()


def default_layout() -> SpecLayout:
    """The repo-standard layout (``dp``/``tp``/``sep``/``ep`` axes)."""
    return _DEFAULT
