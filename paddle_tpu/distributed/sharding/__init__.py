"""paddle.distributed.sharding (parity: python/paddle/distributed/
sharding/ — group_sharded_parallel/save_group_sharded_model, the dygraph
ZeRO entry points over the fleet sharding stages)."""
from __future__ import annotations

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]


def group_sharded_parallel(model, optimizer, level, scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """Wrap model+optimizer for ZeRO stage os/os_g/p_g_os (parity:
    sharding/group_sharded_parallel). Maps onto the GSPMD sharding
    stages: os -> ShardingStage1, os_g -> Stage2, p_g_os -> Stage3."""
    from ..auto_parallel.api import (ShardingStage1, ShardingStage2,
                                     ShardingStage3, shard_optimizer)
    stage = {"os": ShardingStage1, "os_g": ShardingStage2,
             "p_g_os": ShardingStage3}.get(level)
    if stage is None:
        raise ValueError(
            f"level must be os | os_g | p_g_os, got {level!r}")
    opt = shard_optimizer(optimizer, stage())
    return model, opt, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """(parity: sharding.save_group_sharded_model)"""
    import os

    from ...framework import save
    os.makedirs(output, exist_ok=True)
    save(model.state_dict(), os.path.join(output, "model.pdmodel"))
    if optimizer is not None and hasattr(optimizer, "state_dict"):
        save(optimizer.state_dict(), os.path.join(output,
                                                  "model.pdopt"))
