"""Distributed IO helpers (parity: python/paddle/distributed/io.py —
save/load for distributed training programs)."""
from __future__ import annotations

import os

from ..framework import load as _load
from ..framework import save as _save

__all__ = ["save_persistables", "load_persistables",
           "is_persistable", "save_distributed_persistables"]


def is_persistable(var):
    return bool(getattr(var, "persistable", False))


def save_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    """Save a program's persistable params (parity: io.save_persistables).
    In this build the 'program' is a Layer or a state_dict."""
    obj = main_program
    state = obj.state_dict() if hasattr(obj, "state_dict") else obj
    path = os.path.join(dirname, filename or "persistables.pdparams")
    _save(state, path)
    return path


def save_distributed_persistables(executor=None, dirname=None,
                                  main_program=None, **kw):
    return save_persistables(executor, dirname, main_program, **kw)


def load_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    path = os.path.join(dirname, filename or "persistables.pdparams")
    state = _load(path)
    if main_program is not None and hasattr(main_program, "set_state_dict"):
        main_program.set_state_dict(state)
    return state
