"""Composable distributed optimization passes.

Capability parity with the reference's pass library
(reference: python/paddle/distributed/passes/ — 13.8k LoC: pass_base.py
registry + amp / gradient-merge / master-grad / recompute / comm-overlap
passes applied by the auto-parallel Parallelizer).

TPU-native design: the reference's passes rewrite static programs; here a
pass transforms the live training objects (optimizer wrapper, model
wrapper, amp policy) — XLA owns the graph-level rewrites the reference
does by hand (fusion, comm overlap, inplace), so only the passes with
training-semantic content survive the translation.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = ["PassBase", "register_pass", "new_pass", "PassContext",
           "apply_passes"]

_PASS_REGISTRY: Dict[str, type] = {}


def register_pass(name: str):
    def deco(cls):
        cls.name = name
        _PASS_REGISTRY[name] = cls
        return cls
    return deco


class PassContext:
    """Mutable bag the passes read/write (parity: PassContext)."""

    def __init__(self, model=None, optimizer=None, strategy=None):
        self.model = model
        self.optimizer = optimizer
        self.strategy = strategy


class PassBase:
    """A pass checks applicability then transforms the context
    (parity: pass_base.py PassBase._check_self/_apply_impl)."""

    name = "base"

    def __init__(self, attrs: Optional[dict] = None):
        self.attrs = dict(attrs or {})

    def check(self, ctx: PassContext) -> bool:
        return True

    def apply(self, ctx: PassContext) -> PassContext:
        raise NotImplementedError


def new_pass(name: str, attrs: Optional[dict] = None) -> PassBase:
    if name not in _PASS_REGISTRY:
        raise KeyError(
            f"unknown pass '{name}'; registered: {sorted(_PASS_REGISTRY)}")
    return _PASS_REGISTRY[name](attrs)


def apply_passes(names, model=None, optimizer=None, strategy=None):
    """Apply passes in order; returns the transformed PassContext."""
    ctx = PassContext(model, optimizer, strategy)
    for item in names:
        name, attrs = item if isinstance(item, tuple) else (item, None)
        p = new_pass(name, attrs)
        if p.check(ctx):
            ctx = p.apply(ctx)
    return ctx


# -- gradient merge ----------------------------------------------------------

class _GradientMergeOptimizer:
    """Accumulate grads for k steps, apply on the k-th (reference
    auto_parallel_gradient_merge.py): step()/clear_grad() on non-boundary
    steps leave ``.grad`` accumulating; the boundary step optionally
    averages and runs the real optimizer."""

    def __init__(self, inner, k_steps: int, avg: bool = True):
        self._inner_opt = inner
        self._k = max(1, int(k_steps))
        self._avg = avg
        self._acc = 0

    @property
    def is_boundary(self) -> bool:
        return self._acc == 0

    def step(self):
        self._acc += 1
        if self._acc < self._k:
            return
        self._acc = 0
        if self._avg and self._k > 1:
            for p in (self._inner_opt._parameter_list or []):
                if p.grad is not None:
                    p.grad = Tensor(p.grad._data / self._k)
        self._inner_opt.step()

    def clear_grad(self, set_to_zero=True):
        # grads must survive across the merge window; only the boundary
        # step really clears
        if self._acc == 0:
            self._inner_opt.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def __getattr__(self, name):
        return getattr(self.__dict__["_inner_opt"], name)


@register_pass("gradient_merge")
@register_pass("auto_parallel_gradient_merge_pass")
class GradientMergePass(PassBase):
    """attrs: k_steps (int), avg (bool)."""

    def check(self, ctx):
        return ctx.optimizer is not None and \
            self.attrs.get("k_steps", 1) > 1

    def apply(self, ctx):
        ctx.optimizer = _GradientMergeOptimizer(
            ctx.optimizer, self.attrs.get("k_steps", 1),
            self.attrs.get("avg", True))
        return ctx


# -- master grad -------------------------------------------------------------

class _MasterGradOptimizer:
    """fp32 master gradients for low-precision params (reference
    auto_parallel_master_grad.py): grads are upcast to fp32 at every
    ``step()`` call. Composed OUTSIDE gradient_merge (the apply_passes
    order ``[gradient_merge, master_grad]`` produces exactly that), the
    upcast runs on every micro-step, so after the first micro-batch the
    accumulator is fp32 and later bf16/fp16 contributions are added in
    fp32 — micro-contributions cannot round away."""

    _LOW_PRECISION = (jnp.bfloat16, jnp.float16)

    def __init__(self, inner):
        self._inner_opt = inner

    def _upcast(self):
        for p in (self._inner_opt._parameter_list or []):
            g = p.grad
            if g is not None and g._data.dtype in self._LOW_PRECISION:
                p.grad = Tensor(g._data.astype(jnp.float32))

    def step(self):
        self._upcast()
        self._inner_opt.step()

    def clear_grad(self, set_to_zero=True):
        self._inner_opt.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def __getattr__(self, name):
        return getattr(self.__dict__["_inner_opt"], name)


@register_pass("master_grad")
@register_pass("auto_parallel_master_grad_pass")
class MasterGradPass(PassBase):
    def check(self, ctx):
        return ctx.optimizer is not None

    def apply(self, ctx):
        ctx.optimizer = _MasterGradOptimizer(ctx.optimizer)
        return ctx


# -- recompute ---------------------------------------------------------------

@register_pass("recompute")
@register_pass("auto_parallel_recompute_pass")
class RecomputePass(PassBase):
    """attrs: sublayers (list of Layer) — wraps each listed sublayer's
    forward in activation recompute (reference auto_parallel_recompute.py
    rewrites the program; here the dygraph recompute API does the same
    trade)."""

    def check(self, ctx):
        return ctx.model is not None

    def apply(self, ctx):
        from ..fleet.recompute import recompute
        targets = self.attrs.get("sublayers")
        if targets is None:
            targets = [lyr for lyr in ctx.model.sublayers()
                       if type(lyr).__name__ in
                       self.attrs.get("layer_types",
                                      ("TransformerEncoderLayer",
                                       "LlamaDecoderLayer"))]
        for lyr in targets:
            if getattr(lyr, "_recompute_wrapped", False):
                continue
            orig = lyr.forward

            def wrapped(*a, _orig=orig, **k):
                return recompute(_orig, *a, **k)
            lyr.forward = wrapped
            lyr._recompute_wrapped = True
        return ctx


# -- amp ---------------------------------------------------------------------

@register_pass("amp")
@register_pass("auto_parallel_amp_pass")
class AMPPass(PassBase):
    """attrs: dtype ('bfloat16'|'float16'), level ('O1'|'O2') — wraps the
    model's forward in auto_cast (reference auto_parallel_amp.py inserts
    cast ops; the amp_state policy does it per-op here)."""

    def check(self, ctx):
        return ctx.model is not None

    def apply(self, ctx):
        from ...amp import auto_cast
        dtype = self.attrs.get("dtype", "bfloat16")
        level = self.attrs.get("level", "O1")
        model = ctx.model
        orig = model.forward

        def wrapped(*a, **k):
            with auto_cast(level=level, dtype=dtype):
                return orig(*a, **k)
        model.forward = wrapped
        return ctx
