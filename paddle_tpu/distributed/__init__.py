"""paddle_tpu.distributed (parity: python/paddle/distributed/)."""
from .process_mesh import (ProcessMesh, Shard, Replicate, Partial,  # noqa: F401
                           Placement, get_mesh, set_mesh, init_mesh,
                           get_current_process_mesh)
from .auto_parallel.static_mode import DistModel, to_static  # noqa: F401
from .auto_parallel.api import (shard_tensor, reshard, shard_layer,  # noqa: F401
                                shard_op, shard_optimizer, dtensor_from_fn,
                                unshard_dtensor, local_value, DistAttr,
                                ShardingStage0, ShardingStage1,
                                ShardingStage2, ShardingStage3)
from .sharding import (group_sharded_parallel,  # noqa: F401
                       save_group_sharded_model)
from . import rpc  # noqa: F401
from .communication_impl import (Group, new_group, get_group, all_reduce,  # noqa: F401
                            all_gather, all_gather_object, all_to_all,
                            all_to_all_single, reduce_scatter, broadcast,
                            reduce, scatter, gather, send, recv, isend,
                            irecv, barrier, ReduceOp, stream, P2POp,
                            batch_isend_irecv, wait, destroy_process_group)
from .parallel import (init_parallel_env, get_rank, get_world_size,  # noqa: F401
                       ParallelEnv, is_initialized, DataParallel)
from . import fleet  # noqa: F401
from . import checkpoint  # noqa: F401
from . import launch  # noqa: F401
from . import auto_tuner  # noqa: F401
from .store import TCPStore, create_or_get_global_tcp_store  # noqa: F401
from .checkpoint import save_state_dict, load_state_dict  # noqa: F401
from .long_context import (ring_attention, ulysses_attention,  # noqa: F401
                           ring_attention_local, ulysses_attention_local)
from . import passes  # noqa: F401
from .comm_watchdog import (CommTaskManager, CommTimeoutError,  # noqa: F401
                            get_comm_task_manager, set_comm_task_manager)
from . import resilience  # noqa: F401
from .resilience import (AsyncCheckpointer, CheckpointManager,  # noqa: F401
                         CheckpointWriteError, latest_checkpoint)

from .extras import (spawn, scatter_object_list, broadcast_object_list,  # noqa: F401
                     gloo_init_parallel_env, gloo_barrier, gloo_release,
                     split, ParallelMode, is_available, get_backend,
                     shard_dataloader, ReduceType, Strategy,
                     CountFilterEntry, ShowClickEntry, ProbabilityEntry,
                     QueueDataset, InMemoryDataset)
from .fleet.dataset import BoxPSDataset, FileInstantDataset  # noqa: F401
from . import cloud_utils  # noqa: F401
from . import io  # noqa: F401
from . import utils  # noqa: F401
from . import communication  # noqa: F401
from . import ps  # noqa: F401

alltoall = all_to_all
alltoall_single = all_to_all_single

# The canonical sharding vocabulary (spec_layout.SpecLayout) is
# re-exported lazily via PEP 562, matching the rpc wire re-export
# pattern: its methods build jax.sharding.PartitionSpecs, and importing
# paddle_tpu.distributed from control-plane contexts (launcher, elastic
# agent) must not pull jax in just to name the vocabulary.
_SPEC_LAYOUT_EXPORTS = ("SpecLayout", "default_layout")


def __getattr__(name):
    if name in _SPEC_LAYOUT_EXPORTS:
        from . import spec_layout
        return getattr(spec_layout, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
