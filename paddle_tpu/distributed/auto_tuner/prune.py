"""Pruning rules (parity: auto_tuner/prune.py — registered rule functions
returning True when a candidate config should be dropped).

A config is a dict with keys: dp_degree, mp_degree, pp_degree,
sharding_degree, micro_batch_size, use_recompute (+ anything else the
search space carries). The tuner_cfg provides the model/hardware facts
(num_devices, global_batch_size, model dims, memory per chip).
"""
from __future__ import annotations

from typing import Callable, Dict, List

_PRUNE_RULES: List[Callable] = []


def register_prune(fn: Callable) -> Callable:
    _PRUNE_RULES.append(fn)
    return fn


def prune_rules() -> List[Callable]:
    return list(_PRUNE_RULES)


@register_prune
def prune_by_num_devices(tuner_cfg: Dict, cfg: Dict, history=None) -> bool:
    """Product of parallel degrees must cover exactly the device count."""
    n = tuner_cfg.get("num_devices") or tuner_cfg.get("num_gpus", 1)
    prod = (cfg.get("dp_degree", 1) * cfg.get("mp_degree", 1)
            * cfg.get("pp_degree", 1) * cfg.get("sharding_degree", 1))
    return prod != n


@register_prune
def prune_by_batch(tuner_cfg: Dict, cfg: Dict, history=None) -> bool:
    """global batch must be divisible by dp*sharding*micro_batch_size."""
    gbs = tuner_cfg.get("global_batch_size")
    if not gbs:
        return False
    dp = cfg.get("dp_degree", 1) * cfg.get("sharding_degree", 1)
    mbs = cfg.get("micro_batch_size", 1)
    if gbs % dp:
        return True
    return (gbs // dp) % mbs != 0


@register_prune
def prune_by_mp(tuner_cfg: Dict, cfg: Dict, history=None) -> bool:
    """mp must divide heads and hidden; mp should stay within one host's
    chips (ICI domain) when hosts are declared."""
    mp = cfg.get("mp_degree", 1)
    model = tuner_cfg.get("model_cfg", {})
    heads = model.get("num_heads")
    hidden = model.get("hidden_size")
    if heads and heads % mp:
        return True
    if hidden and hidden % mp:
        return True
    per_host = tuner_cfg.get("devices_per_host")
    if per_host and mp > per_host:
        return True
    return False


@register_prune
def prune_by_pp(tuner_cfg: Dict, cfg: Dict, history=None) -> bool:
    """pp must divide the layer count, and microbatch count must cover
    the pipeline (accumulate_steps >= pp for 1F1B to fill)."""
    pp = cfg.get("pp_degree", 1)
    model = tuner_cfg.get("model_cfg", {})
    layers = model.get("num_layers")
    if layers and layers % pp:
        return True
    gbs = tuner_cfg.get("global_batch_size")
    if gbs and pp > 1:
        dp = cfg.get("dp_degree", 1) * cfg.get("sharding_degree", 1)
        acc = gbs // dp // max(cfg.get("micro_batch_size", 1), 1)
        if acc < pp:
            return True
    return False


def estimate_memory_bytes(tuner_cfg: Dict, cfg: Dict) -> float:
    """Per-chip memory model for a transformer LM (the standard
    params + grads + Adam states + activations accounting; activations
    follow the Megatron formula, /sqrt under full recompute)."""
    model = tuner_cfg.get("model_cfg", {})
    h = model.get("hidden_size", 0)
    layers = model.get("num_layers", 0)
    vocab = model.get("vocab_size", 0)
    seq = model.get("seq_length", model.get("max_position_embeddings", 2048))
    inter = model.get("intermediate_size", 4 * h)
    if not h or not layers:
        return 0.0
    mp = cfg.get("mp_degree", 1)
    pp = cfg.get("pp_degree", 1)
    shard = cfg.get("sharding_degree", 1) * (
        cfg.get("dp_degree", 1)
        if tuner_cfg.get("sharding_stage", 1) >= 3 else 1)
    mbs = cfg.get("micro_batch_size", 1)

    per_layer = 4 * h * h + 3 * h * inter  # qkv/o + gated mlp
    n_params = layers * per_layer + vocab * h
    local_params = n_params / (mp * pp)
    # bf16 params + f32 grads-accum + 2x f32 adam moments + f32 master
    state_bytes = local_params * (2 + 4 / max(shard, 1) * 3 + 4)
    # activations per microbatch per layer (bf16): ~s*b*h*(34 + 5*heads*s/h)
    act = seq * mbs * h * 34 * 2
    if cfg.get("use_recompute"):
        act = act / 8  # checkpoint boundaries only
    act_bytes = act * layers / pp / mp
    # 1F1B keeps up to pp in-flight microbatches on stage 0
    act_bytes *= min(pp, max(tuner_cfg.get("num_model_chunks", 1), 1)) \
        if pp > 1 else 1
    return state_bytes + act_bytes


@register_prune
def prune_by_memory(tuner_cfg: Dict, cfg: Dict, history=None) -> bool:
    cap = tuner_cfg.get("max_mem_usage")  # bytes per chip
    if not cap:
        return False
    return estimate_memory_bytes(tuner_cfg, cfg) > cap


def prune_by_history(tuner_cfg: Dict, cfg: Dict, history) -> bool:
    """Drop configs dominated by a recorded OOM: same (mp, pp, sharding)
    with micro_batch_size >= one that already OOM'd, or <= one that
    already ran slower than the current best at a smaller batch.
    (parity: auto_tuner/utils.py history pruning)."""
    if history is None:
        return False
    for rec in history.records:
        if rec.get("error") != "oom":
            continue
        same_shape = all(
            rec["cfg"].get(k, 1) == cfg.get(k, 1)
            for k in ("mp_degree", "pp_degree", "sharding_degree",
                      "dp_degree"))
        if same_shape and cfg.get("micro_batch_size", 1) >= \
                rec["cfg"].get("micro_batch_size", 1):
            return True
        # larger model-parallel shrink of the same oom config cannot help
        # if every degree is <= the oom'd one
        dominated = all(
            cfg.get(k, 1) <= rec["cfg"].get(k, 1)
            for k in ("mp_degree", "pp_degree", "sharding_degree")) and \
            cfg.get("micro_batch_size", 1) >= \
            rec["cfg"].get("micro_batch_size", 1)
        if dominated:
            return True
    return False
