"""Profile-based trial launcher (VERDICT r2 item 9; parity:
auto_tuner/tuner.py:21 — the reference tuner launches a real training run
per candidate via `launch`, reads back the recorded metric, and feeds
failures into history pruning; it never ranks from a cost model alone).

Each candidate is measured in a child OS process, like the reference's
launch-based trials: the child builds a device mesh sized to the candidate
(`dp*mp*pp*sharding` virtual CPU devices by default, the real accelerator
when ``trial_platform`` says so), jits one llama train step with the
candidate's placements — TP via the Megatron spec map, ZeRO-3 via the FSDP
overlay, pp via the 1F1B PipelineParallel engine on `llama_pipeline_model`
— times a few steps, and prints ONE json line. Crashes, hangs, OOMs and
compile failures come back as error records that drive
``prune_by_history``.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Dict

__all__ = ["launch_trial", "measure_candidate"]


def _degrees(cfg: Dict):
    return (cfg.get("dp_degree", 1), cfg.get("mp_degree", 1),
            cfg.get("pp_degree", 1), cfg.get("sharding_degree", 1))


def measure_candidate(tuner_cfg: Dict, cfg: Dict) -> Dict:
    """Run one short training trial for `cfg` in THIS process and return
    {"tokens_per_sec", "steps", "loss"}. Assumes jax sees at least
    dp*mp*pp*sharding devices (the subprocess parent guarantees it)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    import paddle_tpu as paddle
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   create_sharded_train_step,
                                   llama_fsdp_spec, llama_param_spec,
                                   llama_pipeline_model)

    dp, mp, pp, sh = _degrees(cfg)
    world = dp * mp * pp * sh
    devs = jax.devices()
    if len(devs) < world:
        raise RuntimeError(
            f"trial needs {world} devices, found {len(devs)}")

    model = dict(tuner_cfg.get("model_cfg", {}))
    seq = int(model.get("seq_length",
                        model.get("max_position_embeddings", 128)))
    mcfg = LlamaConfig(
        vocab_size=int(model.get("vocab_size", 256)),
        hidden_size=int(model.get("hidden_size", 64)),
        intermediate_size=int(model.get("intermediate_size",
                                        4 * model.get("hidden_size", 64))),
        num_layers=int(model.get("num_layers", 2)),
        num_heads=int(model.get("num_heads", 4)),
        num_kv_heads=int(model.get("num_kv_heads",
                                   model.get("num_heads", 4))),
        max_position_embeddings=seq,
        dropout=0.0,
        use_recompute=bool(cfg.get("use_recompute", False)))

    mbs = int(cfg.get("micro_batch_size", 1))
    gbs = int(tuner_cfg.get("global_batch_size", mbs * dp * sh))
    steps = int(tuner_cfg.get("trial_steps", 3))
    rng = np.random.RandomState(0)
    paddle.seed(0)

    if pp > 1:
        if mp > 1 or sh > 1 or dp > 1:
            # the 1F1B engine places stages on disjoint sub-meshes; an
            # in-stage dp/TP/ZeRO overlay is a hybrid the trial path cannot
            # measure honestly yet — reject rather than mis-rank it
            raise RuntimeError(
                "unsupported-combo: pp>1 with dp/mp/sharding>1")
        acc = max(pp, gbs // max(mbs, 1))
        pipe = llama_pipeline_model(mcfg, num_stages=pp)

        class _S:
            pipeline_configs = {"accumulate_steps": acc,
                                "micro_batch_size": mbs}

        from paddle_tpu.distributed.fleet.meta_parallel import \
            PipelineParallel
        engine = PipelineParallel(pipe, None, _S())
        engine.train()  # training mode recursively: recompute stays active
        opt = paddle.optimizer.AdamW(1e-3, parameters=pipe.parameters())
        batch = acc * mbs
        ids = paddle.to_tensor(rng.randint(
            0, mcfg.vocab_size, (batch, seq)).astype(np.int64))
        labels = paddle.to_tensor(rng.randint(
            0, mcfg.vocab_size, (batch, seq)).astype(np.int64))
        loss = engine.train_batch((ids, labels), opt)   # warmup/compile
        float(loss)  # sync before opening the window
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch((ids, labels), opt)
        final = float(loss)  # host fetch closes the timed window
        dt = time.perf_counter() - t0
        tokens = batch * seq * steps
    else:
        data_par = dp * sh
        mesh = Mesh(np.array(devs[:world]).reshape(data_par, mp),
                    ("dp", "tp"))
        net = LlamaForCausalLM(mcfg)
        if sh > 1:
            named = {k: tuple(v.shape) for k, v in net.named_parameters()}
            spec_fn = lambda name: llama_fsdp_spec(  # noqa: E731
                name, named.get(name, (1,)), data_par)
        else:
            spec_fn = llama_param_spec
        opt = paddle.optimizer.AdamW(1e-3, parameters=net.parameters())
        step, params, opt_state, shard_batch = create_sharded_train_step(
            net, opt, mesh, spec_fn)
        batch = mbs * data_par
        ids = shard_batch(rng.randint(0, mcfg.vocab_size, (batch, seq)))
        labels = shard_batch(rng.randint(0, mcfg.vocab_size, (batch, seq)))
        key = jax.random.key(0)
        loss, params, opt_state = step(params, opt_state, key, ids,
                                       labels, 1e-3)   # warmup/compile
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, params, opt_state = step(params, opt_state, key, ids,
                                           labels, 1e-3)
        final = float(jax.device_get(loss))
        dt = time.perf_counter() - t0
        tokens = batch * seq * steps

    if not np.isfinite(final):
        raise RuntimeError(f"trial loss not finite: {final}")
    return {"tokens_per_sec": tokens / max(dt, 1e-9), "steps": steps,
            "loss": final}


def _force_cpu_platform(n_devices: int) -> None:
    """Pin this process to an n-device virtual CPU platform. Env vars alone
    are not enough: the environment's sitecustomize registers the
    accelerator backend at interpreter start, so the live jax config must
    be overridden too (same pattern as tests/conftest.py)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb
        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass


def _child_main() -> int:
    payload = json.loads(sys.stdin.read())
    try:
        if payload["tuner_cfg"].get("trial_platform", "cpu") == "cpu":
            dp, mp, pp, sh = _degrees(payload["cfg"])
            _force_cpu_platform(dp * mp * pp * sh)
        out = measure_candidate(payload["tuner_cfg"], payload["cfg"])
        out["ok"] = True
    except Exception as e:  # noqa: BLE001 — the parent classifies it
        out = {"ok": False, "error": f"{type(e).__name__}: {e}"[:500]}
    print(json.dumps(out), flush=True)
    return 0


def launch_trial(tuner_cfg: Dict, cfg: Dict) -> float:
    """Measure `cfg` in a child process; return tokens/sec.

    Raises MemoryError on OOM (so AutoTuner records 'oom' and
    prune_by_history drops dominated candidates) and RuntimeError on any
    other failure."""
    dp, mp, pp, sh = _degrees(cfg)
    world = dp * mp * pp * sh
    env = dict(os.environ)
    # make paddle_tpu importable in the child regardless of the parent's
    # cwd (run-from-checkout layout: package root = .../paddle_tpu/..)
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    env["PYTHONPATH"] = (pkg_root + os.pathsep + env["PYTHONPATH"]
                         if env.get("PYTHONPATH") else pkg_root)
    platform = tuner_cfg.get("trial_platform", "cpu")
    env["JAX_PLATFORMS"] = platform
    if platform == "cpu":
        flags = env.get("XLA_FLAGS", "")
        flags = " ".join(f for f in flags.split()
                         if "host_platform_device_count" not in f)
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={world}"
        ).strip()
    timeout = float(tuner_cfg.get("trial_timeout", 600))
    try:
        r = subprocess.run(
            [sys.executable, "-m",
             "paddle_tpu.distributed.auto_tuner.trial"],
            input=json.dumps({"tuner_cfg": tuner_cfg, "cfg": cfg}),
            capture_output=True, text=True, timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        raise RuntimeError(f"trial timeout after {timeout}s")
    line = (r.stdout or "").strip().splitlines()
    out = None
    for ln in reversed(line):
        try:
            parsed = json.loads(ln)
        except ValueError:
            continue
        if isinstance(parsed, dict):
            out = parsed
            break
    if out is None:
        raise RuntimeError(
            f"trial child died rc={r.returncode}: {(r.stderr or '')[-300:]}")
    if out.get("ok"):
        return float(out["tokens_per_sec"])
    err = out.get("error", "unknown")
    if ("RESOURCE_EXHAUSTED" in err or "oom" in err.lower()
            or "MemoryError" in err or "bad_alloc" in err):
        raise MemoryError(err)
    raise RuntimeError(err)


if __name__ == "__main__":
    sys.exit(_child_main())
