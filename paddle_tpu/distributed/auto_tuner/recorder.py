"""Trial history (parity: auto_tuner/recorder.py — add/sort/store)."""
from __future__ import annotations

import csv
import json
from typing import Dict, List, Optional


class HistoryRecorder:
    def __init__(self, metric_name: str = "throughput",
                 higher_is_better: bool = True):
        self.metric_name = metric_name
        self.higher_is_better = higher_is_better
        self.records: List[Dict] = []

    def add_cfg(self, cfg: Dict, metric: Optional[float] = None,
                error: Optional[str] = None, **extra) -> None:
        self.records.append({"cfg": dict(cfg), "metric": metric,
                             "error": error, **extra})

    def sorted_records(self) -> List[Dict]:
        ok = [r for r in self.records
              if r.get("metric") is not None and not r.get("error")]
        return sorted(ok, key=lambda r: r["metric"],
                      reverse=self.higher_is_better)

    def get_best(self) -> Optional[Dict]:
        s = self.sorted_records()
        return s[0] if s else None

    def store_history(self, path: str) -> None:
        if path.endswith(".json"):
            with open(path, "w") as f:
                json.dump(self.records, f, indent=2)
            return
        keys = sorted({k for r in self.records for k in r["cfg"]})
        with open(path, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(keys + [self.metric_name, "error"])
            for r in self.records:
                w.writerow([r["cfg"].get(k) for k in keys]
                           + [r.get("metric"), r.get("error")])

    def load_history(self, path: str) -> None:
        with open(path) as f:
            self.records = json.load(f)
