"""Parallel-strategy auto-tuner (parity: python/paddle/distributed/
auto_tuner/ — tuner.py:21 AutoTuner, grid search over
{dp, mp, pp, sharding, micro_batch_size, recompute} with rule-based
pruning and history-based pruning)."""
from .prune import register_prune, prune_by_memory, prune_by_history  # noqa: F401
from .recorder import HistoryRecorder  # noqa: F401
from .search import GridSearch  # noqa: F401
from .tuner import AutoTuner  # noqa: F401
