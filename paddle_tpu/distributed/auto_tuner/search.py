"""Search algorithms (parity: auto_tuner/search.py — GridSearch over the
candidate space built from the tune config)."""
from __future__ import annotations

import itertools
from typing import Dict, List

_DEGREE_KEYS = ("dp_degree", "mp_degree", "pp_degree", "sharding_degree")


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def candidate_space(tuner_cfg: Dict) -> List[Dict]:
    """Expand the tune config into the full cartesian candidate list.
    Each degree key may be a list, a single int, or "auto" (divisors of
    num_devices); micro_batch_size/use_recompute likewise."""
    n = tuner_cfg.get("num_devices") or tuner_cfg.get("num_gpus", 1)
    axes = {}
    for k in _DEGREE_KEYS:
        v = tuner_cfg.get(k, "auto")
        if v == "auto":
            axes[k] = _divisors(n)
        elif isinstance(v, (list, tuple)):
            axes[k] = list(v)
        else:
            axes[k] = [int(v)]
    mbs = tuner_cfg.get("micro_batch_size", "auto")
    if mbs == "auto":
        gbs = tuner_cfg.get("global_batch_size", 32)
        axes["micro_batch_size"] = [m for m in (1, 2, 4, 8, 16, 32, 64)
                                    if m <= gbs]
    elif isinstance(mbs, (list, tuple)):
        axes["micro_batch_size"] = list(mbs)
    else:
        axes["micro_batch_size"] = [int(mbs)]
    rc = tuner_cfg.get("use_recompute", "auto")
    if rc == "auto":
        axes["use_recompute"] = [False, True]
    elif isinstance(rc, (list, tuple)):
        axes["use_recompute"] = list(rc)
    else:
        axes["use_recompute"] = [bool(rc)]

    keys = list(axes)
    return [dict(zip(keys, combo))
            for combo in itertools.product(*[axes[k] for k in keys])]


class GridSearch:
    """Iterate pruned candidates (parity: auto_tuner GridSearch)."""

    def __init__(self, tuner_cfg: Dict, prune_fns, history=None):
        self.tuner_cfg = tuner_cfg
        self.all_cfgs = candidate_space(tuner_cfg)
        self.prune_fns = list(prune_fns)
        self.history = history
        self.idx = 0

    def search_once(self):
        while self.idx < len(self.all_cfgs):
            cfg = self.all_cfgs[self.idx]
            self.idx += 1
            if not any(fn(self.tuner_cfg, cfg, self.history)
                       for fn in self.prune_fns):
                return cfg
        return None
