"""AutoTuner driver (parity: auto_tuner/tuner.py:21).

TPU-native trial modes:
- ``run_trial`` callback: the caller measures a candidate in-process
  (e.g. a jitted train step over a virtual CPU mesh, or a real slice) and
  returns throughput — no subprocess relaunch needed because mesh shape
  is a jit argument, not a process topology.
- cost-model mode (no callback): candidates are ranked by the analytic
  memory/compute model in prune.estimate_memory_bytes — the reference's
  rule-based pre-ranking.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

from .prune import estimate_memory_bytes, prune_by_history, prune_rules
from .recorder import HistoryRecorder
from .search import GridSearch


class AutoTuner:
    def __init__(self, tuner_cfg: Dict,
                 run_trial: Optional[Callable[[Dict], float]] = None):
        self.tuner_cfg = dict(tuner_cfg)
        self.run_trial = run_trial
        self.recorder = HistoryRecorder(
            metric_name=self.tuner_cfg.get("metric_cfg", {})
            .get("name", "throughput"))
        fns = prune_rules() + [prune_by_history]
        self.searcher = GridSearch(self.tuner_cfg, fns, self.recorder)
        self.cur_cfg: Optional[Dict] = None

    def search_once(self) -> Optional[Dict]:
        """Next un-pruned candidate, or None when exhausted."""
        self.cur_cfg = self.searcher.search_once()
        return self.cur_cfg

    def update(self, cfg: Dict, metric: Optional[float] = None,
               error: Optional[str] = None) -> None:
        """Record a trial result ('oom' errors feed history pruning)."""
        self.recorder.add_cfg(cfg, metric=metric, error=error)

    def tune(self, max_trials: Optional[int] = None) -> Optional[Dict]:
        """Run the full loop. With a run_trial callback: measure every
        surviving candidate. Without: rank by the memory model (lowest
        projected footprint that fits wins ties toward larger mbs)."""
        trials = 0
        while True:
            cfg = self.search_once()
            if cfg is None:
                break
            trials += 1
            if self.run_trial is not None:
                try:
                    metric = self.run_trial(cfg)
                    self.update(cfg, metric=metric)
                except MemoryError:
                    self.update(cfg, error="oom")
                except Exception as e:  # noqa: BLE001 — trials may fail
                    self.update(cfg, error=repr(e))
            else:
                mem = estimate_memory_bytes(self.tuner_cfg, cfg)
                # analytic score: prefer less model-parallel fragmentation
                # and bigger microbatches (better MXU utilization)
                score = (cfg.get("micro_batch_size", 1)
                         / (cfg.get("mp_degree", 1)
                            * cfg.get("pp_degree", 1)))
                self.update(cfg, metric=score)
                del mem
            if max_trials and trials >= max_trials:
                break
        best = self.recorder.get_best()
        return best["cfg"] if best else None

    def get_best(self) -> Optional[Dict]:
        best = self.recorder.get_best()
        return best["cfg"] if best else None
