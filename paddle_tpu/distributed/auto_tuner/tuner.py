"""AutoTuner driver (parity: auto_tuner/tuner.py:21).

TPU-native trial modes:
- ``run_trial="launch"``: every surviving candidate is MEASURED by a real
  short training run in a child process (trial.launch_trial) — the
  reference's profile-based tuning loop; OOM/crash records feed
  prune_by_history.
- ``run_trial`` callback: the caller measures a candidate in-process
  (e.g. a jitted train step over a virtual CPU mesh, or a real slice) and
  returns throughput — no subprocess relaunch needed because mesh shape
  is a jit argument, not a process topology.
- cost-model mode (no callback): candidates are ranked by the analytic
  memory/compute model in prune.estimate_memory_bytes — the reference's
  rule-based pre-ranking.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Union

from .prune import estimate_memory_bytes, prune_by_history, prune_rules
from .recorder import HistoryRecorder
from .search import GridSearch


class AutoTuner:
    def __init__(self, tuner_cfg: Dict,
                 run_trial: Union[Callable[[Dict], float], str,
                                  None] = None):
        self.tuner_cfg = dict(tuner_cfg)
        if isinstance(run_trial, str):
            if run_trial != "launch":
                raise ValueError(
                    f"run_trial: unknown mode {run_trial!r} (expected "
                    "'launch' or a callable)")
            from .trial import launch_trial
            run_trial = lambda cfg: launch_trial(  # noqa: E731
                self.tuner_cfg, cfg)
        self.run_trial = run_trial
        self.recorder = HistoryRecorder(
            metric_name=self.tuner_cfg.get("metric_cfg", {})
            .get("name", "throughput"))
        fns = prune_rules() + [prune_by_history]
        self.searcher = GridSearch(self.tuner_cfg, fns, self.recorder)
        self.cur_cfg: Optional[Dict] = None

    def search_once(self) -> Optional[Dict]:
        """Next un-pruned candidate, or None when exhausted."""
        self.cur_cfg = self.searcher.search_once()
        return self.cur_cfg

    def update(self, cfg: Dict, metric: Optional[float] = None,
               error: Optional[str] = None) -> None:
        """Record a trial result ('oom' errors feed history pruning)."""
        self.recorder.add_cfg(cfg, metric=metric, error=error)

    def tune(self, max_trials: Optional[int] = None) -> Optional[Dict]:
        """Run the full loop. With a run_trial callback: measure every
        surviving candidate. Without: rank by the memory model (lowest
        projected footprint that fits wins ties toward larger mbs)."""
        trials = 0
        while True:
            cfg = self.search_once()
            if cfg is None:
                break
            trials += 1
            if self.run_trial is not None:
                try:
                    metric = self.run_trial(cfg)
                    self.update(cfg, metric=metric)
                except MemoryError:
                    self.update(cfg, error="oom")
                except Exception as e:  # noqa: BLE001 — trials may fail
                    self.update(cfg, error=repr(e))
            else:
                mem = estimate_memory_bytes(self.tuner_cfg, cfg)
                # analytic score: prefer less model-parallel fragmentation
                # and bigger microbatches (better MXU utilization)
                score = (cfg.get("micro_batch_size", 1)
                         / (cfg.get("mp_degree", 1)
                            * cfg.get("pp_degree", 1)))
                self.update(cfg, metric=score)
                del mem
            if max_trials and trials >= max_trials:
                break
        best = self.recorder.get_best()
        return best["cfg"] if best else None

    def get_best(self) -> Optional[Dict]:
        best = self.recorder.get_best()
        return best["cfg"] if best else None

    def ranked(self) -> List[Dict]:
        """Strategy list ranked by measured metric, best first — each
        entry {"cfg", "metric"} (the reference tuner's sorted history)."""
        return [{"cfg": r["cfg"], "metric": r["metric"]}
                for r in self.recorder.sorted_records()]
