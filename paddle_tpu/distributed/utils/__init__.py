"""paddle.distributed.utils (parity: python/paddle/distributed/utils/ —
__all__ is empty in the reference; the module hosts moe_utils'
global_scatter/global_gather helpers used by the MoE stack)."""
from __future__ import annotations

__all__ = []

from .moe_utils import global_gather, global_scatter  # noqa: E402,F401
