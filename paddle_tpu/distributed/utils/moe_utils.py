"""MoE dispatch all-to-alls (parity: python/paddle/distributed/utils/
moe_utils.py:20 global_scatter, :153 global_gather — the reference's CUDA
collective ops; here the exchange is the expert-parallel all_to_all the
incubate MoE layer compiles over the 'ep' mesh axis)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.dispatch import run_op
from ...core.tensor import Tensor

__all__ = ["global_scatter", "global_gather"]


def _counts(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def global_scatter(x, local_count, global_count, group=None,
                   use_calc_stream=True):
    """Reorder rows of ``x`` from local (expert, rank)-bucket order into
    the receive layout ``global_count`` describes (parity:
    moe_utils.py:20). In the single-process global-array view the
    exchange is a row permutation: bucket (e, r) of size
    local_count[e*W+r] moves to the position global_count assigns it;
    under an 'ep'-sharded mesh GSPMD compiles the same movement as the
    all-to-all."""
    lc = np.asarray(_counts(local_count)).astype(np.int64)
    gc = np.asarray(_counts(global_count)).astype(np.int64)
    if lc.sum() != gc.sum():
        raise ValueError(
            f"global_scatter: local rows {int(lc.sum())} != global rows "
            f"{int(gc.sum())}")
    src_off = np.concatenate([[0], np.cumsum(lc)[:-1]])
    dst_off = np.concatenate([[0], np.cumsum(gc)[:-1]])
    perm = np.empty(int(lc.sum()), np.int64)
    for b in range(len(lc)):
        n = int(lc[b])
        if n:
            perm[dst_off[b]:dst_off[b] + n] = np.arange(
                src_off[b], src_off[b] + n)

    def fn(xv):
        return xv[jnp.asarray(perm)]
    return run_op("global_scatter", fn, (x,))


def global_gather(x, local_count, global_count, group=None,
                  use_calc_stream=True):
    """Inverse row movement of global_scatter (parity: moe_utils.py:153)."""
    lc = np.asarray(_counts(local_count)).astype(np.int64)
    gc = np.asarray(_counts(global_count)).astype(np.int64)
    if lc.sum() != gc.sum():
        raise ValueError(
            f"global_gather: local rows {int(lc.sum())} != global rows "
            f"{int(gc.sum())}")
    src_off = np.concatenate([[0], np.cumsum(lc)[:-1]])
    dst_off = np.concatenate([[0], np.cumsum(gc)[:-1]])
    perm = np.empty(int(lc.sum()), np.int64)
    for b in range(len(lc)):
        n = int(lc[b])
        if n:
            perm[src_off[b]:src_off[b] + n] = np.arange(
                dst_off[b], dst_off[b] + n)

    def fn(xv):
        return xv[jnp.asarray(perm)]
    return run_op("global_gather", fn, (x,))
