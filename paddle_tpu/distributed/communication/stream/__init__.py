"""paddle.distributed.communication.stream (parity:
python/paddle/distributed/communication/stream/ — the *_on_calc_stream
async variants; XLA compiles collectives into programs, so these are the
same ops with the reference's (sync_op, use_calc_stream) signature)."""
from ...communication_impl import stream as _ns

all_gather = _ns.all_gather
all_reduce = _ns.all_reduce
alltoall = _ns.alltoall
from ...communication_impl import all_to_all_single as alltoall_single
broadcast = _ns.broadcast
reduce = _ns.reduce
reduce_scatter = _ns.reduce_scatter
recv = _ns.recv
send = _ns.send
scatter = _ns.scatter

__all__ = ["all_gather", "all_reduce", "alltoall", "alltoall_single",
           "broadcast", "reduce", "reduce_scatter", "recv", "send",
           "scatter"]
