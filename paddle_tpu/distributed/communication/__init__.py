"""Path-faithful package (parity: python/paddle/distributed/
communication/): the collective API lives in distributed/communication.py
on this build; this package re-exports it plus the stream.* async
variants."""
from ..communication_impl import *  # noqa: F401,F403
from ..communication_impl import __all__  # noqa: F401
# the impl module also exports a `stream` class namespace whose name
# shadows the submodule on `from . import stream`; resolve the real
# submodule through importlib so the package attribute is the module
# (the reference's layout)
import importlib as _importlib

stream = _importlib.import_module(__name__ + ".stream")
