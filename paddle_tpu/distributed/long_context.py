"""Long-context attention strategies: ring attention and Ulysses.

The reference's long-context story (SURVEY.md §5.7) stops at sharding the
sequence axis and gathering before a local attention kernel
(fleet/meta_parallel/segment_parallel.py, sequence_parallel_utils.py, and
the sep axis in base/topology.py:64); it has no ring-attention or
all-to-all attention in-tree. Here both are first-class TPU-native
strategies, designed for the ICI torus:

- ``ring_attention``: q/k/v stay sharded on the sequence axis; k/v chunks
  rotate around the ring via ``ppermute`` while each device accumulates its
  queries' attention with the online-softmax (m, l) recurrence — the
  flash-attention math at the inter-chip level. Communication is
  neighbor-to-neighbor, exactly what ICI is best at, and overlaps with the
  per-chunk compute.
- ``ulysses_attention``: one ``all_to_all`` re-shards activations from
  sequence-sharded to head-sharded, runs the full-sequence local kernel
  (the Pallas flash kernel on TPU), and swaps back. Cheaper for moderate
  sequence lengths; requires num_heads % axis_size == 0.

Both are pure-jnp + lax collectives, so jax.vjp differentiates through
them (the scan body is rematerialized instead of storing per-step score
matrices).
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8 top-level shard_map
    from jax import shard_map as _shard_map

    def shard_map(f, mesh, in_specs, out_specs):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs)
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _old_shard_map

    def shard_map(f, mesh, in_specs, out_specs):
        return _old_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs)

from ..core.dispatch import run_op

__all__ = ["ring_attention", "ulysses_attention", "ring_attention_local",
           "ulysses_attention_local"]

_NEG_INF = float("-inf")


def _online_update(qf, kc, vc, acc, m, l, q_off, k_off, causal):
    """One blockwise softmax-accumulation step.

    qf: (B, Sq, H, D) f32 (pre-scaled by the caller); kc/vc: (B, Sc, Hk, D)
    with Hk == H or a GQA divisor of it (expanded here, after the ring
    transfer, so only Hk heads ride the ICI);
    acc: (B, H, Sq, D); m, l: (B, H, Sq, 1). Offsets are global sequence
    positions of the q and k chunks (traced scalars are fine).
    """
    kc = _repeat_kv(kc, qf.shape[2])
    vc = _repeat_kv(vc, qf.shape[2])
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kc.astype(jnp.float32))
    if causal:
        sq, sk = qf.shape[1], kc.shape[1]
        qidx = q_off + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        kidx = k_off + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where((kidx <= qidx)[None, None], s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    m_safe = jnp.where(m_new == _NEG_INF, 0.0, m_new)
    alpha = jnp.exp(m - m_safe)
    p = jnp.exp(s - m_safe)
    l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.einsum("bhqk,bkhd->bhqd", p,
                                       vc.astype(jnp.float32))
    return acc_new, m_new, l_new


def _repeat_kv(k, hq):
    hk = k.shape[2]
    if hk != hq:
        k = jnp.repeat(k, hq // hk, axis=2)
    return k


def ring_attention_local(q, k, v, axis_name, axis_size, causal=True,
                         scale=None):
    """Per-shard body: call inside shard_map with q/k/v sequence-sharded
    [B, S/N, H, D]. Returns the local output chunk [B, S/N, H, D]."""
    B, sc, H, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    # GQA kv chunks rotate un-expanded (Hk heads of ICI traffic, not H)
    idx = jax.lax.axis_index(axis_name)
    qf = q.astype(jnp.float32) * scale
    acc = jnp.zeros((B, H, sc, D), jnp.float32)
    m = jnp.full((B, H, sc, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, sc, 1), jnp.float32)
    # the scan carry must be device-varying over the mesh axis from step 0
    if hasattr(jax.lax, "pcast"):
        acc, m, l = (jax.lax.pcast(x, (axis_name,), to="varying")
                     for x in (acc, m, l))
    elif hasattr(jax.lax, "pvary"):  # older jax
        acc, m, l = (jax.lax.pvary(x, (axis_name,)) for x in (acc, m, l))
    # neighbor ring: each step every device hands its current k/v chunk to
    # the previous rank, so device i sees chunk (i + t) mod N at step t
    perm = [((r + 1) % axis_size, r) for r in range(axis_size)]

    def body(carry, t):
        kc, vc, acc, m, l = carry
        j = (idx + t) % axis_size
        # remat: recompute the per-step score matrix in backward instead of
        # storing N of them (the flash-attention memory property, at the
        # inter-chip granularity)
        acc, m, l = jax.checkpoint(
            lambda kc_, vc_, a, mm, ll: _online_update(
                qf, kc_, vc_, a, mm, ll, q_off=idx * sc, k_off=j * sc,
                causal=causal))(kc, vc, acc, m, l)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (kc, vc, acc, m, l), None

    (kc, vc, acc, m, l), _ = jax.lax.scan(
        body, (k, v, acc, m, l), jnp.arange(axis_size))
    safe_l = jnp.where(l == 0.0, 1.0, l)
    out = jnp.where(l > 0.0, acc / safe_l, 0.0)                # (B,H,Sq,D)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ulysses_attention_local(q, k, v, axis_name, axis_size, causal=True,
                            scale=None):
    """Per-shard body: all_to_all seq-shard -> head-shard, local full-seq
    attention, swap back. q/k/v [B, S/N, H, D]; needs H % N == 0 (kv heads
    too: GQA is expanded before the swap when Hk < N)."""
    B, sc, H, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    k = _repeat_kv(k, H)
    v = _repeat_kv(v, H)

    def swap_in(x):   # [B, S/N, H, D] -> [B, S, H/N, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def swap_out(x):  # [B, S, H/N, D] -> [B, S/N, H, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qg, kg, vg = swap_in(q), swap_in(k), swap_in(v)
    from ..core.dispatch import select_impl
    impl = select_impl("flash_attention")
    out = impl(qg, kg, vg, None, causal, scale, 0.0, None)
    return swap_out(out)


def _as_mesh(mesh):
    if isinstance(mesh, Mesh):
        return mesh
    if mesh is None:
        from .process_mesh import get_mesh
        mesh = get_mesh()
        if mesh is None:
            raise RuntimeError("ring/ulysses attention needs a mesh: pass "
                               "one or call dist.set_mesh/init_mesh first")
    return mesh.to_jax()  # ProcessMesh


def ring_attention(q, k, v, mesh=None, seq_axis="sep", causal=True,
                   scale=None):
    """User API: q/k/v Tensors/arrays [B, S, H, D]; runs ring attention with
    the sequence dim sharded over ``seq_axis`` of ``mesh``. Differentiable
    through the tape (run_op -> jax.vjp through shard_map)."""
    jmesh = _as_mesh(mesh)
    n = int(jmesh.shape[seq_axis])
    spec = P(None, seq_axis, None, None)
    body = functools.partial(ring_attention_local, axis_name=seq_axis,
                             axis_size=n, causal=causal, scale=scale)
    fn = shard_map(lambda a, b, c: body(a, b, c), jmesh,
                   in_specs=(spec, spec, spec), out_specs=spec)
    return run_op("ring_attention", fn, (q, k, v))


def ulysses_attention(q, k, v, mesh=None, seq_axis="sep", causal=True,
                      scale=None):
    """User API: Ulysses all-to-all attention over ``seq_axis``."""
    jmesh = _as_mesh(mesh)
    n = int(jmesh.shape[seq_axis])
    spec = P(None, seq_axis, None, None)
    body = functools.partial(ulysses_attention_local, axis_name=seq_axis,
                             axis_size=n, causal=causal, scale=scale)
    fn = shard_map(lambda a, b, c: body(a, b, c), jmesh,
                   in_specs=(spec, spec, spec), out_specs=spec)
    return run_op("ulysses_attention", fn, (q, k, v))
