"""Long-context attention strategies: ring attention and Ulysses.

The reference's long-context story (SURVEY.md §5.7) stops at sharding the
sequence axis and gathering before a local attention kernel
(fleet/meta_parallel/segment_parallel.py, sequence_parallel_utils.py, and
the sep axis in base/topology.py:64); it has no ring-attention or
all-to-all attention in-tree. Here both are first-class TPU-native
strategies, designed for the ICI torus:

- ``ring_attention``: q/k/v stay sharded on the sequence axis; k/v chunks
  rotate around the ring via ``ppermute`` while each device accumulates its
  queries' attention with the online-softmax (m, l) recurrence — the
  flash-attention math at the inter-chip level. Communication is
  neighbor-to-neighbor, exactly what ICI is best at, and overlaps with the
  per-chunk compute.
- ``ulysses_attention``: one ``all_to_all`` re-shards activations from
  sequence-sharded to head-sharded, runs the full-sequence local kernel
  (the Pallas flash kernel on TPU), and swaps back. Cheaper for moderate
  sequence lengths; requires num_heads % axis_size == 0.
- ``ring_attention(..., layout="zigzag")``: the causal ring's load
  balance fix — device d holds sub-chunks (c_d, c_{2N-1-d}), making every
  step near-equal work instead of the last device gating the ring.

Both are pure-jnp + lax collectives, so jax.vjp differentiates through
them (the scan body is rematerialized instead of storing per-step score
matrices).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8 top-level shard_map
    from jax import shard_map as _shard_map

    def shard_map(f, mesh, in_specs, out_specs):
        # check_vma=False: pallas_call outputs carry no varying-mesh-axes
        # metadata, so the vma checker rejects any kernel launched inside
        # the shard (both the ring chunk kernels and Ulysses' local flash)
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _old_shard_map

    def shard_map(f, mesh, in_specs, out_specs):
        return _old_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False)

from ..core.dispatch import run_op

__all__ = ["ring_attention", "ulysses_attention", "ring_attention_local",
           "ulysses_attention_local"]

_NEG_INF = float("-inf")


def _online_update(qf, kc, vc, acc, m, l, q_off, k_off, causal):
    """One blockwise softmax-accumulation step.

    qf: (B, Sq, H, D) f32 (pre-scaled by the caller); kc/vc: (B, Sc, Hk, D)
    with Hk == H or a GQA divisor of it (expanded here, after the ring
    transfer, so only Hk heads ride the ICI);
    acc: (B, H, Sq, D); m, l: (B, H, Sq, 1). Offsets are global sequence
    positions of the q and k chunks (traced scalars are fine).
    """
    kc = _repeat_kv(kc, qf.shape[2])
    vc = _repeat_kv(vc, qf.shape[2])
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kc.astype(jnp.float32))
    if causal:
        sq, sk = qf.shape[1], kc.shape[1]
        qidx = q_off + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        kidx = k_off + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where((kidx <= qidx)[None, None], s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    m_safe = jnp.where(m_new == _NEG_INF, 0.0, m_new)
    alpha = jnp.exp(m - m_safe)
    p = jnp.exp(s - m_safe)
    l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.einsum("bhqk,bkhd->bhqd", p,
                                       vc.astype(jnp.float32))
    return acc_new, m_new, l_new


def _repeat_kv(k, hq):
    hk = k.shape[2]
    if hk != hq:
        k = jnp.repeat(k, hq // hk, axis=2)
    return k


def _vary(xs, axis_name):
    """Mark replicated-constant scan carries device-varying over the mesh
    axis (required before they meet ppermute'd values in the carry)."""
    if hasattr(jax.lax, "pcast"):
        return tuple(jax.lax.pcast(x, (axis_name,), to="varying")
                     for x in xs)
    if hasattr(jax.lax, "pvary"):  # older jax
        return tuple(jax.lax.pvary(x, (axis_name,)) for x in xs)
    return tuple(xs)


# ---------------------------------------------------------------------------
# Pallas-backed ring attention (VERDICT r4 #5): each ring step runs the
# flash block kernel (ops/pallas/flash_attention.py) on the resident k/v
# chunk, and per-chunk (out, lse) pairs merge by log-sum-exp — the online-
# softmax carry at inter-chip granularity, with the intra-chip tiling done
# by the same kernel the single-chip path ships. The backward is a second
# ring pass: dk/dv accumulators rotate WITH their chunk while each device
# adds its queries' contribution via the Pallas backward fed the global
# lse/delta (with the global lse, per-chunk gradients sum exactly).
# ---------------------------------------------------------------------------

def _bwd_delta(do, out):
    """delta_i = rowsum(dO_i * O_i) — shared by every chunk's backward."""
    return jnp.einsum("bshd,bshd->bhs", do.astype(jnp.float32),
                      out.astype(jnp.float32))


def _merge_lse(out_acc, lse_acc, o, lse):
    """Merge a new chunk's normalized (o, lse) into the running pair."""
    lse_new = jnp.logaddexp(lse_acc, lse)
    safe = jnp.where(lse_new == _NEG_INF, 0.0, lse_new)
    wa = jnp.where(lse_acc == _NEG_INF, 0.0, jnp.exp(lse_acc - safe))
    wb = jnp.where(lse == _NEG_INF, 0.0, jnp.exp(lse - safe))

    def tr(w):  # (B, H, S) weights onto (B, S, H, 1) activations
        return w.transpose(0, 2, 1)[..., None]

    return out_acc * tr(wa) + o.astype(jnp.float32) * tr(wb), lse_new


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash(q, k, v, axis_name, axis_size, causal, scale, interpret):
    """Ring attention with Pallas per-chunk compute; call inside shard_map
    with q/k/v sequence-sharded [B, S/N, H(k), D]. GQA-native: kv chunks
    rotate un-expanded (Hk heads of ICI traffic)."""
    out, _ = _ring_flash_fwd(q, k, v, axis_name, axis_size, causal, scale,
                             interpret)
    return out


def _ring_flash_fwd(q, k, v, axis_name, axis_size, causal, scale,
                    interpret):
    from ..ops.pallas.flash_attention import flash_chunk_fwd
    B, sc, H, D = q.shape
    # only the causal schedule consults the device index; a dead
    # axis_index in the non-causal graph survives DCE and lowers to a
    # PartitionId instruction the SPMD partitioner rejects
    idx = jax.lax.axis_index(axis_name) if causal else None
    perm = [((r + 1) % axis_size, r) for r in range(axis_size)]
    out0 = jnp.zeros((B, sc, H, D), jnp.float32)
    lse0 = jnp.full((B, H, sc), _NEG_INF, jnp.float32)
    out0, lse0 = _vary((out0, lse0), axis_name)

    def full(kc, vc):
        return flash_chunk_fwd(q, kc, vc, False, scale, interpret=interpret)

    def diag(kc, vc):
        return flash_chunk_fwd(q, kc, vc, True, scale, interpret=interpret)

    def skip(kc, vc):
        return (jnp.zeros((B, sc, H, D), q.dtype),
                jnp.full((B, H, sc), _NEG_INF, jnp.float32))

    def body(carry, t):
        kc, vc, out_acc, lse_acc = carry
        if causal:
            # j < idx: chunk fully visible; j == idx: the diagonal chunk
            # (in-kernel causal mask); j > idx: fully masked — skip the
            # compute entirely (lax.switch runs one branch at runtime)
            j = (idx + t) % axis_size
            br = jnp.where(j == idx, 1, jnp.where(j < idx, 0, 2))
            o, lse = jax.lax.switch(br, (full, diag, skip), kc, vc)
        else:
            o, lse = full(kc, vc)
        out_acc, lse_acc = _merge_lse(out_acc, lse_acc, o, lse)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (kc, vc, out_acc, lse_acc), None

    (_, _, out_acc, lse), _ = jax.lax.scan(
        body, (k, v, out0, lse0), jnp.arange(axis_size))
    out = out_acc.astype(q.dtype)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, axis_size, causal, scale, interpret, res,
                    do):
    from ..ops.pallas.flash_attention import flash_chunk_bwd
    q, k, v, out, lse = res
    B, sc, H, D = q.shape
    idx = jax.lax.axis_index(axis_name) if causal else None
    perm = [((r + 1) % axis_size, r) for r in range(axis_size)]
    delta = _bwd_delta(do, out)
    dq0 = jnp.zeros((B, sc, H, D), jnp.float32)
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    dq0, dk0, dv0 = _vary((dq0, dk0, dv0), axis_name)

    def full(kc, vc):
        return flash_chunk_bwd(q, kc, vc, do, lse, delta, False, scale,
                               interpret=interpret)

    def diag(kc, vc):
        return flash_chunk_bwd(q, kc, vc, do, lse, delta, True, scale,
                               interpret=interpret)

    def skip(kc, vc):
        return (jnp.zeros((B, sc, H, D), q.dtype),
                jnp.zeros(k.shape, q.dtype), jnp.zeros(v.shape, q.dtype))

    def body(carry, t):
        kc, vc, dkc, dvc, dq_acc = carry
        if causal:
            j = (idx + t) % axis_size
            br = jnp.where(j == idx, 1, jnp.where(j < idx, 0, 2))
            dq_c, dk_c, dv_c = jax.lax.switch(br, (full, diag, skip),
                                              kc, vc)
        else:
            dq_c, dk_c, dv_c = full(kc, vc)
        dq_acc = dq_acc + dq_c.astype(jnp.float32)
        # dk/dv accumulators rotate WITH their chunk: after axis_size
        # steps every chunk is home carrying all devices' contributions
        dkc = dkc + dk_c.astype(jnp.float32)
        dvc = dvc + dv_c.astype(jnp.float32)
        kc, vc, dkc, dvc = (jax.lax.ppermute(x, axis_name, perm)
                            for x in (kc, vc, dkc, dvc))
        return (kc, vc, dkc, dvc, dq_acc), None

    (_, _, dkc, dvc, dq_acc), _ = jax.lax.scan(
        body, (k, v, dk0, dv0, dq0), jnp.arange(axis_size))
    return (dq_acc.astype(q.dtype), dkc.astype(k.dtype),
            dvc.astype(v.dtype))


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def ring_chunked_single(q, k, v, n_chunks, causal, scale, interpret):
    """Single-chip model of the per-device ring compute: q/k/v [B,S,H(k),D]
    split into ``n_chunks`` sequence chunks, flash block kernel per (qi,
    kj) chunk pair, log-sum-exp merge — exactly what each ring device
    executes, minus the ppermute. This is the chunk-level bench surface
    (bench_kernels.py ring_chunks_*): its time vs the monolithic kernel
    is the ring's single-chip compute overhead."""
    out, _ = _ring_chunked_fwd(q, k, v, n_chunks, causal, scale, interpret)
    return out


def _ring_chunked_fwd(q, k, v, n_chunks, causal, scale, interpret):
    from ..ops.pallas.flash_attention import flash_chunk_fwd
    B, S, H, D = q.shape
    if S % n_chunks:
        raise ValueError(
            f"ring_chunked_single: sequence {S} not divisible by "
            f"n_chunks {n_chunks}")
    sc = S // n_chunks
    outs, lses = [], []
    for i in range(n_chunks):
        qi = q[:, i * sc:(i + 1) * sc]
        out_acc = jnp.zeros((B, sc, H, D), jnp.float32)
        lse_acc = jnp.full((B, H, sc), _NEG_INF, jnp.float32)
        for j in range(i + 1 if causal else n_chunks):
            kc = k[:, j * sc:(j + 1) * sc]
            vc = v[:, j * sc:(j + 1) * sc]
            o, lse = flash_chunk_fwd(qi, kc, vc, causal and j == i, scale,
                                     interpret=interpret)
            out_acc, lse_acc = _merge_lse(out_acc, lse_acc, o, lse)
        outs.append(out_acc.astype(q.dtype))
        lses.append(lse_acc)
    out = jnp.concatenate(outs, axis=1)
    lse = jnp.concatenate(lses, axis=2)
    return out, (q, k, v, out, lse)


def _ring_chunked_bwd(n_chunks, causal, scale, interpret, res, do):
    from ..ops.pallas.flash_attention import flash_chunk_bwd
    q, k, v, out, lse = res
    B, S, H, D = q.shape
    sc = S // n_chunks
    delta = _bwd_delta(do, out)
    dqs = []
    dks = [jnp.zeros((B, sc) + k.shape[2:], jnp.float32)
           for _ in range(n_chunks)]
    dvs = [jnp.zeros((B, sc) + v.shape[2:], jnp.float32)
           for _ in range(n_chunks)]
    for i in range(n_chunks):
        qi = q[:, i * sc:(i + 1) * sc]
        doi = do[:, i * sc:(i + 1) * sc]
        lsei = lse[:, :, i * sc:(i + 1) * sc]
        deltai = delta[:, :, i * sc:(i + 1) * sc]
        dq_acc = jnp.zeros((B, sc, H, D), jnp.float32)
        for j in range(i + 1 if causal else n_chunks):
            kc = k[:, j * sc:(j + 1) * sc]
            vc = v[:, j * sc:(j + 1) * sc]
            dq_c, dk_c, dv_c = flash_chunk_bwd(
                qi, kc, vc, doi, lsei, deltai, causal and j == i, scale,
                interpret=interpret)
            dq_acc = dq_acc + dq_c.astype(jnp.float32)
            dks[j] = dks[j] + dk_c.astype(jnp.float32)
            dvs[j] = dvs[j] + dv_c.astype(jnp.float32)
        dqs.append(dq_acc.astype(q.dtype))
    return (jnp.concatenate(dqs, axis=1),
            jnp.concatenate(dks, axis=1).astype(k.dtype),
            jnp.concatenate(dvs, axis=1).astype(v.dtype))


ring_chunked_single.defvjp(_ring_chunked_fwd, _ring_chunked_bwd)


# ---------------------------------------------------------------------------
# Zigzag ring attention: causal load balancing. With contiguous chunks,
# device 0's queries see only their own chunk (idle N-1 of N steps) while
# device N-1 computes against every chunk — the causal ring's wall time is
# the LAST device's. The zigzag layout gives device d sub-chunks
# (c_d, c_{2N-1-d}) of the 2N-way split; at every step each device runs
# exactly one always-visible pair (q_hi x k_lo) plus one pair that is
# full/diag/skip complementarily across devices — near-perfect balance,
# ~2x causal ring throughput at scale. (Same trick as the public zigzag /
# striped ring-attention formulations; built here from the identical
# flash_chunk primitives + lse merges the contiguous ring uses.)
# ---------------------------------------------------------------------------

def _zigzag_perm(S: int, N: int):
    """new-position -> old-position index map: device d's shard is
    (c_d, c_{2N-1-d}) of the 2N-way chunk split. (Reference layout for
    tests; the runtime exchange is the structured ppermute pair in
    ``_zz_shard_exchange`` — never a global gather.)"""
    import numpy as _np
    if S % (2 * N):
        raise ValueError(
            f"zigzag ring needs seq {S} divisible by 2*axis_size {2 * N}")
    scc = S // (2 * N)
    idx = []
    for d in range(N):
        idx.extend(range(d * scc, (d + 1) * scc))
        j = 2 * N - 1 - d
        idx.extend(range(j * scc, (j + 1) * scc))
    return _np.asarray(idx, dtype=_np.int32)


def _zz_shard_exchange(lo, hi, axis_name, axis_size, inverse=False):
    """Contiguous <-> zigzag shard layout in TWO ppermutes (each sub-chunk
    travels once over ICI; a global take across the sharded axis would
    all-gather the sequence and forfeit the O(S/N) memory property).

    Forward: device d holds contiguous (c_{2d}, c_{2d+1}) and ends with
    zigzag (c_d, c_{2N-1-d}). Each stream's source->target map is a
    device permutation; receivers select by their own parity (device t's
    zig-lo c_t arrives on the even-chunk stream iff t is even)."""
    n = axis_size
    idx = jax.lax.axis_index(axis_name)
    even = (idx % 2 == 0)
    if not inverse:
        # stream 0 carries c_{2d} (even chunks), stream 1 carries
        # c_{2d+1} (odd chunks); chunk c_j lands on device j if j < n
        # else 2n-1-j
        perm0 = [(d, 2 * d if 2 * d < n else 2 * n - 1 - 2 * d)
                 for d in range(n)]
        perm1 = [(d, 2 * d + 1 if 2 * d + 1 < n else 2 * n - 2 - 2 * d)
                 for d in range(n)]
        r0 = jax.lax.ppermute(lo, axis_name, perm0)
        r1 = jax.lax.ppermute(hi, axis_name, perm1)
        return jnp.where(even, r0, r1), jnp.where(even, r1, r0)
    # inverse: device d holds (c_d, c_{2n-1-d}); exactly one of the two is
    # an even chunk (parity of d decides which) — send it on the even
    # stream toward device j//2, likewise the odd chunk
    send_even = jnp.where(even, lo, hi)
    send_odd = jnp.where(even, hi, lo)
    perm_e = [(d, (d if d % 2 == 0 else 2 * n - 1 - d) // 2)
              for d in range(n)]
    perm_o = [(d, (d if d % 2 == 1 else 2 * n - 1 - d) // 2)
              for d in range(n)]
    return (jax.lax.ppermute(send_even, axis_name, perm_e),
            jax.lax.ppermute(send_odd, axis_name, perm_o))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _zigzag_ring_flash(q, k, v, axis_name, axis_size, scale, interpret):
    """Causal-only, zigzag-sharded per-device body: q/k/v
    [B, 2*scc, H(k), D] holding (c_d, c_{2N-1-d}). Call inside shard_map
    over the zigzag-permuted sequence."""
    out, _ = _zz_fwd(q, k, v, axis_name, axis_size, scale, interpret)
    return out


def _zz_split(x):
    scc = x.shape[1] // 2
    return x[:, :scc], x[:, scc:]


def _zz_fwd(q, k, v, axis_name, axis_size, scale, interpret):
    from ..ops.pallas.flash_attention import flash_chunk_fwd
    B, sc2, H, D = q.shape
    scc = sc2 // 2
    idx = jax.lax.axis_index(axis_name)
    perm = [((r + 1) % axis_size, r) for r in range(axis_size)]
    q_lo, q_hi = _zz_split(q)

    def acc0():
        return (jnp.zeros((B, scc, H, D), jnp.float32),
                jnp.full((B, H, scc), _NEG_INF, jnp.float32))

    o_lo, l_lo = _vary(acc0(), axis_name)
    o_hi, l_hi = _vary(acc0(), axis_name)

    def pair(qc, causal):
        def run(kc, vc):
            return flash_chunk_fwd(qc, kc, vc, causal, scale,
                                   interpret=interpret)
        return run

    def skip(kc, vc):
        return (jnp.zeros((B, scc, H, D), q.dtype),
                jnp.full((B, H, scc), _NEG_INF, jnp.float32))

    def body(carry, t):
        kc2, vc2, o_lo, l_lo, o_hi, l_hi = carry
        j = (idx + t) % axis_size
        br = jnp.where(j == idx, 1, jnp.where(j < idx, 0, 2))
        k_lo, k_hi = _zz_split(kc2)
        v_lo, v_hi = _zz_split(vc2)
        # pair3 (q_hi x k_lo): c_{2N-1-idx} always AFTER c_j — every
        # branch computes it, so it stays outside the switch
        o3, l3 = flash_chunk_fwd(q_hi, k_lo, v_lo, False, scale,
                                 interpret=interpret)
        o_hi, l_hi = _merge_lse(o_hi, l_hi, o3, l3)
        # pair1 (q_lo x k_lo): full when j < idx, diag at j == idx,
        # fully-masked after
        o1, l1 = jax.lax.switch(
            br, (pair(q_lo, False), pair(q_lo, True), skip), k_lo, v_lo)
        o_lo, l_lo = _merge_lse(o_lo, l_lo, o1, l1)
        # pair4 (q_hi x k_hi): the complement — masked when j < idx,
        # diag at j == idx, full after (c_{2N-1-j} < c_{2N-1-idx})
        o4, l4 = jax.lax.switch(
            br, (skip, pair(q_hi, True), pair(q_hi, False)), k_hi, v_hi)
        o_hi, l_hi = _merge_lse(o_hi, l_hi, o4, l4)
        kc2 = jax.lax.ppermute(kc2, axis_name, perm)
        vc2 = jax.lax.ppermute(vc2, axis_name, perm)
        return (kc2, vc2, o_lo, l_lo, o_hi, l_hi), None

    (_, _, o_lo, l_lo, o_hi, l_hi), _ = jax.lax.scan(
        body, (k, v, o_lo, l_lo, o_hi, l_hi), jnp.arange(axis_size))
    out = jnp.concatenate([o_lo, o_hi], axis=1).astype(q.dtype)
    lse = jnp.concatenate([l_lo, l_hi], axis=2)
    return out, (q, k, v, out, lse)


def _zz_bwd(axis_name, axis_size, scale, interpret, res, do):
    from ..ops.pallas.flash_attention import flash_chunk_bwd
    q, k, v, out, lse = res
    B, sc2, H, D = q.shape
    scc = sc2 // 2
    idx = jax.lax.axis_index(axis_name)
    perm = [((r + 1) % axis_size, r) for r in range(axis_size)]
    delta = _bwd_delta(do, out)
    q_lo, q_hi = _zz_split(q)
    do_lo, do_hi = _zz_split(do)
    l_lo, l_hi = lse[:, :, :scc], lse[:, :, scc:]
    d_lo, d_hi = delta[:, :, :scc], delta[:, :, scc:]

    kv_shape = (B, scc) + k.shape[2:]

    def bwd_pair(qc, doc, lc, dc, causal):
        def run(kc, vc):
            return flash_chunk_bwd(qc, kc, vc, doc, lc, dc, causal,
                                   scale, interpret=interpret)
        return run

    def skip(kc, vc):
        return (jnp.zeros((B, scc, H, D), q.dtype),
                jnp.zeros(kv_shape, q.dtype),
                jnp.zeros(kv_shape, q.dtype))

    dq0 = jnp.zeros((B, sc2, H, D), jnp.float32)
    dkv0 = jnp.zeros((B, sc2) + k.shape[2:], jnp.float32)
    dq0, dk0, dv0 = _vary((dq0, dkv0, dkv0), axis_name)

    def body(carry, t):
        kc2, vc2, dkc2, dvc2, dq = carry
        j = (idx + t) % axis_size
        br = jnp.where(j == idx, 1, jnp.where(j < idx, 0, 2))
        k_lo, k_hi = _zz_split(kc2)
        v_lo, v_hi = _zz_split(vc2)
        # pair3: q_hi x k_lo, always visible
        dq3, dk3, dv3 = flash_chunk_bwd(q_hi, k_lo, v_lo, do_hi, l_hi,
                                        d_hi, False, scale,
                                        interpret=interpret)
        # pair1: q_lo x k_lo (full / diag / masked)
        dq1, dk1, dv1 = jax.lax.switch(
            br, (bwd_pair(q_lo, do_lo, l_lo, d_lo, False),
                 bwd_pair(q_lo, do_lo, l_lo, d_lo, True), skip),
            k_lo, v_lo)
        # pair4: q_hi x k_hi (masked / diag / full)
        dq4, dk4, dv4 = jax.lax.switch(
            br, (skip, bwd_pair(q_hi, do_hi, l_hi, d_hi, True),
                 bwd_pair(q_hi, do_hi, l_hi, d_hi, False)),
            k_hi, v_hi)
        f32 = jnp.float32
        dq = dq.at[:, :scc].add(dq1.astype(f32))
        dq = dq.at[:, scc:].add(dq3.astype(f32) + dq4.astype(f32))
        dkc2 = dkc2.at[:, :scc].add(dk1.astype(f32) + dk3.astype(f32))
        dkc2 = dkc2.at[:, scc:].add(dk4.astype(f32))
        dvc2 = dvc2.at[:, :scc].add(dv1.astype(f32) + dv3.astype(f32))
        dvc2 = dvc2.at[:, scc:].add(dv4.astype(f32))
        kc2, vc2, dkc2, dvc2 = (jax.lax.ppermute(x, axis_name, perm)
                                for x in (kc2, vc2, dkc2, dvc2))
        return (kc2, vc2, dkc2, dvc2, dq), None

    (_, _, dkc2, dvc2, dq), _ = jax.lax.scan(
        body, (k, v, dk0, dv0, dq0), jnp.arange(axis_size))
    return dq.astype(q.dtype), dkc2.astype(k.dtype), dvc2.astype(v.dtype)


_zigzag_ring_flash.defvjp(_zz_fwd, _zz_bwd)


def ring_attention_local(q, k, v, axis_name, axis_size, causal=True,
                         scale=None, impl=None):
    """Per-shard body: call inside shard_map with q/k/v sequence-sharded
    [B, S/N, H, D]. Returns the local output chunk [B, S/N, H, D].

    ``impl``: "pallas" runs the flash block kernel inside each ring step
    (the TPU path — interpret-mode on CPU when forced); "xla" is the
    pure-jnp online-softmax reference; None picks by backend."""
    B, sc, H, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    from ..ops.pallas.common import pallas_interpret
    if impl is None:
        impl = "xla" if pallas_interpret() else "pallas"
    if impl == "pallas":
        interpret = pallas_interpret()
        return _ring_flash(q, k, v, axis_name, axis_size, causal,
                           float(scale), interpret)
    # GQA kv chunks rotate un-expanded (Hk heads of ICI traffic, not H)
    idx = jax.lax.axis_index(axis_name)
    qf = q.astype(jnp.float32) * scale
    acc = jnp.zeros((B, H, sc, D), jnp.float32)
    m = jnp.full((B, H, sc, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, sc, 1), jnp.float32)
    # the scan carry must be device-varying over the mesh axis from step 0
    acc, m, l = _vary((acc, m, l), axis_name)
    # neighbor ring: each step every device hands its current k/v chunk to
    # the previous rank, so device i sees chunk (i + t) mod N at step t
    perm = [((r + 1) % axis_size, r) for r in range(axis_size)]

    def body(carry, t):
        kc, vc, acc, m, l = carry
        j = (idx + t) % axis_size
        # remat: recompute the per-step score matrix in backward instead of
        # storing N of them (the flash-attention memory property, at the
        # inter-chip granularity)
        acc, m, l = jax.checkpoint(
            lambda kc_, vc_, a, mm, ll: _online_update(
                qf, kc_, vc_, a, mm, ll, q_off=idx * sc, k_off=j * sc,
                causal=causal))(kc, vc, acc, m, l)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (kc, vc, acc, m, l), None

    (kc, vc, acc, m, l), _ = jax.lax.scan(
        body, (k, v, acc, m, l), jnp.arange(axis_size))
    safe_l = jnp.where(l == 0.0, 1.0, l)
    out = jnp.where(l > 0.0, acc / safe_l, 0.0)                # (B,H,Sq,D)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ulysses_attention_local(q, k, v, axis_name, axis_size, causal=True,
                            scale=None):
    """Per-shard body: all_to_all seq-shard -> head-shard, local full-seq
    attention, swap back. q/k/v [B, S/N, H, D]; needs H % N == 0. GQA kv
    heads swap UN-expanded when Hk % N == 0 (Hk/H of the all_to_all
    bytes — the local flash kernel is GQA-native); only Hk < N forces
    the expansion."""
    B, sc, H, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    if k.shape[2] % axis_size:
        k = _repeat_kv(k, H)
        v = _repeat_kv(v, H)

    def swap_in(x):   # [B, S/N, H, D] -> [B, S, H/N, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def swap_out(x):  # [B, S, H/N, D] -> [B, S/N, H, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qg, kg, vg = swap_in(q), swap_in(k), swap_in(v)
    from ..core.dispatch import select_impl
    impl = select_impl("flash_attention")
    out = impl(qg, kg, vg, None, causal, scale, 0.0, None)
    return swap_out(out)


def _as_mesh(mesh):
    if isinstance(mesh, Mesh):
        return mesh
    if mesh is None:
        from .process_mesh import get_mesh
        mesh = get_mesh()
        if mesh is None:
            raise RuntimeError("ring/ulysses attention needs a mesh: pass "
                               "one or call dist.set_mesh/init_mesh first")
    return mesh.to_jax()  # ProcessMesh


def ring_attention(q, k, v, mesh=None, seq_axis="sep", causal=True,
                   scale=None, impl=None, layout="contiguous"):
    """User API: q/k/v Tensors/arrays [B, S, H, D]; runs ring attention with
    the sequence dim sharded over ``seq_axis`` of ``mesh``. Differentiable
    through the tape (run_op -> jax.vjp through shard_map). ``impl``:
    "pallas" (flash block kernel per ring step), "xla" (pure-jnp), or None
    to pick by backend. ``layout="zigzag"`` (causal only) load-balances
    the ring: device d holds sub-chunks (c_d, c_{2N-1-d}) so every step
    does near-equal work instead of the last device gating the ring."""
    jmesh = _as_mesh(mesh)
    n = int(jmesh.shape[seq_axis])
    spec = P(None, seq_axis, None, None)
    if layout == "zigzag":
        if not causal:
            raise ValueError("zigzag layout only balances the CAUSAL "
                             "ring; use layout='contiguous'")
        if impl == "xla":
            raise ValueError("zigzag ring is built from the Pallas chunk "
                             "kernels; impl='xla' is only available with "
                             "layout='contiguous'")
        if scale is None:
            scale = 1.0 / math.sqrt(int(q.shape[-1]))
        from ..ops.pallas.common import pallas_interpret
        interpret = pallas_interpret()
        _zigzag_perm(int(q.shape[1]), n)  # validate divisibility early

        def shard_body(a, b, c):
            # contiguous -> zigzag in-shard (two ppermutes), ring, back
            def to_zz(x):
                l, h = _zz_split(x)
                l, h = _zz_shard_exchange(l, h, seq_axis, n)
                return jnp.concatenate([l, h], axis=1)

            o = _zigzag_ring_flash(to_zz(a), to_zz(b), to_zz(c),
                                   seq_axis, n, float(scale), interpret)
            ol, oh = _zz_split(o)
            rl, rh = _zz_shard_exchange(ol, oh, seq_axis, n, inverse=True)
            return jnp.concatenate([rl, rh], axis=1)

        fn = shard_map(shard_body, jmesh, in_specs=(spec, spec, spec),
                       out_specs=spec)
        return run_op("ring_attention_zigzag", fn, (q, k, v))
    if layout != "contiguous":
        raise ValueError(f"unknown ring layout {layout!r}: expected "
                         "'contiguous' | 'zigzag'")
    body = functools.partial(ring_attention_local, axis_name=seq_axis,
                             axis_size=n, causal=causal, scale=scale,
                             impl=impl)
    fn = shard_map(lambda a, b, c: body(a, b, c), jmesh,
                   in_specs=(spec, spec, spec), out_specs=spec)
    return run_op("ring_attention", fn, (q, k, v))


def ulysses_attention(q, k, v, mesh=None, seq_axis="sep", causal=True,
                      scale=None):
    """User API: Ulysses all-to-all attention over ``seq_axis``."""
    jmesh = _as_mesh(mesh)
    n = int(jmesh.shape[seq_axis])
    spec = P(None, seq_axis, None, None)
    body = functools.partial(ulysses_attention_local, axis_name=seq_axis,
                             axis_size=n, causal=causal, scale=scale)
    fn = shard_map(lambda a, b, c: body(a, b, c), jmesh,
                   in_specs=(spec, spec, spec), out_specs=spec)
    return run_op("ulysses_attention", fn, (q, k, v))
