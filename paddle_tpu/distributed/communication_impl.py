"""Collective communication API.

Capability parity with the reference's communication stack
(reference: python/paddle/distributed/communication/ over
paddle/fluid/distributed/collective/process_group_nccl.cc and
paddle/phi/core/distributed/nccl_comm_context.h). TPU-native design
(SURVEY.md §5.8): there is no runtime comm library — collectives are XLA
ops compiled into the program. The same Python API surface is kept:

* Inside a ``shard_map`` region (rank-local code, the exact analog of the
  reference's per-rank dygraph code), each function lowers to the
  corresponding ``jax.lax`` collective over the group's mesh axis, and XLA
  schedules it on ICI.
* Outside, on dist tensors (global arrays), all_reduce/all_gather/... are
  reshard transitions (auto_parallel/api.py).

Groups are mesh-axis-aligned: a Group names one axis of the active device
mesh (how the reference's ring ids map to topology axes; see
fleet/base/topology.py). TCPStore/rendezvous has no in-program analog —
host-side coordination lives in distributed/launch.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import run_op
from ..core.tensor import Tensor

__all__ = ["Group", "new_group", "get_group", "all_reduce", "all_gather",
           "all_gather_object", "all_to_all", "all_to_all_single",
           "reduce_scatter", "broadcast", "reduce", "scatter", "gather",
           "send", "recv", "isend", "irecv", "barrier", "ReduceOp",
           "stream", "P2POp", "batch_isend_irecv", "wait",
           "destroy_process_group"]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A communication group = one mesh axis (or the world axis)."""

    _next_id = 0

    def __init__(self, axis_name: Optional[str], ranks: Sequence[int],
                 mesh=None):
        self.axis_name = axis_name
        self.ranks = list(ranks)
        self.nranks = len(self.ranks)
        self.mesh = mesh
        self.id = Group._next_id
        Group._next_id += 1

    @property
    def world_size(self):
        return self.nranks

    @property
    def rank(self):
        from .parallel import get_rank
        r = get_rank()
        return self.ranks.index(r) if r in self.ranks else -1

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    @property
    def process_group(self):
        return self

    def __repr__(self):
        return f"Group(id={self.id}, axis={self.axis_name}, ranks={self.ranks})"


_GROUPS = {}
_DEFAULT_GROUP: List[Optional[Group]] = [None]


def _world_group() -> Group:
    if _DEFAULT_GROUP[0] is None:
        from .parallel import init_parallel_env
        init_parallel_env()
    return _DEFAULT_GROUP[0]


def _set_world_group(g: Group):
    _DEFAULT_GROUP[0] = g
    _GROUPS[g.id] = g


def new_group(ranks=None, backend=None, timeout=None, axis_name=None) -> Group:
    """Create a group (parity: paddle.distributed.new_group). Groups must be
    axis-aligned with the active mesh; ``axis_name`` binds one (the fleet
    topology passes it; plain rank lists get a private axis over the world
    mesh when they cover it)."""
    world = _world_group()
    if ranks is None:
        ranks = list(world.ranks)
    g = Group(axis_name or world.axis_name if list(ranks) == list(world.ranks)
              else axis_name, list(ranks), mesh=world.mesh)
    _GROUPS[g.id] = g
    return g


def get_group(gid: int) -> Optional[Group]:
    return _GROUPS.get(gid)


def destroy_process_group(group=None):
    if group is None:
        _GROUPS.clear()
        _DEFAULT_GROUP[0] = None
        _P2P_CHANNELS.clear()
    else:
        _GROUPS.pop(group.id, None)
        for key in [k for k in _P2P_CHANNELS if k[0] == group.id]:
            del _P2P_CHANNELS[key]


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _axis(group: Optional[Group]) -> str:
    g = group or _world_group()
    if g.axis_name is None:
        raise ValueError(
            "group is not bound to a mesh axis; collectives inside shard_map "
            "need an axis-aligned group")
    return g.axis_name


def _unwrap(t):
    return t._data if isinstance(t, Tensor) else t


def _rewrap(tensor, arr):
    if isinstance(tensor, Tensor):
        tensor._data = arr
        return tensor
    return Tensor(arr)


def _reduce_impl(arr, op, axis_name):
    if op in (ReduceOp.SUM, "sum"):
        return jax.lax.psum(arr, axis_name)
    if op in (ReduceOp.MAX, "max"):
        return jax.lax.pmax(arr, axis_name)
    if op in (ReduceOp.MIN, "min"):
        return jax.lax.pmin(arr, axis_name)
    if op in (ReduceOp.AVG, "avg"):
        return jax.lax.pmean(arr, axis_name)
    if op in (ReduceOp.PROD, "prod"):
        # gather-then-prod: exact for negatives and zeros (PROD is rare
        # enough that the extra bandwidth beats a sign/abs decomposition)
        g = jax.lax.all_gather(arr, axis_name, axis=0)
        return jnp.prod(g, axis=0)
    raise ValueError(f"unknown reduce op {op}")


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """All-reduce (parity: paddle.distributed.all_reduce; reference
    process_group_nccl.cc:228 AllReduce). In-place on the Tensor wrapper."""
    arr = _unwrap(tensor)
    if _is_tracer(arr):
        return _rewrap(tensor, _reduce_impl(arr, op, _axis(group)))
    if isinstance(tensor, Tensor) and tensor.dist_attr is not None:
        from .auto_parallel.api import reshard
        from .process_mesh import Replicate
        attr = tensor.dist_attr
        out = reshard(tensor, attr.process_mesh,
                      [Replicate()] * attr.process_mesh.ndim)
        tensor._data = out._data
        tensor.dist_attr = out.dist_attr
        return tensor
    return tensor  # replicated single-controller value: already reduced


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    """All-gather into ``tensor_list`` (parity: dist.all_gather)."""
    arr = _unwrap(tensor)
    g = group or _world_group()
    if _is_tracer(arr):
        gathered = jax.lax.all_gather(arr, _axis(group), axis=0)
        if isinstance(tensor_list, list):
            tensor_list.clear()
            for i in range(gathered.shape[0]):
                tensor_list.append(Tensor(gathered[i]))
            return tensor_list
        return Tensor(gathered)
    # global-array mode: every "rank" holds the same value
    if isinstance(tensor_list, list):
        tensor_list.clear()
        for _ in range(g.nranks):
            tensor_list.append(Tensor(arr))
        return tensor_list
    return Tensor(jnp.stack([arr] * g.nranks))


def all_gather_object(object_list, obj, group=None):
    g = group or _world_group()
    object_list.clear()
    object_list.extend([obj] * g.nranks)
    return object_list


def reduce_scatter(tensor, tensor_or_tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    """Reduce-scatter (parity: dist.reduce_scatter)."""
    src = tensor_or_tensor_list
    if isinstance(src, (list, tuple)):
        arr = jnp.concatenate([_unwrap(t) for t in src], axis=0)
    else:
        arr = _unwrap(src)
    if _is_tracer(arr):
        out = jax.lax.psum_scatter(arr, _axis(group), scatter_dimension=0,
                                   tiled=True)
        return _rewrap(tensor, out)
    g = group or _world_group()
    n = g.nranks
    chunk = arr.shape[0] // n
    idx = g.rank if g.rank >= 0 else 0
    return _rewrap(tensor, arr[idx * chunk:(idx + 1) * chunk] * 1)


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    """All-to-all (parity: dist.alltoall; the MoE dispatch primitive,
    reference global_scatter/global_gather ops)."""
    arrs = [_unwrap(t) for t in in_tensor_list]
    if arrs and _is_tracer(arrs[0]):
        stacked = jnp.stack(arrs, axis=0)  # [n, ...]
        out = jax.lax.all_to_all(stacked, _axis(group), split_axis=0,
                                 concat_axis=0, tiled=False)
        res = [Tensor(out[i]) for i in range(out.shape[0])]
    else:
        res = [Tensor(a) for a in arrs]
    if isinstance(out_tensor_list, list):
        out_tensor_list.clear()
        out_tensor_list.extend(res)
        return out_tensor_list
    return res


def all_to_all_single(out_tensor, in_tensor, in_split_sizes=None,
                      out_split_sizes=None, group=None, sync_op=True):
    arr = _unwrap(in_tensor)
    if _is_tracer(arr):
        out = jax.lax.all_to_all(arr, _axis(group), split_axis=0,
                                 concat_axis=0, tiled=True)
        return _rewrap(out_tensor, out)
    return _rewrap(out_tensor, arr)


def broadcast(tensor, src=0, group=None, sync_op=True):
    """Broadcast from src rank (parity: dist.broadcast). Inside shard_map:
    every rank takes rank-src's value via an index-select all_gather."""
    arr = _unwrap(tensor)
    if _is_tracer(arr):
        g = group or _world_group()
        src_in_group = g.get_group_rank(src) if g.ranks else src
        if src_in_group < 0:
            raise ValueError(f"broadcast src rank {src} is not a member of "
                             f"group ranks {g.ranks}")
        gathered = jax.lax.all_gather(arr, _axis(group), axis=0)
        return _rewrap(tensor, gathered[src_in_group])
    return tensor  # replicated global value: broadcast is identity


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    arr = _unwrap(tensor)
    if _is_tracer(arr):
        out = _reduce_impl(arr, op, _axis(group))
        # non-dst ranks keep their input (reference Reduce semantics)
        g = group or _world_group()
        idx = jax.lax.axis_index(_axis(group))
        dst_in_group = g.get_group_rank(dst) if g.ranks else dst
        if dst_in_group < 0:
            raise ValueError(f"reduce dst rank {dst} is not a member of "
                             f"group ranks {g.ranks}")
        return _rewrap(tensor, jnp.where(idx == dst_in_group, out, arr))
    return tensor


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if tensor_list is not None:
        arrs = [_unwrap(t) for t in tensor_list]
        if arrs and (any(_is_tracer(a) for a in arrs)
                     or _is_tracer(_unwrap(tensor))):
            stacked = jnp.stack(arrs, 0)
            idx = jax.lax.axis_index(_axis(group))
            return _rewrap(tensor, jnp.take(stacked, idx, axis=0))
        g = group or _world_group()
        idx = max(g.rank, 0)
        return _rewrap(tensor, arrs[idx])
    return tensor


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    arr = _unwrap(tensor)
    if _is_tracer(arr):
        gathered = jax.lax.all_gather(arr, _axis(group), axis=0)
        if gather_list is not None:
            gather_list.clear()
            for i in range(gathered.shape[0]):
                gather_list.append(Tensor(gathered[i]))
        return gather_list
    g = group or _world_group()
    if gather_list is not None:
        gather_list.clear()
        gather_list.extend([Tensor(arr)] * g.nranks)
    return gather_list


def send(tensor, dst=0, group=None, sync_op=True, tag=0):
    """P2P send (parity: dist.send). Inside shard_map this is a ppermute
    shift — the reference's batched isend/irecv pipeline pattern maps to a
    single collective_permute on ICI (see fleet/meta_parallel p2p).

    SPMD semantics: (src=this group rank, dst) define a uniform ring shift
    delta = dst - src; the shifted value is buffered on the channel keyed by
    (group, delta, tag) and handed to the matching ``recv(src=..., tag=...)``
    of the same trace. Explicit channel keys — NOT arrival order — pair the
    two sides, so interleaved sends from several peers cannot mispair
    (reference pairs by (peer, tag) in ProcessGroup::Send/Recv)."""
    arr = _unwrap(tensor)
    if _is_tracer(arr):
        g = group or _world_group()
        src = g.rank if g.rank >= 0 else 0
        n = g.nranks
        delta = (dst - src) % n
        out = jax.lax.ppermute(arr, _axis(group),
                               perm=[(i, (i + delta) % n)
                                     for i in range(n)])
        chan = _P2P_CHANNELS.setdefault((g.id, delta, tag), deque())
        # evict leftovers from earlier (aborted) traces so unmatched sends
        # can't pin dead jaxprs for the process lifetime
        cur_trace = getattr(out, "_trace", None)
        while chan and getattr(chan[0], "_trace", None) is not cur_trace:
            chan.popleft()
        chan.append(out)
        return tensor
    return tensor


# per-channel FIFOs pairing in-trace send()s with recv()s: key is
# (group id, ring shift, tag); unmatched entries from an aborted trace are
# discarded when a stale tracer is seen
from collections import deque  # noqa: E402

_P2P_CHANNELS: dict = {}


def _pop_live_p2p(chan: "deque", current):
    """Pop the oldest buffered send on ``chan`` from the SAME trace as
    ``current``; discard leftovers from earlier (aborted) traces."""
    cur_trace = getattr(current, "_trace", None)
    while chan:
        cand = chan.popleft()
        if getattr(cand, "_trace", None) is cur_trace:
            return cand
    return None


def recv(tensor, src=0, group=None, sync_op=True, tag=0):
    arr = _unwrap(tensor)
    if _is_tracer(arr):
        if not isinstance(tensor, Tensor):
            raise TypeError(
                "recv/irecv write in place and require a Tensor wrapper; "
                "got a raw array whose received value would be dropped")
        g = group or _world_group()
        dstr = g.rank if g.rank >= 0 else 0
        n = g.nranks
        delta = (dstr - src) % n
        key = (g.id, delta, tag)
        chan = _P2P_CHANNELS.get(key)  # read-only: don't allocate on the
        buffered = None                # common pure-ppermute recv path
        if chan is not None:
            buffered = _pop_live_p2p(chan, arr)
            if not chan:
                _P2P_CHANNELS.pop(key, None)
        if buffered is not None:
            return _rewrap(tensor, buffered)
        out = jax.lax.ppermute(arr, _axis(group),
                               perm=[(i, (i + delta) % n)
                                     for i in range(n)])
        return _rewrap(tensor, out)
    return tensor


def isend(tensor, dst=0, group=None, tag=0):
    send(tensor, dst, group, tag=tag)
    return _Task()


def irecv(tensor, src=0, group=None, tag=0):
    recv(tensor, src, group, tag=tag)
    return _Task()


class _Task:
    """Async-task shim (parity: ProcessGroup::Task). XLA programs are
    async by construction — wait() is dispatch-order sync."""

    def wait(self):
        return True

    def is_completed(self):
        return True


class P2POp:
    def __init__(self, op, tensor, peer, group=None, tag=0):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group
        self.tag = tag


def batch_isend_irecv(p2p_op_list):
    tasks = []
    for op in p2p_op_list:
        tasks.append(op.op(op.tensor, op.peer, op.group,
                           tag=getattr(op, "tag", 0)))
    return [t if isinstance(t, _Task) else _Task() for t in tasks]


def barrier(group=None):
    """Host barrier (parity: dist.barrier). Single-controller: device sync,
    watchdog-bounded when FLAGS_comm_timeout_s > 0 (reference:
    CommTaskManager hang detection)."""
    from .comm_watchdog import CommTimeoutError, get_comm_task_manager
    try:
        get_comm_task_manager().barrier()
    except CommTimeoutError:
        raise
    except Exception:
        pass


def wait(tensor, group=None, use_calc_stream=True):
    arr = _unwrap(tensor)
    if not _is_tracer(arr):
        from .comm_watchdog import get_comm_task_manager
        get_comm_task_manager().wait(arr, desc="wait")
    return tensor


class stream:
    """paddle.distributed.stream.* parity namespace: the *_on_calc_stream
    variants are identical under XLA's single ordered program."""
    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    all_to_all = staticmethod(all_to_all)
    alltoall = staticmethod(all_to_all)
    reduce_scatter = staticmethod(reduce_scatter)
    broadcast = staticmethod(broadcast)
    reduce = staticmethod(reduce)
    scatter = staticmethod(scatter)
    send = staticmethod(send)
    recv = staticmethod(recv)
