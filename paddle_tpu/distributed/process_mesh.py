"""ProcessMesh + placements: the semi-auto SPMD core.

Capability parity with the reference's auto-parallel core
(reference: paddle/phi/core/distributed/auto_parallel/process_mesh.h,
placement_types.h Shard/Replicate/Partial, python mirror
python/paddle/distributed/auto_parallel/process_mesh.py:72).

TPU-native design: ProcessMesh wraps jax.sharding.Mesh; Shard/Replicate map
onto PartitionSpec dims (GSPMD does propagation); Partial — which JAX has no
public first-class representation for — is materialized explicitly as a
leading stacked axis sharded over the mesh axis, so every reshard transition
(r_to_s, s_to_r, p_to_r, ...) is an executable, testable function like the
reference's 13 reshard function pairs.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["ProcessMesh", "Placement", "Shard", "Replicate", "Partial",
           "get_mesh", "set_mesh", "init_mesh",
           "get_current_process_mesh"]

# `with mesh:` context stack (reference process_mesh.py)
_MESH_STACK: list = []


def get_current_process_mesh():
    """Innermost mesh entered with ``with mesh:`` or None."""
    return _MESH_STACK[-1] if _MESH_STACK else None


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicate(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    """Tensor dim ``dim`` split across this mesh axis."""

    def __init__(self, dim: int):
        self.dim = int(dim)

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def get_dim(self):
        return self.dim

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Replicate(Placement):
    def is_replicate(self):
        return True

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("replicate")

    def __repr__(self):
        return "Replicate()"


class Partial(Placement):
    """Pending reduction across this mesh axis (sum/avg/max/min)."""

    def __init__(self, reduce_type: str = "sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __eq__(self, other):
        return isinstance(other, Partial) and \
            other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("partial", self.reduce_type))

    def __repr__(self):
        return f"Partial(reduce_type={self.reduce_type})"


class ProcessMesh:
    """N-D mesh of processes/devices (parity: dist.ProcessMesh). Each mesh
    entry indexes into jax.devices()."""

    def __init__(self, mesh: Union[Sequence, np.ndarray],
                 dim_names: Optional[List[str]] = None,
                 shape=None, process_ids=None):
        arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        if len(dim_names) != arr.ndim:
            raise ValueError("dim_names must match mesh ndim")
        self._mesh_array = arr
        self._dim_names = list(dim_names)
        self._jax_mesh = None

    # -- current-mesh context (reference process_mesh.py: `with mesh:`
    # sets the mesh shard_op/shard_tensor default) ------------------------
    def __enter__(self):
        _MESH_STACK.append(self)
        return self

    def __exit__(self, *exc):
        _MESH_STACK.pop()
        return False

    @property
    def shape(self):
        return list(self._mesh_array.shape)

    @property
    def ndim(self):
        return self._mesh_array.ndim

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def mesh(self):
        return self._mesh_array

    @property
    def process_ids(self):
        return self._mesh_array.reshape(-1).tolist()

    @property
    def size(self):
        return int(self._mesh_array.size)

    def get_dim_size(self, name: str) -> int:
        return self._mesh_array.shape[self._dim_names.index(name)]

    def get_rank_by_dim_and_process_id(self, dim, process_id):
        axis = self._dim_names.index(dim) if isinstance(dim, str) else dim
        loc = np.argwhere(self._mesh_array == process_id)
        if loc.size == 0:
            return -1
        return int(loc[0][axis])

    def get_mesh_with_dim(self, dim_name: str, index=None):
        """Sub-mesh views along an axis (parity: ProcessMesh.get_mesh_with_dim)."""
        axis = self._dim_names.index(dim_name)
        moved = np.moveaxis(self._mesh_array, axis, 0)
        names = [dim_name] + [n for n in self._dim_names if n != dim_name]
        if index is not None:
            return ProcessMesh(moved[index], names[1:])
        return ProcessMesh(moved, names)

    def to_jax(self) -> Mesh:
        if self._jax_mesh is None:
            devices = jax.devices()
            dev_map = {d.id: d for d in devices}
            try:
                dev_arr = np.vectorize(lambda i: dev_map[int(i)])(self._mesh_array)
            except KeyError as e:
                raise RuntimeError(
                    f"mesh references device id {e} but only "
                    f"{len(devices)} devices exist") from None
            self._jax_mesh = Mesh(dev_arr, tuple(self._dim_names))
        return self._jax_mesh

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and np.array_equal(self._mesh_array, other._mesh_array)
                and self._dim_names == other._dim_names)

    def __hash__(self):
        return hash((self._mesh_array.tobytes(), tuple(self._dim_names)))

    def __repr__(self):
        return (f"ProcessMesh(shape={self.shape}, dim_names={self._dim_names},"
                f" process_ids={self.process_ids})")


_GLOBAL_MESH: List[Optional[ProcessMesh]] = [None]


def set_mesh(mesh: ProcessMesh):
    _GLOBAL_MESH[0] = mesh


def get_mesh() -> Optional[ProcessMesh]:
    return _GLOBAL_MESH[0]


def init_mesh(shape: Sequence[int], dim_names: Sequence[str]) -> ProcessMesh:
    n = int(np.prod(shape))
    mesh = ProcessMesh(np.arange(n).reshape(shape), list(dim_names))
    set_mesh(mesh)
    return mesh


def placements_to_spec(placements: Sequence[Placement],
                       dim_names: Sequence[str]) -> PartitionSpec:
    """[Shard(0), Replicate()] over axes (x,y) -> PartitionSpec('x', ...)
    assembled per tensor dim. Partial axes carry no spec entry (handled by
    the DistTensor stacked representation)."""
    by_tensor_dim = {}
    for axis_name, p in zip(dim_names, placements):
        if isinstance(p, Shard):
            d = p.dim
            by_tensor_dim.setdefault(d, []).append(axis_name)
    if not by_tensor_dim:
        return PartitionSpec()
    ndim = max(by_tensor_dim) + 1
    entries = []
    for d in range(ndim):
        axes = by_tensor_dim.get(d)
        if axes is None:
            entries.append(None)
        elif len(axes) == 1:
            entries.append(axes[0])
        else:
            entries.append(tuple(axes))
    return PartitionSpec(*entries)
