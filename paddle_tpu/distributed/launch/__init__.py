"""``python -m paddle_tpu.distributed.launch`` — cluster launcher
(parity: python/paddle/distributed/launch/main.py:20).

Examples::

    # single node, 4 processes (CPU-mesh testing or 4 local hosts)
    python -m paddle_tpu.distributed.launch --nproc_per_node 4 train.py

    # two nodes sharing a master
    python -m paddle_tpu.distributed.launch --nnodes 2 \
        --master 10.0.0.1:6070 train.py --my-arg 1
"""
from __future__ import annotations

import argparse
import sys

from .controller import CollectiveController

__all__ = ["main", "parse_args", "CollectiveController"]


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="TPU-native distributed launcher")
    p.add_argument("--master", default=None,
                   help="host:port of the rendezvous KV master")
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--rank", type=int, default=-1,
                   help="node rank; -1 = assign via rendezvous")
    p.add_argument("--nproc_per_node", "--nprocs", type=int, default=1)
    p.add_argument("--devices", "--gpus", default=None,
                   help="device ids visible to each process")
    p.add_argument("--job_id", default="default")
    p.add_argument("--log_dir", default=None)
    p.add_argument("--max_restart", type=int, default=3,
                   help="fault-tolerance: restarts before giving up")
    p.add_argument("--max_elastic_restart", type=int, default=10,
                   help="elastic: restart-signal relaunches before "
                        "giving up (budgeted separately from crash "
                        "restarts)")
    p.add_argument("--rendezvous_timeout", type=float, default=300.0)
    p.add_argument("script", help="training script (.py) or executable")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    return CollectiveController(args).run()


def launch():  # reference entry-point name
    sys.exit(main())
