"""Collective launch controller (parity:
python/paddle/distributed/launch/controllers/collective.py + master.py +
watcher.py): KV rendezvous across nodes, PADDLE_TRAINER_* env contract,
process watch with fault-tolerant restart.

TPU-native notes: one process per host is the normal TPU topology (all
local chips belong to one jax process), but ``--nproc_per_node`` > 1 is
supported for CPU-mesh testing. The master KV server is the native C++
store (csrc/kv_store.cpp). Child processes get the JAX distributed env
(coordinator address/process id) derived from the same rendezvous.
"""
from __future__ import annotations

import os
import signal
import socket
import time
from typing import List, Optional

from ..store import TCPStore
from .job import Container, Job, Pod, python_entrypoint


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _host_ip() -> str:
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


class CollectiveController:
    def __init__(self, args):
        self.args = args
        self.pod = Pod()
        self.store: Optional[TCPStore] = None
        self._stop = False
        self._seen_epoch = 0

    # -- rendezvous --------------------------------------------------------
    def build_job(self) -> Job:
        a = self.args
        nnodes = a.nnodes
        nproc = a.nproc_per_node
        if nnodes > 1 and not a.master:
            raise SystemExit(
                "launch: --nnodes > 1 requires --master host:port (every "
                "node must rendezvous at the same KV endpoint)")
        if nnodes > 1 or a.master:
            master = a.master
            host, port = master.rsplit(":", 1)
            is_master = a.rank == 0 or (a.rank < 0 and self._is_local(host))
            self.store = TCPStore(host, int(port), is_master=is_master,
                                  world_size=nnodes,
                                  timeout=a.rendezvous_timeout)
            node_rank = (a.rank if a.rank >= 0
                         else self.store.add("__launch/next_rank", 1) - 1)
            my_eps = ",".join(f"{_host_ip()}:{_free_port()}"
                              for _ in range(nproc))
            self.store.set(f"__launch/pod/{node_rank}", my_eps)
            self.store.barrier("launch", a.rendezvous_timeout)
            per_node = [self.store.get(f"__launch/pod/{r}").decode()
                        .split(",") for r in range(nnodes)]
            all_eps: List[str] = [ep for eps in per_node for ep in eps]
            rank_base = sum(len(per_node[r]) for r in range(node_rank))
            master_ep = master
        else:
            node_rank, rank_base = 0, 0
            all_eps = [f"127.0.0.1:{_free_port()}" for _ in range(nproc)]
            master_ep = all_eps[0]

        world = len(all_eps)
        for local_rank in range(nproc):
            rank = rank_base + local_rank
            env = {
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(world),
                "PADDLE_TRAINER_ENDPOINTS": ",".join(all_eps),
                "PADDLE_CURRENT_ENDPOINT": all_eps[rank],
                "PADDLE_LOCAL_RANK": str(local_rank),
                "PADDLE_LOCAL_SIZE": str(nproc),
                "PADDLE_NNODES": str(nnodes),
                "PADDLE_NODE_RANK": str(node_rank),
                "PADDLE_MASTER": master_ep,
                # the launcher's own KV server serves the job's global
                # store: workers must connect as clients, not re-bind
                "PADDLE_MASTER_HOSTED": "1" if self.store else "0",
                "PADDLE_JOB_ID": self.args.job_id,
                # jax.distributed.initialize reads these directly
                "JAX_COORDINATOR_ADDRESS": master_ep,
                "JAX_NUM_PROCESSES": str(world),
                "JAX_PROCESS_ID": str(rank),
            }
            if self.args.devices:
                env["FLAGS_selected_devices"] = self.args.devices
            log = (os.path.join(self.args.log_dir,
                                f"workerlog.{local_rank}")
                   if self.args.log_dir else None)
            self.pod.containers.append(Container(
                python_entrypoint(self.args.script, self.args.script_args),
                env, log))
        return Job(self.args.job_id, self.pod)

    @staticmethod
    def _is_local(host: str) -> bool:
        try:
            return socket.gethostbyname(host) in (
                "127.0.0.1", _host_ip())
        except OSError:
            return False

    # -- elastic restart signal --------------------------------------------
    # The elastic layer (fleet/elastic/manager.py) signals a required
    # re-rendezvous by bumping the job epoch key: the comm watchdog's
    # notify_comm_hang and any ElasticManager.signal_restart() land
    # there. Consuming it HERE closes the loop the resilience stack left
    # to the caller's on_fault: the launcher itself tears the pod down
    # and relaunches every process, no training-script cooperation
    # needed.
    def _elastic_epoch_key(self) -> str:
        return f"__elastic/{self.args.job_id}/epoch"

    def _elastic_epoch(self) -> int:
        """Current job epoch (0 when single-node without a store, or
        when the store is unreachable — pod status then governs alone)."""
        if self.store is None:
            return 0
        try:
            return self.store.add(self._elastic_epoch_key(), 0)
        except Exception:
            return 0

    # -- run & watch -------------------------------------------------------
    def run(self) -> int:
        self.build_job()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                signal.signal(sig, self._on_signal)
            except ValueError:
                pass  # not the main thread (tests)
        restarts = 0
        elastic_restarts = 0
        self._seen_epoch = self._elastic_epoch()
        while True:
            self.pod.deploy()
            status = self._watch()
            if status == "completed":
                return 0
            if self._stop:
                return 1
            if status == "elastic_restart":
                # a deliberate re-rendezvous, not a crash: budgeted
                # separately from failure restarts so a long elastic job
                # is not starved of its crash budget
                elastic_restarts += 1
                if elastic_restarts > self.args.max_elastic_restart:
                    print("launch: exceeded max_elastic_restart="
                          f"{self.args.max_elastic_restart}, giving up")
                    self.pod.stop(force=True)
                    return 1
                print(f"launch: elastic restart signal, relaunch "
                      f"{elastic_restarts}/{self.args.max_elastic_restart}")
            else:
                restarts += 1
                if restarts > self.args.max_restart:
                    print(f"launch: pod failed and exceeded max_restart="
                          f"{self.args.max_restart}, giving up")
                    self.pod.stop(force=True)
                    return 1
                print(f"launch: pod failed, restart {restarts}/"
                      f"{self.args.max_restart}")
            self.pod.stop(force=True)
            fresh = Pod()
            fresh.containers = [Container(c.entrypoint, c.env, c.log_path)
                                for c in self.pod.containers]
            fresh.restart_count = restarts + elastic_restarts
            self.pod = fresh

    def _watch(self) -> str:
        while not self._stop:
            status = self.pod.poll()
            if status != "running":
                if status == "failed":
                    self.pod.stop(force=True)
                return status
            epoch = self._elastic_epoch()
            if epoch > self._seen_epoch:
                self._seen_epoch = epoch
                return "elastic_restart"
            time.sleep(0.2)
        self.pod.stop(force=True)
        return "stopped"

    def _on_signal(self, signum, frame):
        del frame
        print(f"launch: got signal {signum}, stopping pod")
        self._stop = True
