"""Job/Pod/Container process model (parity:
python/paddle/distributed/launch/job/ — Job, Pod, Container with per-
container env + log files, status polling)."""
from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Dict, List, Optional


class Container:
    """One training process with its env contract and log file."""

    def __init__(self, entrypoint: List[str], env: Dict[str, str],
                 log_path: Optional[str] = None):
        self.entrypoint = entrypoint
        self.env = dict(env)
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        self._log_f = None

    def start(self):
        env = dict(os.environ)
        env.update(self.env)
        if self.log_path:
            os.makedirs(os.path.dirname(self.log_path), exist_ok=True)
            self._log_f = open(self.log_path, "ab")
            out = self._log_f
        else:
            out = None
        self.proc = subprocess.Popen(self.entrypoint, env=env, stdout=out,
                                     stderr=subprocess.STDOUT
                                     if out else None)

    @property
    def status(self) -> str:
        if self.proc is None:
            return "init"
        rc = self.proc.poll()
        if rc is None:
            return "running"
        return "completed" if rc == 0 else "failed"

    @property
    def exit_code(self):
        return self.proc.poll() if self.proc else None

    def terminate(self, force: bool = False):
        if self.proc and self.proc.poll() is None:
            self.proc.kill() if force else self.proc.terminate()

    def wait(self, timeout=None):
        if self.proc:
            self.proc.wait(timeout)
        if self._log_f:
            self._log_f.close()
            self._log_f = None


class Pod:
    """All containers of this node."""

    def __init__(self):
        self.containers: List[Container] = []
        self.restart_count = 0

    def deploy(self):
        for c in self.containers:
            c.start()

    def poll(self) -> str:
        """'running' | 'completed' | 'failed'."""
        states = [c.status for c in self.containers]
        if any(s == "failed" for s in states):
            return "failed"
        if all(s == "completed" for s in states):
            return "completed"
        return "running"

    def stop(self, force: bool = False):
        for c in self.containers:
            c.terminate(force=force)
        deadline = time.time() + 10
        for c in self.containers:
            try:
                c.wait(max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                c.terminate(force=True)
                try:
                    # even SIGKILL reaping gets a bound: a process stuck
                    # in the kernel (D-state) must orphan, not wedge the
                    # launcher's teardown forever
                    c.wait(10)
                except subprocess.TimeoutExpired:
                    pass

    def join(self):
        for c in self.containers:
            c.wait()


class Job:
    def __init__(self, job_id: str, pod: Pod):
        self.job_id = job_id
        self.pod = pod


def python_entrypoint(script: str, script_args: List[str]) -> List[str]:
    if script.endswith(".py"):
        return [sys.executable, "-u", script] + list(script_args)
    return [script] + list(script_args)
