"""Cluster topology model: which mesh axes ride ICI vs DCN.

The reference resolves rank -> device -> link class through its Cluster
description + process-group mapper
(/root/reference/python/paddle/distributed/auto_parallel/static/cluster.py,
mapper.py) and prices collectives per link class in the cost model
(static/cost/comm_op_cost.py alpha/beta tables). The TPU analog is
simpler and derivable at runtime: devices within one process (host)
reach each other over ICI; a mesh axis whose neighbor hops cross a
process boundary communicates over DCN. This module infers a per-axis
relative-bandwidth map from any device mesh, which the planner and the
Completer's comm terms consume (``axis_bandwidth``).
"""
from __future__ import annotations

from typing import Dict, Sequence

import numpy as np

__all__ = ["ICI_BANDWIDTH", "DCN_BANDWIDTH", "infer_axis_bandwidth"]

# relative link bandwidths (ICI-normalized). v5e ICI ~ 400 GB/s/link vs
# ~ 10-25 GB/s/host DCN: a DCN-crossing collective costs ~25x the bytes.
ICI_BANDWIDTH = 1.0
DCN_BANDWIDTH = 0.04


def _process_of(dev) -> int:
    return int(getattr(dev, "process_index", 0))


def infer_axis_bandwidth(devices, axis_names: Sequence[str]
                         ) -> Dict[str, float]:
    """Per-axis relative bandwidth for a device mesh.

    ``devices``: an ndarray of device objects shaped like the mesh (a
    ``jax.sharding.Mesh.devices`` array, or any object array exposing
    ``process_index``); ``axis_names``: one name per mesh dim. An axis
    where ANY neighbor hop crosses a process boundary is priced at DCN
    bandwidth — one slow hop gates the whole ring collective.
    """
    devs = np.asarray(devices, dtype=object)
    if devs.ndim != len(axis_names):
        raise ValueError(
            f"device mesh rank {devs.ndim} != {len(axis_names)} axis "
            f"names {tuple(axis_names)}")
    out: Dict[str, float] = {}
    for i, name in enumerate(axis_names):
        crosses = False
        for j in range(devs.shape[i] - 1):
            a = np.take(devs, j, axis=i).ravel()
            b = np.take(devs, j + 1, axis=i).ravel()
            if any(_process_of(x) != _process_of(y)
                   for x, y in zip(a, b)):
                crosses = True
                break
        out[name] = DCN_BANDWIDTH if crosses else ICI_BANDWIDTH
    return out
