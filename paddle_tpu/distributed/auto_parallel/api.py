"""Semi-auto SPMD API: shard_tensor / reshard / shard_layer / shard_optimizer.

Capability parity with the reference's dygraph semi-auto API
(reference: python/paddle/distributed/auto_parallel/api.py:124 shard_tensor,
:302 reshard, :401 shard_layer, :730 shard_optimizer) and the reshard
function pairs (paddle/phi/core/distributed/auto_parallel/reshard/ —
r_to_s, s_to_r, p_to_r, p_to_s, s_to_p, s_to_s, r_to_p, cross-mesh
same_status).

TPU-native design:
* Shard/Replicate  -> the payload stays a GLOBAL jax.Array carrying a
  NamedSharding; XLA chooses the collective (split, all-gather, all-to-all)
  when the sharding changes — the reference implements each transition by
  hand with NCCL; here each transition is one device_put/jit move.
* Partial          -> materialized as an explicit leading "stack" axis of
  size |axis|, sharded over that mesh axis (one addend per rank). p_to_r is
  a tree-sum over that axis (XLA lowers to all-reduce), p_to_s a sum +
  resharding (reduce-scatter). This keeps every one of the reference's 13
  transitions an observable, unit-testable function.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ...core.dispatch import run_op
from ...core.tensor import Tensor
from ..process_mesh import (Partial, Placement, ProcessMesh, Replicate, Shard,
                            placements_to_spec)

__all__ = ["DistAttr", "shard_tensor", "reshard", "shard_layer",
           "shard_op", "shard_optimizer", "dtensor_from_fn",
           "unshard_dtensor", "local_value", "ShardingStage0",
           "ShardingStage1", "ShardingStage2", "ShardingStage3"]


@dataclass
class DistAttr:
    process_mesh: ProcessMesh
    placements: List[Placement]

    @property
    def partial_axes(self) -> List[int]:
        return [i for i, p in enumerate(self.placements)
                if isinstance(p, Partial)]

    def sharding_specs(self):
        return self.placements

    # hashable so it can travel in pytree aux data (jit cache keys)
    def __hash__(self):
        return hash((self.process_mesh, tuple(self.placements)))

    def __eq__(self, other):
        return (isinstance(other, DistAttr)
                and self.process_mesh == other.process_mesh
                and list(self.placements) == list(other.placements))


def _partial_identity(reduce_type: str):
    """Stack-fill identity element per reduce type (max needs -inf etc.)."""
    if reduce_type in ("max",):
        return -jnp.inf
    if reduce_type in ("min",):
        return jnp.inf
    return 0.0


def _partial_stack(out, n, reduce_type):
    """value on rank 0, identity elsewhere; for 'avg' scale so the later
    mean returns the original value (r_to_p contract)."""
    if reduce_type in ("avg", "mean"):
        out = out * n
    fill = _partial_identity(reduce_type)
    pad = jnp.full((n - 1,) + out.shape, fill, out.dtype)
    return jnp.concatenate([out[None], pad], 0)


def _spec_with_partial_stack(mesh: ProcessMesh,
                             placements: Sequence[Placement]) -> PartitionSpec:
    """PartitionSpec for the stacked representation: one leading dim per
    partial axis (sharded over it), then the logical dims with Shard axes
    shifted by the number of stack dims."""
    partial_axes = [i for i, p in enumerate(placements)
                    if isinstance(p, Partial)]
    nstack = len(partial_axes)
    base = placements_to_spec(placements, mesh.dim_names)
    lead = tuple(mesh.dim_names[i] for i in partial_axes)
    body = tuple(base) if len(base) else ()
    return PartitionSpec(*lead, *body)


def _is_dist(x: Tensor) -> bool:
    return isinstance(x, Tensor) and x.dist_attr is not None


def _shard_spec_placements(shard_spec, mesh: ProcessMesh):
    """['x', None, 'y']-style per-tensor-dim mesh-axis names (the
    reference's shard_spec form, interface.py:122) -> placements list."""
    placements = [Replicate()] * mesh.ndim
    if shard_spec is not None:
        names = mesh.dim_names
        for tdim, axis in enumerate(shard_spec):
            if axis is None:
                continue
            if axis not in names:
                raise ValueError(
                    f"shard_spec axis '{axis}' not in mesh dims {names}")
            idx = names.index(axis)
            if placements[idx].is_shard():
                raise ValueError(
                    f"shard_spec {shard_spec} maps mesh axis '{axis}' to "
                    "two tensor dims")
            placements[idx] = Shard(tdim)
    return placements


def shard_op(op, process_mesh=None, in_shard_specs=None,
             out_shard_specs=None, **kwargs):
    """Wrap a callable so its inputs/outputs are annotated+placed on
    ``process_mesh`` per the given shard specs (parity:
    auto_parallel/interface.py:122 shard_op; specs are per-tensor lists of
    mesh dim names, None = replicated). With no mesh argument the
    innermost ``with mesh:`` context is used."""
    from ..process_mesh import get_current_process_mesh
    mesh = process_mesh if process_mesh is not None \
        else get_current_process_mesh()
    if mesh is None:
        raise AssertionError(
            "Specify the process mesh argument or use the ProcessMesh "
            "context manager first.")

    def _place(x, spec):
        if not isinstance(x, Tensor) or spec is None:
            return x
        return shard_tensor(x, mesh, _shard_spec_placements(spec, mesh))

    def wrapped(*args, **kw):
        if in_shard_specs is not None:
            args = tuple(
                _place(a, in_shard_specs[i]) if i < len(in_shard_specs)
                else a for i, a in enumerate(args))
        outs = op(*args, **kw)
        if out_shard_specs is None:
            return outs
        if isinstance(outs, (tuple, list)):
            placed = [ _place(o, out_shard_specs[i])
                       if i < len(out_shard_specs) else o
                       for i, o in enumerate(outs)]
            if isinstance(outs, tuple) and hasattr(outs, "_fields"):
                return type(outs)(*placed)   # namedtuple
            return type(outs)(placed)
        return _place(outs, out_shard_specs[0])
    return wrapped


def shard_tensor(data, mesh: ProcessMesh, placements: Sequence[Placement],
                 dtype=None, place=None, stop_gradient=None) -> Tensor:
    """Place a (global) tensor onto ``mesh`` with ``placements``
    (parity: dist.shard_tensor). Differentiable: the backward of the
    placement move is the reverse move, handled by jax's device_put vjp."""
    t = data if isinstance(data, Tensor) else Tensor(data, dtype=dtype)
    placements = list(placements)
    while len(placements) < mesh.ndim:
        placements.append(Replicate())
    jmesh = mesh.to_jax()
    partial_axes = [i for i, p in enumerate(placements)
                    if isinstance(p, Partial)]
    if partial_axes:
        # r_to_p semantics (reference r_to_p_reshard_function): rank 0 along
        # the partial axis holds the value, others hold zeros.
        def fn(a):
            out = a
            for ax_i in reversed(partial_axes):
                out = _partial_stack(out, mesh.shape[ax_i],
                                     placements[ax_i].reduce_type)
            return jax.device_put(
                out, NamedSharding(jmesh, _spec_with_partial_stack(mesh, placements)))
        out = run_op("shard_tensor", fn, (t,))
    else:
        spec = placements_to_spec(placements, mesh.dim_names)
        sharding = NamedSharding(jmesh, spec)
        out = run_op("shard_tensor",
                     lambda a: jax.device_put(a, sharding), (t,))
    if stop_gradient is not None:
        out.stop_gradient = stop_gradient
    else:
        out.stop_gradient = t.stop_gradient
    out.dist_attr = DistAttr(mesh, placements)
    return out


def _to_global(arr, attr: DistAttr):
    """Collapse the stacked partial representation to the reduced global
    value (p_to_r: all-reduce; reference p_to_r_reshard_function)."""
    partial_axes = attr.partial_axes
    if not partial_axes:
        return arr
    for k, ax_i in enumerate(partial_axes):
        p = attr.placements[ax_i]
        red = {"sum": jnp.sum, "avg": jnp.mean, "mean": jnp.mean,
               "max": jnp.max, "min": jnp.min}[p.reduce_type]
        arr = red(arr, axis=0)
    return arr


def reshard(dist_tensor: Tensor, mesh: ProcessMesh,
            placements: Sequence[Placement]) -> Tensor:
    """Transition a dist tensor to new placements — the explicit reshard API
    (parity: dist.reshard; subsumes all 13 reference transition pairs:
    r_to_s/s_to_r = split/all-gather, p_to_r = all-reduce, p_to_s =
    reduce-scatter, s_to_s = all-to-all, r_to_p = zero-pad, cross-mesh =
    device-to-device copy)."""
    t = dist_tensor
    placements = list(placements)
    while len(placements) < mesh.ndim:
        placements.append(Replicate())
    src = t.dist_attr or DistAttr(mesh, [Replicate()] * mesh.ndim)
    jmesh = mesh.to_jax()
    partial_axes = [i for i, p in enumerate(placements)
                    if isinstance(p, Partial)]

    def fn(a):
        g = _to_global(a, src)
        if partial_axes:
            out = g
            for ax_i in reversed(partial_axes):
                out = _partial_stack(out, mesh.shape[ax_i],
                                     placements[ax_i].reduce_type)
            return jax.device_put(
                out, NamedSharding(jmesh, _spec_with_partial_stack(mesh, placements)))
        spec = placements_to_spec(placements, mesh.dim_names)
        return jax.device_put(g, NamedSharding(jmesh, spec))

    out = run_op("reshard", fn, (t,))
    out.stop_gradient = t.stop_gradient
    out.dist_attr = DistAttr(mesh, placements)
    return out


def local_value(dist_tensor: Tensor) -> Tensor:
    """This process's local shard(s) (parity: DistTensor._local_value). In
    single-controller JAX all shards are addressable; returns the
    first-device shard."""
    shards = dist_tensor._data.addressable_shards
    return Tensor(jnp.asarray(shards[0].data))


def unshard_dtensor(dist_tensor: Tensor) -> Tensor:
    """Gather a dist tensor back to a dense replicated tensor
    (parity: dist.unshard_dtensor)."""
    attr = dist_tensor.dist_attr
    if attr is None:
        return dist_tensor

    def fn(a):
        g = _to_global(a, attr)
        return jax.device_put(
            g, NamedSharding(attr.process_mesh.to_jax(), PartitionSpec()))
    out = run_op("unshard_dtensor", fn, (dist_tensor,))
    out.stop_gradient = dist_tensor.stop_gradient
    return out


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs):
    """Build a dist tensor from a creation fn (parity: dist.dtensor_from_fn)."""
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def shard_layer(layer, process_mesh: ProcessMesh, shard_fn=None,
                input_fn=None, output_fn=None):
    """Shard a layer's parameters across a mesh (parity: dist.shard_layer).
    Default: replicate every parameter (the data-parallel base state);
    ``shard_fn(name, layer, mesh)`` customizes per-sublayer placement."""
    def _default_shard(name, sublayer, mesh):
        for pname, p in list(sublayer._parameters.items()):
            if p is None or _is_dist(p):
                continue
            sharded = shard_tensor(p, mesh, [Replicate()] * mesh.ndim)
            p._data = sharded._data
            p.dist_attr = sharded.dist_attr

    fn = shard_fn or _default_shard
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda l, inputs: input_fn(inputs, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda l, inputs, outputs: output_fn(outputs, process_mesh))
    return layer


# -- sharding stages (ZeRO) -------------------------------------------------

class ShardingStage0:
    """No parameter/state sharding (pure DP)."""


class ShardingStage1:
    """Optimizer-state sharding over the data axis (parity:
    DygraphShardingOptimizer, dygraph_sharding_optimizer.py:48)."""

    def __init__(self, mesh_axis="dp"):
        self.mesh_axis = mesh_axis


class ShardingStage2(ShardingStage1):
    """+ gradient sharding (parity: GroupShardedStage2)."""


class ShardingStage3(ShardingStage1):
    """+ parameter sharding (parity: GroupShardedStage3 / FSDP). On TPU this
    is a NamedSharding over the data axis: XLA all-gathers params before use
    and reduce-scatters grads — the hooks-based machinery of the reference
    collapses into GSPMD."""


def shard_optimizer(optimizer, shard_fn=None):
    """Make optimizer states follow parameter placements (parity:
    dist.shard_optimizer). States are created with zeros_like(param), which
    inherits the param's NamedSharding; an explicit ``shard_fn`` (or a
    ShardingStage1/2/3 instance) additionally shards states/params over the
    data axis for ZeRO semantics."""
    if shard_fn is None or isinstance(shard_fn, ShardingStage0):
        return optimizer

    if isinstance(shard_fn, ShardingStage1):
        stage = shard_fn
        params = optimizer._parameter_list or []
        axis = stage.mesh_axis
        meshes = [p.dist_attr.process_mesh for p in params if _is_dist(p)]
        if not meshes:
            return optimizer
        for p in params:
            if not _is_dist(p):
                continue
            attr: DistAttr = p.dist_attr
            mesh = attr.process_mesh
            if axis not in mesh.dim_names:
                continue
            ax_i = mesh.dim_names.index(axis)
            pl = list(attr.placements)
            if isinstance(shard_fn, ShardingStage3):
                # shard the parameter itself over the data axis on its
                # largest evenly-divisible dim
                if pl[ax_i].is_replicate():
                    for d in range(len(p._data.shape)):
                        taken = {q.dim for q in pl if isinstance(q, Shard)}
                        if d in taken:
                            continue
                        if p._data.shape[d] % mesh.shape[ax_i] == 0:
                            pl[ax_i] = Shard(d)
                            break
                    new = reshard(p, mesh, pl)
                    p._data = new._data
                    p.dist_attr = new.dist_attr

        # stage 1/2: optimizer STATES shard over the axis even though the
        # params stay replicated (the ZeRO-1/2 memory saving; reference
        # DygraphShardingOptimizer). Stage 3 states inherit the now-sharded
        # param layout via zeros_like. Wrap _init_state so states created
        # later are placed, and re-place any that already exist.
        if not isinstance(shard_fn, ShardingStage3):
            m0 = meshes[0]
            if axis in m0.dim_names:
                jmesh0 = m0.to_jax()
                n = m0.get_dim_size(axis)

                def _place_state(st):
                    # Compose the ZeRO axis with whatever sharding each
                    # state already inherited from its param (zeros_like
                    # preserves TP placements): shard the first free,
                    # evenly-divisible dim over `axis`; keep existing mp
                    # dims intact. States living on a mesh without `axis`
                    # (e.g. another pipeline stage's mesh) are skipped.
                    for k, v in st.items():
                        if v.ndim < 1:
                            continue
                        sh = getattr(v, "sharding", None)
                        if isinstance(sh, NamedSharding):
                            jmesh, spec = sh.mesh, tuple(sh.spec)
                        else:
                            jmesh, spec = jmesh0, ()
                        if axis not in jmesh.axis_names:
                            continue
                        spec = spec + (None,) * (v.ndim - len(spec))
                        used = {s for d in spec if d is not None
                                for s in (d if isinstance(d, tuple) else (d,))}
                        if axis in used:
                            continue
                        # divisibility must be checked against THIS state's
                        # mesh extent of `axis`, which can differ from the
                        # param mesh's (e.g. another pipeline stage's mesh)
                        n_ax = int(jmesh.shape[axis])
                        for d in range(v.ndim):
                            if spec[d] is None and v.shape[d] % n_ax == 0:
                                spec = spec[:d] + (axis,) + spec[d + 1:]
                                st[k] = jax.device_put(
                                    v, NamedSharding(jmesh,
                                                     PartitionSpec(*spec)))
                                break
                    return st

                # idempotent wrap: re-applying a strategy replaces, not
                # stacks, the placement hook
                orig_init = getattr(optimizer, "_orig_init_state", None)
                if orig_init is None:
                    orig_init = optimizer._init_state
                    optimizer._orig_init_state = orig_init
                optimizer._init_state = lambda p: _place_state(orig_init(p))
                for st in optimizer._states.values():
                    _place_state(st)
        return optimizer
    # custom callable: fn(param) -> placements
    for p in optimizer._parameter_list or []:
        if _is_dist(p):
            new_placements = shard_fn(p)
            if new_placements is not None:
                new = reshard(p, p.dist_attr.process_mesh, new_placements)
                p._data = new._data
                p.dist_attr = new.dist_attr
    return optimizer
