"""Auto-parallel Engine (parity: distributed/auto_parallel/static/
engine.py:611 — Engine(model, loss, optimizer, metrics) with
fit/evaluate/predict/prepare/save/load over the distributed program).

TPU-native: the Engine drives a DistModel (one GSPMD-partitioned XLA
train/eval program over the mesh) through epoch loops, metric updates,
and checkpointing, instead of orchestrating the reference's
Completer/Partitioner/Resharder program pipeline. Sharding strategy comes
from the same auto-completion (or user placements) DistModel uses."""
from __future__ import annotations

import os
import time
from typing import Optional

import numpy as np

from ...core.tensor import Tensor
from .static_mode import DistModel

__all__ = ["Engine"]


def _batches(data, batch_size):
    """Accept a paddle_tpu.io.DataLoader-like iterable (yielding (x, y))
    or an (x, y) array pair to slice into FULL batches (drop-last: static
    shapes keep one compiled program). batch_size > n is an error, not a
    silent no-op."""
    if hasattr(data, "__iter__") and not isinstance(data, (tuple, list)):
        yield from data
        return
    x, y = data
    x = x._data if isinstance(x, Tensor) else np.asarray(x)
    y = y._data if isinstance(y, Tensor) else np.asarray(y)
    n = x.shape[0]
    bs = batch_size or n
    if bs > n:
        raise ValueError(
            f"batch_size={bs} exceeds the {n} samples provided")
    for i in range(0, n - bs + 1, bs):
        yield x[i:i + bs], y[i:i + bs]


class Engine:
    def __init__(self, model, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None, mesh=None,
                 param_spec_fn=None, data_axis: str = "dp"):
        del cluster
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = list(metrics) if metrics is not None else []
        self._strategy = strategy
        self._dist: Optional[DistModel] = None
        self._mesh = mesh
        self._spec_fn = param_spec_fn
        self._data_axis = data_axis
        self.history: dict = {"loss": []}

    # -- preparation -------------------------------------------------------
    def prepare(self, *a, **k):
        """Build the DistModel (parity: Engine.prepare — program build +
        parallelization; here both are one jit compile deferred to the
        first batch)."""
        if self._dist is None:
            self._dist = DistModel(
                self._model, loss=self._loss, optimizer=self._optimizer,
                strategy=self._strategy, mesh=self._mesh,
                param_spec_fn=self._spec_fn, data_axis=self._data_axis)
        return self._dist

    @property
    def main_program(self):
        return self.prepare().dist_main_program()

    # -- training ----------------------------------------------------------
    def fit(self, train_data, epochs=1, batch_size=None, steps_per_epoch=None,
            log_freq=10, verbose=0):
        """Epoch loop over ``train_data`` (DataLoader-like or (x, y)
        arrays). Records per-epoch mean loss in ``history``."""
        dist = self.prepare()
        dist.train()
        for epoch in range(epochs):
            losses = []
            t0 = time.time()
            for step, (x, y) in enumerate(_batches(train_data, batch_size)):
                if steps_per_epoch and step >= steps_per_epoch:
                    break
                loss = dist.train_batch(x, y)
                losses.append(float(loss))
                if verbose and step % max(log_freq, 1) == 0:
                    print(f"epoch {epoch} step {step} "
                          f"loss {losses[-1]:.4f}")
            mean = float(np.mean(losses)) if losses else float("nan")
            self.history["loss"].append(mean)
            if verbose:
                print(f"epoch {epoch}: loss {mean:.4f} "
                      f"({time.time() - t0:.1f}s)")
        return self.history

    # -- evaluation / prediction ------------------------------------------
    def evaluate(self, valid_data, batch_size=None, steps=None):
        """Mean loss (+ metric results) over ``valid_data``."""
        dist = self.prepare()
        was_mode = dist._mode
        dist.eval()
        for m in self._metrics:
            if hasattr(m, "reset"):
                m.reset()
        losses = []
        try:
            for step, (x, y) in enumerate(
                    _batches(valid_data, batch_size)):
                if steps and step >= steps:
                    break
                # ONE forward per batch: loss and metrics both come from
                # the same logits
                out = self._predict_batch(x)
                yt = Tensor(y._data if isinstance(y, Tensor)
                            else np.asarray(y))
                if self._loss is not None:
                    losses.append(float(self._loss(Tensor(out), yt)))
                for m in self._metrics:
                    m.update(*m.compute(Tensor(out), yt))
        finally:
            dist._mode = was_mode
        result = {"loss": float(np.mean(losses)) if losses
                  else float("nan")}
        for m in self._metrics:
            result[m.name() if callable(getattr(m, "name", None))
                   else type(m).__name__] = m.accumulate()
        return result

    def _predict_batch(self, x):
        dist = self._dist
        was = dist._mode
        dist.eval()
        try:
            out = dist(x)
        finally:
            dist._mode = was
        return out._data if isinstance(out, Tensor) else out

    def predict(self, test_data, batch_size=None, steps=None):
        """Forward-only outputs, concatenated over batches."""
        self.prepare()
        outs = []
        data = test_data
        if isinstance(data, (tuple, list, np.ndarray, Tensor)) or \
                hasattr(data, "shape"):
            x = data[0] if isinstance(data, (tuple, list)) else data
            data = (x, x)   # _batches wants a pair; y is unused here
        for step, (x, _) in enumerate(_batches(data, batch_size)):
            if steps and step >= steps:
                break
            outs.append(np.asarray(self._predict_batch(x)))
        return np.concatenate(outs, axis=0) if outs else np.empty((0,))

    # -- checkpointing -----------------------------------------------------
    def save(self, path, training=True):
        """Distributed checkpoint of the current (possibly sharded) state
        (parity: Engine.save -> dist_checkpoint)."""
        from ..checkpoint import save_state_dict

        del training
        state = {k: v._data for k, v in
                 self.prepare().state_dict().items()}
        os.makedirs(path, exist_ok=True)
        save_state_dict(state, path)
        return path

    def load(self, path):
        """Load (resharding onto the current placements as needed) and
        write into the model."""
        from ..checkpoint import load_state_dict

        dist = self.prepare()
        state = {k: v._data for k, v in dist.state_dict().items()}
        load_state_dict(state, path)   # in-place, reshard-on-load
        # plain-array leaves come back wrapped as Tensors — unwrap so the
        # layer's param slots hold raw device arrays
        state = {k: (v._data if isinstance(v, Tensor) else v)
                 for k, v in state.items()}
        entries = dict(self._model.named_parameters())
        for k, v in state.items():
            if k in entries:
                entries[k]._data = v
        if dist._params is not None:
            for k in list(dist._params):
                if k in state:
                    dist._params[k] = state[k]
        dist._eval_placed = None   # re-place from the loaded weights
        return state

    def cost(self, mode="train"):
        """Analytic cost surface (parity: Engine.cost): projected per-chip
        memory from the auto-tuner's model, fed the REAL model dims when
        the model exposes a config."""
        from ..auto_tuner.prune import estimate_memory_bytes

        del mode
        jmesh = self.prepare()._jmesh
        if jmesh is None:
            # no mesh given and the degree planner has not seen a batch
            # yet: cost over all visible devices as one dp axis
            import jax
            n_axes = {"dp": len(jax.devices())}
        else:
            n_axes = dict(zip(jmesh.axis_names, jmesh.devices.shape))
        cfg = {"mp_degree": n_axes.get("tp", 1),
               "dp_degree": n_axes.get("dp", 1)}
        params = sum(int(np.prod(p.shape))
                     for p in self._model.parameters())
        mc = getattr(self._model, "cfg", None) or getattr(
            self._model, "config", None)
        model_cfg = {}
        for field in ("hidden_size", "num_layers", "vocab_size",
                      "intermediate_size", "num_heads",
                      "max_position_embeddings"):
            v = getattr(mc, field, None)
            if v is not None:
                model_cfg[field] = int(v)
        est = (estimate_memory_bytes({"model_cfg": model_cfg}, cfg)
               if model_cfg.get("hidden_size") else None)
        return {"params": params, "estimated_bytes": est}
