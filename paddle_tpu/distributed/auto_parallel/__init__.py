from ..process_mesh import ProcessMesh, Shard, Replicate, Partial  # noqa: F401
from .api import (shard_tensor, reshard, shard_layer, shard_optimizer,  # noqa: F401
                  dtensor_from_fn, unshard_dtensor, local_value, DistAttr)
from .engine import Engine  # noqa: F401
