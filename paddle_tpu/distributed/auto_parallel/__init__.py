from ..process_mesh import ProcessMesh, Shard, Replicate, Partial  # noqa: F401
from ..process_mesh import get_current_process_mesh  # noqa: F401
from .api import (shard_tensor, reshard, shard_layer, shard_op,  # noqa: F401
                  shard_optimizer, dtensor_from_fn, unshard_dtensor,
                  local_value, DistAttr)
from .engine import Engine  # noqa: F401
