"""Parallel-degree planner: choose (dp, tp) and every parameter's layout
with NO user mesh axes (VERDICT r3 #5b).

The reference searches this space two ways: the static Engine's
Planner/Parallelizer scores strategies with a cost model
(auto_parallel/static/engine.py:611, static/cost/), and the auto-tuner
grid-searches degree configs with prune rules + profile trials
(auto_tuner/tuner.py:21). Here the two halves are composed from parts
that already exist in-tree:

1. **candidate space + pruning** — every (dp_degree, mp_degree)
   factorization of the device count, filtered by the auto_tuner's
   registered prune rules (degree product, head/hidden divisibility,
   batch divisibility, memory estimate — auto_tuner/prune.py);
2. **scoring** — each surviving candidate mesh is handed to the
   Completer (completion.py), which derives all parameter placements
   over the recorded op DAG and returns its comm/compute/memory plan
   cost; the planner adds the data-parallel gradient-synchronization
   term (2(dp-1)/dp x param bytes per step, the ring all-reduce the
   per-op cost model never sees because grad sync happens between
   steps), and picks the argmin.

Everything is metadata over shapes — no device buffers move during
planning; the chosen mesh + specs feed DistModel/create_sharded_train_step
exactly as user-provided ones would.
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, Optional

import numpy as np

__all__ = ["plan_parallel_layout", "plan_parallel_config",
           "planner_stats", "rank_agreement"]

logger = logging.getLogger(__name__)

# fallback accounting (VERDICT r4 weak #8): dispatch and the Completer both
# count their silent-degrade paths and honor a strict flag; the planner's
# all-candidates-pruned fallback gets the same treatment
_PLANNER_STATS = {"planned": 0, "fallbacks": 0}


def planner_stats() -> Dict[str, int]:
    return dict(_PLANNER_STATS)


def _divisors(n: int):
    """All divisors of n, ascending."""
    out = [d for d in range(1, n + 1) if n % d == 0]
    return out


def rank_agreement(analytic: Dict[str, float],
                   measured: Dict[str, float]) -> float:
    """Kendall-tau rank correlation between the analytic candidate costs
    and measured trial times over their shared tags (VERDICT r4 #4: the
    cost model is only trustworthy if its RANKING matches measurement).
    Returns tau in [-1, 1]; 0.0 when fewer than two shared tags."""
    tags = [t for t in analytic
            if t in measured and np.isfinite(analytic[t])
            and isinstance(measured[t], (int, float))]
    if len(tags) < 2:
        return 0.0
    conc = disc = 0
    for i in range(len(tags)):
        for j in range(i + 1, len(tags)):
            a = analytic[tags[i]] - analytic[tags[j]]
            m = measured[tags[i]] - measured[tags[j]]
            s = np.sign(a) * np.sign(m)
            if s > 0:
                conc += 1
            elif s < 0:
                disc += 1
    total = len(tags) * (len(tags) - 1) / 2
    return (conc - disc) / total


def _first_prune_reason(tuner_cfg: Dict, cfg: Dict):
    """Name of the first auto_tuner prune rule that vetoes ``cfg`` (None
    when it survives). A rule that raises never vetoes — rule bugs must
    not shrink the search space."""
    from ..auto_tuner.prune import prune_rules
    for rule in prune_rules():
        try:
            hit = rule(tuner_cfg, cfg, [])
        except Exception:  # noqa: BLE001
            continue
        if hit:
            return getattr(rule, "__name__", repr(rule))
    return None


def _tp_local_bytes(param_sizes: Dict[str, int], specs, model_axis: str,
                    tp: int) -> float:
    """Per-rank parameter bytes under the planned specs: tp-sharded
    params carry 1/tp of their bytes — the dp-sync volume must come from
    the plan, not total param bytes, else hybrid candidates are
    over-penalized by ~tp."""
    local = 0.0
    for name, nbytes in param_sizes.items():
        spec = specs.get(name)
        sharded = spec is not None and any(
            e == model_axis for e in tuple(spec))
        local += nbytes / (tp if sharded else 1)
    return local


def _model_cfg_of(layer) -> Dict:
    mc = getattr(layer, "cfg", None) or getattr(layer, "config", None)
    out = {}
    for field in ("hidden_size", "num_layers", "vocab_size",
                  "intermediate_size", "num_heads", "num_kv_heads",
                  "max_position_embeddings"):
        v = getattr(mc, field, None)
        if v is not None:
            out[field] = int(v)
    return out


def plan_parallel_layout(layer, sample_feed, devices=None, loss_fn=None,
                         hbm_bytes: Optional[float] = None,
                         data_axis: str = "dp", model_axis: str = "tp",
                         profile_runner: Optional[Callable] = None,
                         axis_bandwidth: Optional[Dict[str, float]] = None):
    """Plan degrees + placements for ``layer`` over ``devices``.

    sample_feed: (x, y) arrays or ShapeDtypeStructs fixing the feed shapes
    (x.shape[0] is the global batch the dp axis must divide).

    ``profile_runner(mesh, spec_fn) -> seconds``: optional measured-trial
    hook (the auto_tuner's profile mode, tuner.py:21) — when given, the
    surviving candidates are ranked by one timed real step each instead
    of by the analytic cost alone; a candidate whose trial raises (e.g.
    OOM) is skipped, exactly like a failed tuner trial.

    Returns ``(mesh, spec_fn, info)``: a ``jax.sharding.Mesh`` with axes
    (data_axis, model_axis), a ``name -> PartitionSpec`` function for
    every parameter, and a dict describing the search (candidates,
    per-candidate costs, prune reasons, profile timings, chosen degrees).
    """
    import jax
    from jax.sharding import Mesh, PartitionSpec

    from .completion import derive_param_specs

    devices = list(devices) if devices is not None else list(jax.devices())
    n = len(devices)
    x = sample_feed[0] if isinstance(sample_feed, tuple) else sample_feed
    gbs = int(np.shape(x)[0]) if np.ndim(x) else None

    param_sizes = {name: int(np.prod(p.shape)) * 4
                   for name, p in layer.named_parameters()}
    tuner_cfg = {
        "num_devices": n,
        "global_batch_size": gbs,
        "model_cfg": _model_cfg_of(layer),
        "memory_per_chip": float(hbm_bytes) if hbm_bytes else 16e9,
    }
    if hbm_bytes:
        # arm prune_by_memory (it reads max_mem_usage): a caller-declared
        # HBM budget is a hard cap, not just documentation
        tuner_cfg["max_mem_usage"] = float(hbm_bytes)

    info: Dict = {"num_devices": n, "candidates": {}, "pruned": {}}
    best = None          # (cost, dp, tp, specs)
    survivors = []       # (dp, tp, specs, cost) for the profile pass
    # every divisor, not just powers of two (VERDICT r4 weak #8): on 6 or
    # 12 devices tp=3/6 are legal candidates the 2^k sweep never tried
    for tp in _divisors(n):
        dp = n // tp
        cfg = {"dp_degree": dp, "mp_degree": tp, "pp_degree": 1,
               "sharding_degree": 1, "micro_batch_size": 1}
        tag = f"dp{dp}xtp{tp}"
        reason = _first_prune_reason(tuner_cfg, cfg)
        if reason is not None:
            info["pruned"][tag] = reason
            continue
        mesh = Mesh(np.array(devices).reshape(dp, tp),
                    (data_axis, model_axis))
        # topology-aware by default (reference cluster.py/mapper.py): an
        # axis whose neighbor hops cross hosts rides DCN. Per-candidate:
        # the same devices reshape differently per (dp, tp), moving which
        # axis crosses the host boundary
        if axis_bandwidth is None:
            from .cluster import infer_axis_bandwidth
            bw_map = infer_axis_bandwidth(mesh.devices, mesh.axis_names)
        else:
            bw_map = axis_bandwidth
        specs, cost = derive_param_specs(
            layer, mesh, sample_feed, loss_fn=loss_fn,
            data_axis=data_axis, model_axis=model_axis,
            return_cost=True, axis_bandwidth=bw_map)
        # dp gradient sync: ring all-reduce of every grad once per
        # step — 2(dp-1)/dp x the LOCAL grad bytes (the per-op
        # plan never charges it; it happens between steps), weighted
        # by the data axis's bandwidth (ICI vs DCN — VERDICT r4 #4)
        local_bytes = _tp_local_bytes(param_sizes, specs, model_axis, tp)
        dp_bw = bw_map.get(data_axis, 1.0)
        cost = cost + 2.0 * (dp - 1) / max(dp, 1) * local_bytes \
            / max(dp_bw, 1e-9)
        info["candidates"][tag] = round(float(cost), 1)
        if np.isfinite(cost):
            survivors.append((dp, tp, specs, cost))
            if best is None or cost < best[0]:
                best = (cost, dp, tp, specs)

    if profile_runner is not None and len(survivors) <= 1:
        # profiling requested but nothing to compare: keep the info
        # contract (the key always exists when profile mode was asked)
        info["profiled_s"] = {"skipped": f"{len(survivors)} survivor(s); "
                              "nothing to rank"}
    if profile_runner is not None and len(survivors) > 1:
        # measured trials override the analytic ranking (auto_tuner
        # profile mode): one real step per candidate, failures skipped
        info["profiled_s"] = {}
        timed_best = None
        for dp, tp, specs, cost in survivors:
            tag = f"dp{dp}xtp{tp}"
            mesh = Mesh(np.array(devices).reshape(dp, tp),
                        (data_axis, model_axis))
            try:
                t = float(profile_runner(
                    mesh, lambda name, _s=specs: _s.get(
                        name, PartitionSpec())))
            except Exception as e:  # noqa: BLE001 — a failed trial loses
                info["profiled_s"][tag] = f"trial failed: {e!r}"[:120]
                continue
            info["profiled_s"][tag] = round(t, 4)
            if timed_best is None or t < timed_best[0]:
                # keep the winner's ANALYTIC cost in slot 0 so
                # info["chosen"]["cost"] stays unit-consistent with
                # info["candidates"]; the measured time rides separately
                timed_best = (t, (cost, dp, tp, specs))
        if timed_best is not None:
            best = timed_best[1]
            info["chosen_trial_s"] = round(timed_best[0], 4)
        # does the analytic ranking agree with measurement? (VERDICT r4
        # #4) — recorded so callers/tests can assert tau > 0
        info["rank_agreement_tau"] = round(rank_agreement(
            info["candidates"], info["profiled_s"]), 4)

    _PLANNER_STATS["planned"] += 1
    if best is None:
        # nothing survived (e.g. odd device count with indivisible heads):
        # fall back to pure data parallel over one axis — counted, and a
        # hard error under FLAGS_planner_strict (the silent-degrade class
        # dispatch and the Completer already guard)
        _PLANNER_STATS["fallbacks"] += 1
        from ...core import flags as _flags
        if _flags.get_flag("planner_strict"):
            raise RuntimeError(
                "planner_strict: every planner candidate was pruned "
                f"({info['pruned']}); refusing the silent pure-dp "
                "fallback")
        logger.warning(
            "plan_parallel_layout: no candidate survived pruning "
            "(%s); falling back to dp=%d", info["pruned"], n)
        mesh = Mesh(np.array(devices).reshape(n, 1),
                    (data_axis, model_axis))
        info["chosen"] = {"dp_degree": n, "mp_degree": 1,
                          "fallback": "all candidates pruned"}
        return mesh, (lambda name: PartitionSpec()), info

    cost, dp, tp, specs = best
    info["chosen"] = {"dp_degree": dp, "mp_degree": tp,
                      "cost": round(float(cost), 1),
                      "sharded_params": sum(
                          1 for s in specs.values() if tuple(s)),
                      "total_params": len(specs)}
    logger.info("plan_parallel_layout: chose dp=%d tp=%d (cost %.3g) "
                "over %s", dp, tp, cost, info["candidates"])
    mesh = Mesh(np.array(devices).reshape(dp, tp), (data_axis, model_axis))

    def spec_fn(name: str) -> PartitionSpec:
        return specs.get(name, PartitionSpec())

    return mesh, spec_fn, info


_RECOMPUTE_FLOP_MULT = {None: 1.0, "dots_saveable": 1.05, "full": 1.3}
_HOST_LAUNCH_FRAC = 1e-3   # host-driven PP schedule cost per launch,
                           # as a fraction of the per-device plan cost


def plan_parallel_config(layer, sample_feed, devices=None, loss_fn=None,
                         hbm_bytes: Optional[float] = None,
                         data_axis: str = "dp", model_axis: str = "tp",
                         stage_layers=None,
                         micro_batch_sizes=(1, 2, 4, 8),
                         recompute_options=(None, "dots_saveable", "full"),
                         axis_bandwidth: Optional[Dict[str, float]] = None):
    """Search the FULL hybrid config space (VERDICT r4 next-round #3):
    candidate tuples (dp, tp, pp, sharding, micro_batch, recompute) over
    every divisor factorization of the device count, co-searched with the
    SegmentLayers stage splitter, pruned by the auto_tuner rules
    (divisibility, batch, pipeline fill, memory — auto_tuner/prune.py)
    and scored analytically:

      cost = plan_cost(dp, tp) / pp x stage_imbalance x bubble(acc, pp)
             x recompute_flops
           + dp-sync ring term / bandwidth(dp axis)
           + pp p2p activations / bandwidth(pp axis)
           + host launch overhead x (acc x pp)

    where plan_cost is the Completer's per-device compute+reshard cost on
    the (dp, tp) sub-mesh, stage_imbalance = max_stage/mean_stage from the
    balanced stage split of ``stage_layers``, and bubble is the 1F1B
    (acc + pp - 1)/acc fill factor. This composes the reference's two
    search mechanisms — the auto_tuner degree grid (auto_tuner/tuner.py:21,
    utils.py search space) and the static Planner's cost-modeled strategy
    scoring (auto_parallel/static/engine.py:611, static/cost/) — into one
    argmin.

    ``stage_layers``: ordered list of sublayers for the pipeline stage
    split (e.g. model.decoder_layers); when omitted, stages are assumed
    uniform over model_cfg.num_layers.

    Returns ``(chosen, info)``: chosen = {dp_degree, mp_degree, pp_degree,
    sharding_degree, micro_batch_size, recompute, accumulate_steps,
    stage_bounds, cost}; info carries every candidate/pruned tag.
    """
    import jax

    from .completion import derive_param_specs

    devices = list(devices) if devices is not None else list(jax.devices())
    n = len(devices)
    x = sample_feed[0] if isinstance(sample_feed, tuple) else sample_feed
    gbs = int(np.shape(x)[0]) if np.ndim(x) else None
    # tokens-per-row for the p2p activation term: axis 1 is a sequence
    # length only when the feed is integer token ids — for a float
    # (B, features) feed the boundary activation is (mbs, hidden), and
    # reading the feature width as "seq" would over-penalize pipelining
    xd = np.dtype(getattr(x, "dtype", np.float32))
    seq = (int(np.shape(x)[1])
           if np.ndim(x) and len(np.shape(x)) > 1
           and np.issubdtype(xd, np.integer) else 1)

    model_cfg = _model_cfg_of(layer)
    hidden = model_cfg.get("hidden_size", 0)
    param_sizes = {name: int(np.prod(p.shape)) * 4
                   for name, p in layer.named_parameters()}
    tuner_cfg = {
        "num_devices": n,
        "global_batch_size": gbs,
        "model_cfg": model_cfg,
        "memory_per_chip": float(hbm_bytes) if hbm_bytes else 16e9,
    }
    if hbm_bytes:
        tuner_cfg["max_mem_usage"] = float(hbm_bytes)

    # stage-split co-search: per-pp balanced bounds + imbalance factor
    def stage_split(pp: int):
        if pp == 1:
            return None, 1.0
        if stage_layers:
            from ..fleet.meta_parallel.parallel_layers import SegmentLayers
            if len(stage_layers) < pp:
                return None, None  # cannot fill the stages
            seg = SegmentLayers(list(stage_layers), pp, method="auto",
                                built_layers=list(stage_layers))
            bounds = seg.do_segment()
            w = seg._param_weights()
            stage_w = [sum(w[a:b]) for a, b in zip(bounds, bounds[1:])]
            imb = max(stage_w) * pp / max(sum(stage_w), 1)
            return bounds, imb
        layers_n = model_cfg.get("num_layers")
        if not layers_n or layers_n % pp:
            return None, None
        per = layers_n // pp
        return [i * per for i in range(pp)] + [layers_n], 1.0

    info: Dict = {"num_devices": n, "candidates": {}, "pruned": {}}
    plan_cache: Dict = {}   # (dp, tp) -> (specs, base_cost, local_bytes)

    def planned(dp, tp):
        if (dp, tp) in plan_cache:
            return plan_cache[(dp, tp)]
        from jax.sharding import Mesh
        mesh = Mesh(np.array(devices[:dp * tp]).reshape(dp, tp),
                    (data_axis, model_axis))
        if axis_bandwidth is None:
            from .cluster import infer_axis_bandwidth
            sub_bw = infer_axis_bandwidth(mesh.devices, mesh.axis_names)
        else:
            sub_bw = axis_bandwidth
        specs, cost = derive_param_specs(
            layer, mesh, sample_feed, loss_fn=loss_fn,
            data_axis=data_axis, model_axis=model_axis,
            return_cost=True, axis_bandwidth=sub_bw)
        local_bytes = _tp_local_bytes(param_sizes, specs, model_axis, tp)
        plan_cache[(dp, tp)] = (specs, float(cost), local_bytes)
        return plan_cache[(dp, tp)]

    def candidate_bw(dp, tp, pp, sh):
        """Per-candidate link classes from the rank->device mapping: the
        full factorization reshapes the same device list, moving which
        axis crosses the host boundary (reference cluster.py/mapper.py).
        tp innermost = fastest-varying, the ICI-first convention."""
        if axis_bandwidth is not None:
            return axis_bandwidth
        from .cluster import infer_axis_bandwidth
        full = np.array(devices, dtype=object).reshape(pp, sh, dp, tp)
        return infer_axis_bandwidth(
            full, ("pp", "sharding", data_axis, model_axis))

    best = None   # (cost, cfg, bounds, specs)
    rc_tag = {None: "none", "dots_saveable": "dots", "full": "full"}
    for pp in _divisors(n):
        bounds, imb = stage_split(pp)
        if imb is None:
            info["pruned"][f"pp{pp}"] = "stage split infeasible"
            continue
        for sh in _divisors(n // pp):
            for tp in _divisors(n // (pp * sh)):
                dp = n // (pp * sh * tp)
                # link classes depend only on the factorization — hoist
                # out of the (mbs, rc) inner sweep
                bw = candidate_bw(dp, tp, pp, sh)
                for mbs in micro_batch_sizes:
                    for rc in recompute_options:
                        cfg = {"dp_degree": dp, "mp_degree": tp,
                               "pp_degree": pp, "sharding_degree": sh,
                               "micro_batch_size": mbs,
                               "use_recompute": rc is not None,
                               "recompute": rc}
                        tag = (f"dp{dp}tp{tp}pp{pp}sh{sh}mb{mbs}"
                               f"rc-{rc_tag[rc]}")
                        reason = _first_prune_reason(tuner_cfg, cfg)
                        if reason is not None:
                            info["pruned"][tag] = reason
                            continue
                        specs, base, local_bytes = planned(dp, tp)
                        if not np.isfinite(base):
                            info["pruned"][tag] = "plan cost infinite"
                            continue
                        acc = (max(gbs // (dp * sh) // mbs, 1)
                               if gbs else pp)
                        bubble = (acc + pp - 1) / acc
                        compute = (base / pp) * imb * bubble \
                            * _RECOMPUTE_FLOP_MULT[rc]
                        # grad sync rides the fused dp x sharding group —
                        # the slowest participating link gates the ring;
                        # ZeRO adds the fwd/bwd param all-gathers (~1.5x)
                        ds = dp * sh
                        sync_bw = min(bw.get(data_axis, 1.0),
                                      bw.get("sharding", 1.0))
                        sync = 2.0 * (ds - 1) / max(ds, 1) * local_bytes \
                            / pp * (1.5 if sh > 1 else 1.0) \
                            / max(sync_bw, 1e-9)
                        # pp p2p: boundary activations fwd+bwd per
                        # microbatch (bf16 = 2 bytes)
                        p2p = 0.0
                        if pp > 1 and hidden:
                            act = mbs * seq * hidden * 2.0
                            p2p = 2.0 * (pp - 1) * acc * act \
                                / max(bw.get("pp", 1.0), 1e-9)
                        host = _HOST_LAUNCH_FRAC * base * acc * pp \
                            if pp > 1 else 0.0
                        cost = compute + sync + p2p + host
                        info["candidates"][tag] = round(float(cost), 1)
                        if best is None or cost < best[0]:
                            best = (cost, dict(cfg), bounds, specs)

    _PLANNER_STATS["planned"] += 1
    if best is None:
        _PLANNER_STATS["fallbacks"] += 1
        from ...core import flags as _flags
        if _flags.get_flag("planner_strict"):
            raise RuntimeError(
                "planner_strict: every hybrid config candidate was "
                f"pruned ({info['pruned']}); refusing the pure-dp "
                "fallback")
        logger.warning(
            "plan_parallel_config: no candidate survived pruning (%s); "
            "falling back to dp=%d", info["pruned"], n)
        chosen = {"dp_degree": n, "mp_degree": 1, "pp_degree": 1,
                  "sharding_degree": 1, "micro_batch_size": 1,
                  "recompute": None, "accumulate_steps": 1,
                  "stage_bounds": None,
                  "fallback": "all candidates pruned"}
        info["chosen"] = chosen
        return chosen, info

    cost, cfg, bounds, specs = best
    acc = (max((gbs or 1) // (cfg["dp_degree"] * cfg["sharding_degree"])
               // cfg["micro_batch_size"], 1) if gbs
           else cfg["pp_degree"])
    chosen = {**{k: cfg[k] for k in (
        "dp_degree", "mp_degree", "pp_degree", "sharding_degree",
        "micro_batch_size", "recompute")},
        "accumulate_steps": acc, "stage_bounds": bounds,
        "cost": round(float(cost), 1),
        "sharded_params": sum(1 for s in specs.values() if tuple(s))}
    info["chosen"] = chosen
    logger.info("plan_parallel_config: chose %s over %d candidates "
                "(%d pruned)", chosen, len(info["candidates"]),
                len(info["pruned"]))
    return chosen, info
