"""Parallel-degree planner: choose (dp, tp) and every parameter's layout
with NO user mesh axes (VERDICT r3 #5b).

The reference searches this space two ways: the static Engine's
Planner/Parallelizer scores strategies with a cost model
(auto_parallel/static/engine.py:611, static/cost/), and the auto-tuner
grid-searches degree configs with prune rules + profile trials
(auto_tuner/tuner.py:21). Here the two halves are composed from parts
that already exist in-tree:

1. **candidate space + pruning** — every (dp_degree, mp_degree)
   factorization of the device count, filtered by the auto_tuner's
   registered prune rules (degree product, head/hidden divisibility,
   batch divisibility, memory estimate — auto_tuner/prune.py);
2. **scoring** — each surviving candidate mesh is handed to the
   Completer (completion.py), which derives all parameter placements
   over the recorded op DAG and returns its comm/compute/memory plan
   cost; the planner adds the data-parallel gradient-synchronization
   term (2(dp-1)/dp x param bytes per step, the ring all-reduce the
   per-op cost model never sees because grad sync happens between
   steps), and picks the argmin.

Everything is metadata over shapes — no device buffers move during
planning; the chosen mesh + specs feed DistModel/create_sharded_train_step
exactly as user-provided ones would.
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, Optional

import numpy as np

__all__ = ["plan_parallel_layout"]

logger = logging.getLogger(__name__)


def _model_cfg_of(layer) -> Dict:
    mc = getattr(layer, "cfg", None) or getattr(layer, "config", None)
    out = {}
    for field in ("hidden_size", "num_layers", "vocab_size",
                  "intermediate_size", "num_heads", "num_kv_heads",
                  "max_position_embeddings"):
        v = getattr(mc, field, None)
        if v is not None:
            out[field] = int(v)
    return out


def plan_parallel_layout(layer, sample_feed, devices=None, loss_fn=None,
                         hbm_bytes: Optional[float] = None,
                         data_axis: str = "dp", model_axis: str = "tp",
                         profile_runner: Optional[Callable] = None):
    """Plan degrees + placements for ``layer`` over ``devices``.

    sample_feed: (x, y) arrays or ShapeDtypeStructs fixing the feed shapes
    (x.shape[0] is the global batch the dp axis must divide).

    ``profile_runner(mesh, spec_fn) -> seconds``: optional measured-trial
    hook (the auto_tuner's profile mode, tuner.py:21) — when given, the
    surviving candidates are ranked by one timed real step each instead
    of by the analytic cost alone; a candidate whose trial raises (e.g.
    OOM) is skipped, exactly like a failed tuner trial.

    Returns ``(mesh, spec_fn, info)``: a ``jax.sharding.Mesh`` with axes
    (data_axis, model_axis), a ``name -> PartitionSpec`` function for
    every parameter, and a dict describing the search (candidates,
    per-candidate costs, prune reasons, profile timings, chosen degrees).
    """
    import jax
    from jax.sharding import Mesh, PartitionSpec

    from ..auto_tuner.prune import prune_rules
    from .completion import derive_param_specs

    devices = list(devices) if devices is not None else list(jax.devices())
    n = len(devices)
    x = sample_feed[0] if isinstance(sample_feed, tuple) else sample_feed
    gbs = int(np.shape(x)[0]) if np.ndim(x) else None

    param_sizes = {name: int(np.prod(p.shape)) * 4
                   for name, p in layer.named_parameters()}
    tuner_cfg = {
        "num_devices": n,
        "global_batch_size": gbs,
        "model_cfg": _model_cfg_of(layer),
        "memory_per_chip": float(hbm_bytes) if hbm_bytes else 16e9,
    }

    info: Dict = {"num_devices": n, "candidates": {}, "pruned": {}}
    best = None          # (cost, dp, tp, specs)
    survivors = []       # (dp, tp, specs, cost) for the profile pass
    tp = 1
    while tp <= n:
        dp = n // tp
        if dp * tp == n:
            cfg = {"dp_degree": dp, "mp_degree": tp, "pp_degree": 1,
                   "sharding_degree": 1, "micro_batch_size": 1}
            tag = f"dp{dp}xtp{tp}"
            reason = None
            for rule in prune_rules():
                try:
                    hit = rule(tuner_cfg, cfg, [])
                except Exception:  # noqa: BLE001 — a rule bug never vetoes
                    continue
                if hit:
                    reason = getattr(rule, "__name__", repr(rule))
                    break
            if reason is not None:
                info["pruned"][tag] = reason
            else:
                mesh = Mesh(np.array(devices).reshape(dp, tp),
                            (data_axis, model_axis))
                specs, cost = derive_param_specs(
                    layer, mesh, sample_feed, loss_fn=loss_fn,
                    data_axis=data_axis, model_axis=model_axis,
                    return_cost=True)
                # dp gradient sync: ring all-reduce of every grad once per
                # step — 2(dp-1)/dp x the LOCAL grad bytes (the per-op
                # plan never charges it; it happens between steps).
                # tp-sharded params carry 1/tp of their bytes per rank, so
                # the synced volume must be computed from the planned
                # specs, not total param bytes — else hybrid candidates
                # are over-penalized by ~tp on this term
                local_bytes = 0.0
                for name, nbytes in param_sizes.items():
                    spec = specs.get(name)
                    sharded = spec is not None and any(
                        e == model_axis for e in tuple(spec))
                    local_bytes += nbytes / (tp if sharded else 1)
                cost = cost + 2.0 * (dp - 1) / max(dp, 1) * local_bytes
                info["candidates"][tag] = round(float(cost), 1)
                if np.isfinite(cost):
                    survivors.append((dp, tp, specs, cost))
                    if best is None or cost < best[0]:
                        best = (cost, dp, tp, specs)
        tp *= 2

    if profile_runner is not None and len(survivors) <= 1:
        # profiling requested but nothing to compare: keep the info
        # contract (the key always exists when profile mode was asked)
        info["profiled_s"] = {"skipped": f"{len(survivors)} survivor(s); "
                              "nothing to rank"}
    if profile_runner is not None and len(survivors) > 1:
        # measured trials override the analytic ranking (auto_tuner
        # profile mode): one real step per candidate, failures skipped
        info["profiled_s"] = {}
        timed_best = None
        for dp, tp, specs, cost in survivors:
            tag = f"dp{dp}xtp{tp}"
            mesh = Mesh(np.array(devices).reshape(dp, tp),
                        (data_axis, model_axis))
            try:
                t = float(profile_runner(
                    mesh, lambda name, _s=specs: _s.get(
                        name, PartitionSpec())))
            except Exception as e:  # noqa: BLE001 — a failed trial loses
                info["profiled_s"][tag] = f"trial failed: {e!r}"[:120]
                continue
            info["profiled_s"][tag] = round(t, 4)
            if timed_best is None or t < timed_best[0]:
                # keep the winner's ANALYTIC cost in slot 0 so
                # info["chosen"]["cost"] stays unit-consistent with
                # info["candidates"]; the measured time rides separately
                timed_best = (t, (cost, dp, tp, specs))
        if timed_best is not None:
            best = timed_best[1]
            info["chosen_trial_s"] = round(timed_best[0], 4)

    if best is None:
        # nothing survived (e.g. odd device count with indivisible heads):
        # fall back to pure data parallel over one axis
        logger.warning(
            "plan_parallel_layout: no candidate survived pruning "
            "(%s); falling back to dp=%d", info["pruned"], n)
        mesh = Mesh(np.array(devices).reshape(n, 1),
                    (data_axis, model_axis))
        info["chosen"] = {"dp_degree": n, "mp_degree": 1,
                          "fallback": "all candidates pruned"}
        return mesh, (lambda name: PartitionSpec()), info

    cost, dp, tp, specs = best
    info["chosen"] = {"dp_degree": dp, "mp_degree": tp,
                      "cost": round(float(cost), 1),
                      "sharded_params": sum(
                          1 for s in specs.values() if tuple(s)),
                      "total_params": len(specs)}
    logger.info("plan_parallel_layout: chose dp=%d tp=%d (cost %.3g) "
                "over %s", dp, tp, cost, info["candidates"])
    mesh = Mesh(np.array(devices).reshape(dp, tp), (data_axis, model_axis))

    def spec_fn(name: str) -> PartitionSpec:
        return specs.get(name, PartitionSpec())

    return mesh, spec_fn, info
