"""Explicit per-op SPMD (sharding-propagation) rules.

Capability parity with the reference's rule registry
(reference: paddle/phi/infermeta/spmd_rules/ — ~34 rules registered in
rules.cc, invoked from the YAML ``spmd_rule:`` field by the generated dist
branch, dist_api_gen.py:46). Each rule maps input ``DistTensorSpec``s (+ op
attrs) to the layouts the op wants for its inputs and the layouts it
produces for its outputs, in the reference's dims_mapping notation:
``dims_mapping[tensor_dim] = mesh axis index or -1``.

TPU-native role (SURVEY §7.1): GSPMD does propagation for the long tail of
ops; these explicit rules cover the cases where GSPMD is suboptimal or
where the decision is semantic (vocab-parallel cross-entropy, flash
attention, norms, MoE dispatch, TP matmul) — the dispatch funnel turns
them into ``with_sharding_constraint`` on traced values so XLA follows the
rule instead of guessing, and into ``dist_attr`` metadata on eager
tensors. Rules are pure functions over metadata: unit-testable with no
devices, mirroring test/auto_parallel/spmd_rules/test_matmul_rule.py.
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["DistTensorSpec", "SpmdRule", "register_spmd_rule",
           "get_spmd_rule", "has_spmd_rule", "SPMD_RULES"]


@dataclass(frozen=True)
class DistTensorSpec:
    """Shape + dims_mapping (+ partial mesh axes) of one dist tensor —
    the metadata half of the reference's DistTensorSpec
    (paddle/phi/core/distributed/auto_parallel/dist_meta_tensor.h)."""
    shape: Tuple[int, ...]
    dims_mapping: Tuple[int, ...]
    partial_dims: frozenset = field(default_factory=frozenset)

    def __post_init__(self):
        object.__setattr__(self, "shape", tuple(self.shape))
        object.__setattr__(self, "dims_mapping", tuple(self.dims_mapping))
        object.__setattr__(self, "partial_dims", frozenset(self.partial_dims))
        if len(self.shape) != len(self.dims_mapping):
            raise ValueError(
                f"dims_mapping rank {len(self.dims_mapping)} != tensor rank "
                f"{len(self.shape)}")

    @property
    def ndim(self):
        return len(self.shape)

    def is_replicated(self):
        return all(m == -1 for m in self.dims_mapping) and not self.partial_dims


def replicated(shape) -> DistTensorSpec:
    return DistTensorSpec(tuple(shape), (-1,) * len(tuple(shape)))


class SpmdRule:
    def __init__(self, name: str, infer_forward: Callable):
        self.name = name
        self._fwd = infer_forward

    def infer_forward(self, *specs, **attrs
                      ) -> Tuple[List[DistTensorSpec], List[DistTensorSpec]]:
        """-> (input specs the op wants, output specs it produces)."""
        return self._fwd(*specs, **attrs)


SPMD_RULES: Dict[str, SpmdRule] = {}


def register_spmd_rule(*names):
    def deco(fn):
        for n in names:
            SPMD_RULES[n] = SpmdRule(n, fn)
        return fn
    return deco


def get_spmd_rule(name: str) -> SpmdRule:
    return SPMD_RULES[name]


def has_spmd_rule(name: str) -> bool:
    return name in SPMD_RULES


# -- helpers -----------------------------------------------------------------

def _dedup(mapping: Sequence[int]) -> Tuple[int, ...]:
    """A mesh axis may shard at most one tensor dim: first use wins."""
    seen, out = set(), []
    for m in mapping:
        if m != -1 and m in seen:
            out.append(-1)
        else:
            out.append(m)
            if m != -1:
                seen.add(m)
    return tuple(out)


def _merge_dim(*ms: int) -> int:
    """Merge per-dim proposals: agreeing non-(-1) wins; conflict -> -1."""
    cand = {m for m in ms if m != -1}
    return cand.pop() if len(cand) == 1 else -1


def _broadcast_merge(specs: Sequence[DistTensorSpec]
                     ) -> Tuple[List[Tuple[int, ...]], Tuple[int, ...], Tuple[int, ...]]:
    """Right-aligned broadcast of inputs; returns (aligned input mappings,
    output shape, output mapping)."""
    nd = max(s.ndim for s in specs)
    out_shape = []
    out_map = []
    for d in range(nd):
        dims, maps = [], []
        for s in specs:
            sd = d - (nd - s.ndim)
            if sd >= 0:
                dims.append(s.shape[sd])
                # a broadcast (size-1) dim can't impose sharding
                maps.append(s.dims_mapping[sd] if s.shape[sd] != 1 else -1)
        out_shape.append(max(dims))
        out_map.append(_merge_dim(*maps))
    out_map = _dedup(out_map)
    aligned = []
    for s in specs:
        off = nd - s.ndim
        aligned.append(tuple(
            out_map[off + i] if s.shape[i] != 1 else -1
            for i in range(s.ndim)))
    return aligned, tuple(out_shape), out_map


# -- rules -------------------------------------------------------------------

@register_spmd_rule("matmul", "linear", "fused_linear")
def _matmul_rule(x: DistTensorSpec, y: DistTensorSpec, *rest,
                 transpose_x=False, transpose_y=False, **_):
    """Parity: spmd_rules/matmul.cc MatmulInferSpmd. x [..., m, k],
    y [..., k, n] -> out [..., m, n]; shared contracted-axis sharding makes
    the output Partial over that mesh axis (TP row-parallel)."""
    xm = list(x.dims_mapping)
    ym = list(y.dims_mapping)
    if transpose_x and x.ndim >= 2:
        xm[-1], xm[-2] = xm[-2], xm[-1]
    if transpose_y and y.ndim >= 2:
        ym[-1], ym[-2] = ym[-2], ym[-1]
    xshape = list(x.shape)
    yshape = list(y.shape)
    if transpose_x and x.ndim >= 2:
        xshape[-1], xshape[-2] = xshape[-2], xshape[-1]
    if transpose_y and y.ndim >= 2:
        yshape[-1], yshape[-2] = yshape[-2], yshape[-1]

    if x.ndim == 1 or y.ndim == 1:  # vec cases: fall back to replication
        out_nd = max(x.ndim + y.ndim - 2, 0)
        return ([replicated(x.shape), replicated(y.shape)] +
                [replicated(r.shape) for r in rest],
                [DistTensorSpec((1,) * out_nd if out_nd else (),
                                (-1,) * out_nd)])

    m, k, n = xshape[-2], xshape[-1], yshape[-1]
    # contracted axis: align (prefer x's non-replicated proposal)
    kmap = _merge_dim(xm[-1], ym[-2])
    if xm[-1] != -1 and ym[-2] != -1 and xm[-1] != ym[-2]:
        kmap = xm[-1]
    xm[-1] = ym[-2] = kmap
    # batch dims broadcast-merge
    bx = DistTensorSpec(xshape[:-2], xm[:-2])
    by = DistTensorSpec(yshape[:-2], ym[:-2])
    aligned, bshape, bmap = _broadcast_merge([bx, by])
    out_map = _dedup(list(bmap) + [xm[-2], ym[-1]])
    # the already-used batch axes must not re-shard m/n
    partial = frozenset({kmap} if kmap != -1 else set())
    out = DistTensorSpec(tuple(bshape) + (m, n), out_map, partial)
    in_x = DistTensorSpec(x.shape, _dedup(
        (list(aligned[0]) + [xm[-2], xm[-1]]) if not transpose_x
        else (list(aligned[0]) + [xm[-1], xm[-2]])))
    in_y = DistTensorSpec(y.shape, _dedup(
        (list(aligned[1]) + [ym[-2], ym[-1]]) if not transpose_y
        else (list(aligned[1]) + [ym[-1], ym[-2]])))
    ins = [in_x, in_y]
    for r in rest:  # bias: follows out's trailing dims
        ins.append(DistTensorSpec(
            r.shape, _dedup(out.dims_mapping[-r.ndim:]) if r.ndim else ()))
    return ins, [out]


@register_spmd_rule("add", "subtract", "multiply", "divide", "maximum",
                    "minimum", "pow", "where", "clip", "lerp", "scale",
                    "cast", "gelu", "relu", "silu", "tanh", "sigmoid",
                    "dropout", "swiglu")
def _elementwise_rule(*specs: DistTensorSpec, **_):
    """Parity: spmd_rules/elementwise.cc — right-aligned broadcast merge."""
    aligned, out_shape, out_map = _broadcast_merge(list(specs))
    ins = [DistTensorSpec(s.shape, a) for s, a in zip(specs, aligned)]
    return ins, [DistTensorSpec(out_shape, out_map)]


@register_spmd_rule("sum", "mean", "max", "min", "prod", "logsumexp")
def _reduction_rule(x: DistTensorSpec, *, axis=None, keepdim=False, **_):
    """Parity: spmd_rules/reduction.cc — reduced sharded axes become
    Partial on the output."""
    nd = x.ndim
    if axis is None:
        axes = set(range(nd))
    else:
        axes = {a % nd for a in
                (axis if isinstance(axis, (list, tuple)) else [axis])}
    out_map, out_shape = [], []
    partial = set()
    for d in range(nd):
        if d in axes:
            if x.dims_mapping[d] != -1:
                partial.add(x.dims_mapping[d])
            if keepdim:
                out_map.append(-1)
                out_shape.append(1)
        else:
            out_map.append(x.dims_mapping[d])
            out_shape.append(x.shape[d])
    return [x], [DistTensorSpec(tuple(out_shape), tuple(out_map),
                                frozenset(partial))]


@register_spmd_rule("transpose")
def _transpose_rule(x: DistTensorSpec, *, perm=None, **_):
    if perm is None:
        perm = list(range(x.ndim))[::-1]
    perm = [p % x.ndim for p in perm]
    return [x], [DistTensorSpec(tuple(x.shape[p] for p in perm),
                                tuple(x.dims_mapping[p] for p in perm),
                                x.partial_dims)]


@register_spmd_rule("reshape", "flatten", "squeeze", "unsqueeze")
def _reshape_rule(x: DistTensorSpec, *, shape=None, **_):
    """Parity: spmd_rules/reshape.cc (dim_trans-lite): a dim keeps its
    sharding iff it survives with the same size and all dims to its left
    map 1:1; anything merged/split falls back to -1."""
    if shape is None:
        # call site didn't thread the target shape: bail rather than answer
        # "replicated" — a wrong Replicate on a still-sharded tensor would
        # corrupt downstream decisions and force an all-gather under jit
        raise ValueError("reshape rule needs the target shape attr")
    out_shape = list(shape)
    # resolve a single -1
    known = 1
    for v in out_shape:
        if v != -1:
            known *= v
    total = 1
    for v in x.shape:
        total *= v
    out_shape = [total // known if v == -1 else v for v in out_shape]
    out_map = [-1] * len(out_shape)
    # factor-group matching (dim_trans proper, reshape.cc): walk both
    # shapes two-pointer, accumulating products until they agree; within a
    # group, 1:1 copies the mapping, a split puts the sharding on the
    # LEADING output factor, a merge keeps a sharded leading input factor
    # (inner-factor sharding cannot survive a merge/regroup and drops)
    i = j = 0
    while i < x.ndim and j < len(out_shape):
        gi, gj = [i], [j]
        pi, pj = x.shape[i], out_shape[j]
        while pi != pj:
            if pi < pj:
                i += 1
                if i >= x.ndim:
                    break
                gi.append(i)
                pi *= x.shape[i]
            else:
                j += 1
                if j >= len(out_shape):
                    break
                gj.append(j)
                pj *= out_shape[j]
        if pi != pj:
            break  # shapes don't factor cleanly: leave the rest replicated
        # size-1 factors carry no data: ignore them when deciding which
        # factor's sharding survives (unsqueeze/squeeze are just 1-padded
        # splits/merges)
        real_in = [k for k in gi if x.shape[k] != 1]
        real_out = [k for k in gj if out_shape[k] != 1]
        if len(real_in) <= 1 and len(real_out) >= 1:
            # 1:1 or split: the (only) data-bearing input dim's sharding
            # rides on the LEADING data-bearing output factor
            m = x.dims_mapping[real_in[0]] if real_in else -1
            out_map[real_out[0]] = m
        elif len(real_out) == 1 and real_in:     # merge many -> 1
            lead = real_in[0]
            if x.dims_mapping[lead] != -1 and all(
                    x.dims_mapping[k] == -1 for k in real_in[1:]):
                out_map[real_out[0]] = x.dims_mapping[lead]
        # many -> many regroup: stays replicated
        i += 1
        j += 1
    return [x], [DistTensorSpec(tuple(out_shape), _dedup(out_map),
                                x.partial_dims)]


@register_spmd_rule("softmax", "log_softmax")
def _softmax_rule(x: DistTensorSpec, *, axis=-1, **_):
    """Parity: spmd_rules/softmax.cc — the softmax axis must be whole."""
    a = axis % x.ndim
    m = list(x.dims_mapping)
    m[a] = -1
    spec = DistTensorSpec(x.shape, tuple(m))
    return [spec], [spec]


@register_spmd_rule("concat")
def _concat_rule(*specs: DistTensorSpec, axis=0, **_):
    nd = specs[0].ndim
    a = axis % nd
    maps = []
    for d in range(nd):
        maps.append(-1 if d == a else _merge_dim(
            *[s.dims_mapping[d] for s in specs]))
    maps = _dedup(maps)
    ins = [DistTensorSpec(s.shape, maps) for s in specs]
    out_shape = list(specs[0].shape)
    out_shape[a] = sum(s.shape[a] for s in specs)
    return ins, [DistTensorSpec(tuple(out_shape), maps)]


@register_spmd_rule("split")
def _split_rule(x: DistTensorSpec, *, axis=0, sections=None,
                num_outputs=1, **_):
    a = axis % x.ndim
    m = list(x.dims_mapping)
    m[a] = -1
    in_spec = DistTensorSpec(x.shape, tuple(m))
    if sections is None:
        sections = [x.shape[a] // num_outputs] * num_outputs
    outs = []
    for sec in sections:
        shp = list(x.shape)
        shp[a] = sec
        outs.append(DistTensorSpec(tuple(shp), tuple(m)))
    return [in_spec], outs


@register_spmd_rule("embedding")
def _embedding_rule(x: DistTensorSpec, w: DistTensorSpec, **_):
    """Parity: spmd_rules/embedding.cc — row(vocab)-sharded table makes the
    output Partial over that axis (VocabParallelEmbedding: each shard
    contributes only the rows it owns, summed over the mp group,
    mp_layers.py:47); column-sharded table shards the hidden dim."""
    vocab_axis, hidden_axis = w.dims_mapping
    out_map = tuple(x.dims_mapping) + (hidden_axis,)
    partial = frozenset({vocab_axis} if vocab_axis != -1 else set())
    out = DistTensorSpec(tuple(x.shape) + (w.shape[1],), _dedup(out_map),
                         partial)
    return [x, w], [out]


@register_spmd_rule("cross_entropy_with_softmax", "cross_entropy")
def _cross_entropy_rule(logits: DistTensorSpec, label: DistTensorSpec, **_):
    """Parity: spmd_rules/cross_entropy_with_softmax.cc — vocab(class)-dim
    sharding is legal (ParallelCrossEntropy): the loss becomes Partial over
    the vocab mesh axis (local max/sum-exp + target-gather contributions,
    reference c_softmax_with_cross_entropy_op.cu); other dims pass through."""
    vocab_axis = logits.dims_mapping[-1]
    lead = logits.dims_mapping[:-1]
    loss = DistTensorSpec(logits.shape[:-1], lead,
                          frozenset({vocab_axis} if vocab_axis != -1
                                    else set()))
    label_map = _dedup(lead[:label.ndim])
    return ([logits, DistTensorSpec(label.shape, label_map)], [loss])


@register_spmd_rule("flash_attention")
def _flash_attention_rule(q: DistTensorSpec, k: DistTensorSpec,
                          v: DistTensorSpec, *rest, causal=False, **_):
    """Parity: spmd_rules/flash_attention.cc. q [b, sq, h, d],
    k/v [b, sk, h_kv, d]: batch and head shardings ride through (TP shards
    heads); q's seq dim may stay sharded (rows are independent); k/v seq
    and head_dim must be whole — sequence-parallel attention goes through
    ring/Ulysses (distributed/long_context.py), not this local kernel."""
    b_ax = _merge_dim(q.dims_mapping[0], k.dims_mapping[0],
                      v.dims_mapping[0])
    h_ax = _merge_dim(q.dims_mapping[2], k.dims_mapping[2],
                      v.dims_mapping[2])
    qs = DistTensorSpec(q.shape,
                        _dedup((b_ax, q.dims_mapping[1], h_ax, -1)))
    ks = DistTensorSpec(k.shape, _dedup((b_ax, -1, h_ax, -1)))
    vs = DistTensorSpec(v.shape, _dedup((b_ax, -1, h_ax, -1)))
    out = DistTensorSpec(q.shape, qs.dims_mapping)
    # lse [b, h, sq] follows (b, h, sq)
    lse = DistTensorSpec((q.shape[0], q.shape[2], q.shape[1]),
                         _dedup((b_ax, h_ax, q.dims_mapping[1])))
    ins = [qs, ks, vs] + [replicated(r.shape) for r in rest]
    return ins, [out, lse]


@register_spmd_rule("layer_norm", "rms_norm", "group_norm")
def _norm_rule(x: DistTensorSpec, *ws: DistTensorSpec, **_):
    """Parity: spmd_rules/layer_norm.cc / rms_norm.cc — the normalized
    (last) dim must be whole; leading dims (batch, seq) ride through; the
    per-row stats follow the leading dims."""
    m = list(x.dims_mapping)
    m[-1] = -1
    xs = DistTensorSpec(x.shape, tuple(m))
    ins = [xs] + [replicated(w.shape) for w in ws]
    stats = DistTensorSpec(x.shape[:-1], tuple(m[:-1]))
    return ins, [xs, stats, stats]


@register_spmd_rule("fused_rope")
def _fused_rope_rule(q: DistTensorSpec, *rest, **_):
    """Parity: spmd_rules/fused_rope.cc — rotation is elementwise over
    (b, s, h): all pass through except the rotated head_dim; cos/sin
    tables replicated."""
    m = list(q.dims_mapping)
    m[-1] = -1
    qs = DistTensorSpec(q.shape, tuple(m))
    ins = [qs]
    outs = [qs]
    for r in rest:
        if r.ndim == q.ndim:  # k rides like q
            rm = list(r.dims_mapping)
            rm[-1] = -1
            rs = DistTensorSpec(r.shape, tuple(rm))
            ins.append(rs)
            outs.append(rs)
        else:  # cos/sin tables
            ins.append(replicated(r.shape))
    return ins, outs


@register_spmd_rule("moe_dispatch", "global_scatter")
def _moe_dispatch_rule(x: DistTensorSpec, *rest, expert_axis=0, **_):
    """MoE all-to-all dispatch (reference global_scatter_op.cu.cc +
    moe_layer.py:263): tokens [E, C, H] leave sharded over the expert mesh
    axis on dim 0 — each rank keeps only its experts' capacity slots."""
    m = [-1] * x.ndim
    m[0] = expert_axis
    out = DistTensorSpec(x.shape, _dedup(m))
    return [x] + [replicated(r.shape) for r in rest], [out]


@register_spmd_rule("moe_combine", "global_gather")
def _moe_combine_rule(x: DistTensorSpec, *rest, **_):
    """Inverse all-to-all: expert-sharded slots return to token order
    (replicated / data-sharded downstream)."""
    m = [-1] * x.ndim
    return [x] + [replicated(r.shape) for r in rest], \
        [DistTensorSpec(x.shape, tuple(m))]


@register_spmd_rule("default_data_parallel")
def _default_dp_rule(*specs: DistTensorSpec, mesh_axis=0, **_):
    """Parity: spmd_rules/default_data_parallel.cc — batch dim sharded over
    the data axis, everything else replicated."""
    outs = []
    for s in specs:
        m = [-1] * s.ndim
        if s.ndim:
            m[0] = mesh_axis
        outs.append(DistTensorSpec(s.shape, tuple(m)))
    return outs, outs


@register_spmd_rule("replicated")
def _replicated_rule(*specs: DistTensorSpec, **_):
    """Parity: spmd_rules/replicated.cc — the universal fallback."""
    outs = [replicated(s.shape) for s in specs]
    return outs, outs


@register_spmd_rule("adamw", "optimizer")
def _optimizer_rule(param: DistTensorSpec, *rest, **_):
    """Parity: spmd_rules/optimizer.cc — grad and every moment follow the
    parameter's layout (ZeRO keeps states aligned with their shard)."""
    ins = [param] + [DistTensorSpec(r.shape, param.dims_mapping
                                    if r.ndim == param.ndim
                                    else (-1,) * r.ndim) for r in rest]
    return ins, [ins[0]] + ins[1:]
