"""Semi-auto -> static conversion (parity: dist.to_static/DistModel,
python/paddle/distributed/auto_parallel/api.py:1396,983 + static/engine.py).

TPU-native: the reference's Completer/Partitioner/Resharder pipeline is
replaced by ONE jitted XLA program over the mesh — GSPMD performs the
per-rank partitioning and collective insertion that the reference
implements manually. DistModel compiles the full train step (fwd + bwd +
optimizer) with the parameter/opt-state shardings derived from each
parameter's placements (set via shard_tensor / shard_layer), and batch
sharding over the data axis.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ...core.tensor import Tensor
from ..process_mesh import ProcessMesh, get_mesh

__all__ = ["DistModel", "to_static"]


class DistModel:
    """Callable train/eval wrapper around one compiled sharded step
    (parity: DistModel api.py:983 — modes via train()/eval()/predict())."""

    def __init__(self, layer, loader=None, loss=None, optimizer=None,
                 strategy=None, metrics=None, mesh: ProcessMesh = None,
                 param_spec_fn: Optional[Callable] = None,
                 data_axis: str = "dp"):
        del metrics
        self._strategy = strategy
        self._layer = layer
        self._loader = loader
        self._loss = loss
        self._optimizer = optimizer
        self._mode = "train" if optimizer is not None else "eval"
        self._mesh = (mesh or get_mesh())
        self._planned_info = None
        if self._mesh is None:
            # NO mesh anywhere: the degree planner derives (dp, tp) and
            # every placement from the first batch's shapes (VERDICT r3
            # #5b — the reference Engine's Planner + auto_tuner search,
            # static/engine.py:611, auto_tuner/tuner.py:21); deferred to
            # the first train_batch/__call__ because planning needs the
            # feed shapes
            self._jmesh = None
            self._data_axis = data_axis
            self._model_axis = "tp"
        else:
            jmesh = self._mesh.to_jax() \
                if isinstance(self._mesh, ProcessMesh) else self._mesh
            self._jmesh = jmesh
            if data_axis not in jmesh.axis_names:
                data_axis = jmesh.axis_names[0]
            self._data_axis = data_axis
            others = [a for a in jmesh.axis_names if a != data_axis]
            self._model_axis = ("tp" if "tp" in others
                                else (others[0] if others else data_axis))
        self._explicit_spec_fn = param_spec_fn is not None
        self._spec_fn = param_spec_fn or self._spec_from_placements
        self._train_step = None
        self._eval_fn = None
        self._params = None
        self._opt_state = None
        self._shard_batch = None
        self._eval_placed = None

    # placements already attached to params (shard_tensor/shard_layer)
    # become the compiled layout; everything else replicates
    def _spec_from_placements(self, name: str) -> PartitionSpec:
        if not hasattr(self, "_param_index"):
            self._param_index = dict(self._layer.named_parameters())
        p = self._param_index.get(name)
        if p is not None:
            sharding = getattr(p._data, "sharding", None)
            if isinstance(sharding, NamedSharding):
                return sharding.spec
        return PartitionSpec()

    def train(self):
        if self._optimizer is None:
            raise ValueError("to_static without optimizer: train() invalid")
        self._mode = "train"
        if not self._layer.training:
            self._layer.train()
        return self

    def eval(self):
        self._mode = "eval"
        if self._layer.training:
            self._layer.eval()
            self._eval_fn = None  # mode is baked at trace time: retrace
        return self

    def _plan_mesh(self, x, y):
        """No mesh anywhere: plan (dp, tp) degrees + placements over all
        visible devices from the feed shapes (planner.py)."""
        if x is None:
            raise ValueError(
                "DistModel has no mesh and no sample batch to plan one "
                "from: pass mesh=, dist.set_mesh(...), or run a batch")
        from .planner import plan_parallel_layout
        xs, ys = self._feed_structs(x, y)
        tuning = getattr(self._strategy, "tuning", None)
        profile_runner = None
        if (getattr(tuning, "enable", False)
                and getattr(tuning, "profile", False)
                and self._optimizer is not None and y is not None):
            profile_runner = self._make_profile_runner(x, y)
        mesh, spec_fn, info = plan_parallel_layout(
            self._layer, (xs, ys),
            loss_fn=self._loss if ys is not None else None,
            data_axis=self._data_axis, model_axis=self._model_axis,
            profile_runner=profile_runner)
        self._jmesh = mesh
        self._planned_info = info
        if not self._explicit_spec_fn:
            self._spec_fn = spec_fn

    def _make_profile_runner(self, x, y):
        """One timed real train step per candidate mesh (the auto_tuner's
        profile trial, tuner.py:21, run in-process on this mesh's devices
        instead of via subprocess launches)."""
        import time

        import jax

        x0 = np.asarray(x._data if isinstance(x, Tensor) else x)
        y0 = np.asarray(y._data if isinstance(y, Tensor) else y)

        def runner(mesh, spec_fn):
            from ...models.trainer import create_sharded_train_step
            loss_fn = None
            if self._loss is not None:
                def loss_fn(model, xx, yy, _lf=self._loss):
                    return _lf(model(xx), yy)
            step, params, opt_state, shard_batch = \
                create_sharded_train_step(
                    self._layer, self._optimizer, mesh, spec_fn,
                    data_axis=self._data_axis, loss_fn=loss_fn)
            xs, ys = shard_batch(x0), shard_batch(y0)
            key = jax.random.key(0)
            loss, params, opt_state = step(params, opt_state, key, xs, ys,
                                           1e-3)      # compile + run
            jax.device_get(loss)
            t0 = time.perf_counter()
            loss, params, opt_state = step(params, opt_state, key, xs, ys,
                                           1e-3)
            jax.device_get(loss)                      # closes the window
            return time.perf_counter() - t0

        return runner

    @staticmethod
    def _feed_structs(x, y):
        import jax
        xs = jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype) \
            if not hasattr(x, "dtype") else jax.ShapeDtypeStruct(
                x.shape, x.dtype)
        ys = None
        if y is not None:
            ys = jax.ShapeDtypeStruct(np.shape(y), np.asarray(y).dtype) \
                if not hasattr(y, "dtype") else jax.ShapeDtypeStruct(
                    y.shape, y.dtype)
        return xs, ys

    def _auto_complete(self, x, y):
        """No user placements anywhere: run the Completer over the recorded
        DAG to derive every parameter's layout automatically (the
        reference's Completer+Planner step of to_static, engine.py:611,
        completion.py:219)."""
        if self._explicit_spec_fn:
            return  # explicit param_spec_fn wins
        self._param_index = dict(self._layer.named_parameters())
        if any(isinstance(getattr(p._data, "sharding", None), NamedSharding)
               and not getattr(p._data.sharding, "is_fully_replicated", True)
               for p in self._param_index.values()):
            return  # user annotated at least one param: respect placements
        from .completion import derive_param_specs
        # planning is metadata-only: hand over shapes/dtypes, never data
        xs, ys = self._feed_structs(x, y)
        specs = derive_param_specs(
            self._layer, self._jmesh, (xs, ys),
            loss_fn=self._loss if ys is not None else None,
            data_axis=self._data_axis, model_axis=self._model_axis)
        if specs:
            self._spec_fn = lambda name: specs.get(name, PartitionSpec())

    def _ensure_train(self, x=None, y=None):
        if self._train_step is None:
            if self._jmesh is None:
                self._plan_mesh(x, y)      # degrees + placements, no mesh
            elif x is not None:
                self._auto_complete(x, y)  # placements on the given mesh
            from ...models.trainer import create_sharded_train_step
            loss_fn = None
            if self._loss is not None:
                def loss_fn(model, x, y, _lf=self._loss):
                    return _lf(model(x), y)
            (self._train_step, self._params, self._opt_state,
             self._shard_batch) = create_sharded_train_step(
                self._layer, self._optimizer, self._jmesh, self._spec_fn,
                data_axis=self._data_axis, loss_fn=loss_fn)

    def _ensure_eval(self):
        if self._eval_fn is None:
            from ...core import random as _random
            from ...core.autograd import tape_paused
            from ...nn.layer.layers import _swapped_state
            layer = self._layer

            def fn(state, key, x, y):
                # key is a traced argument: any dropout left in train mode
                # draws fresh per call instead of a constant-folded mask
                with _random.key_context(key):
                    with _swapped_state(layer, state):
                        with tape_paused():
                            out = layer(Tensor(x))
                            if self._loss is not None and y is not None:
                                out = self._loss(out, Tensor(y))
                return out._data
            self._eval_fn = jax.jit(fn)

    def _current_state(self):
        """Layer snapshot overlaid with the trained compiled-step params —
        eval always sees the latest weights."""
        from ...nn.layer.layers import functional_state
        state = functional_state(self._layer)
        if self._params is not None:
            state.update(self._params)
        elif self._eval_placed is not None:
            state.update(self._eval_placed)
        return state

    def __call__(self, *args):
        if self._mode == "train":
            x, y = args
            return self.train_batch(x, y)
        x = args[0]._data if isinstance(args[0], Tensor) else args[0]
        y = args[1] if len(args) > 1 else None
        y = y._data if isinstance(y, Tensor) else y
        if self._params is None and self._eval_placed is None:
            # eval-only DistModel still gets the auto-derived layout; the
            # cache is invalidated (set back to None) when new weights are
            # loaded from a checkpoint
            if self._jmesh is None:
                self._plan_mesh(x, y)
            else:
                self._auto_complete(x, y)
            from ...models.trainer import place_by_spec
            self._eval_placed = {
                name: place_by_spec(p._data, self._spec_fn(name),
                                    self._jmesh)
                for name, p in self._layer.named_parameters()}
        self._ensure_eval()
        from ...core import random as _random
        with self._jmesh:
            return Tensor(
                self._eval_fn(self._current_state(),
                              _random.default_generator.next_key(), x, y),
                stop_gradient=True)

    def train_batch(self, x, y, lr: Optional[float] = None):
        x0 = x._data if isinstance(x, Tensor) else x
        y0 = y._data if isinstance(y, Tensor) else y
        self._ensure_train(x0, y0)
        if lr is None:
            lr = float(self._optimizer.get_lr()) \
                if hasattr(self._optimizer, "get_lr") else 1e-3
        x = x._data if isinstance(x, Tensor) else np.asarray(x)
        y = y._data if isinstance(y, Tensor) else np.asarray(y)
        # draw from the global generator so get/set_rng_state replays the
        # exact dropout key sequence (the (seed, offset) contract)
        from ...core import random as _random
        key = _random.default_generator.next_key()
        loss, self._params, self._opt_state = self._train_step(
            self._params, self._opt_state, key,
            self._shard_batch(x), self._shard_batch(y), lr)
        return Tensor(loss, stop_gradient=True)

    def state_dict(self, mode: str = "all"):
        """Full state (buffers + frozen params included), with trained
        values overlaid — parity with DistModel.state_dict."""
        del mode
        return {k: Tensor(v) for k, v in self._current_state().items()}

    def dist_main_program(self, mode=None):
        """The compiled artifact description — the PIR-program analog is
        the GSPMD-partitioned XLA program owned by jax's jit cache."""
        del mode
        return "<compiled XLA program (GSPMD-partitioned)>"

    def write_back(self):
        """Copy compiled-step params back into the eager layer
        (parity: DistModel parameter sync)."""
        if self._params is not None:
            from ...models.trainer import write_back as _wb
            _wb(self._layer, self._params)


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None,
              mesh=None, param_spec_fn=None, data_axis: str = "dp"
              ) -> DistModel:
    """Parity: dist.to_static(layer, loader, loss, optimizer) -> DistModel."""
    return DistModel(layer, loader, loss, optimizer, strategy, mesh=mesh,
                     param_spec_fn=param_spec_fn, data_axis=data_axis)
