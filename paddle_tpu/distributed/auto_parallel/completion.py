"""Automatic sharding completion over the recorded static DAG.

The reference derives a full distributed program from partial (or absent)
user annotations: ``Completer.complete_forward_annotation`` propagates
dist attrs op-by-op (auto_parallel/static/completion.py:219), the
``Parallelizer``/``Planner`` choose strategies with a cost model
(static/engine.py:611, static/cost/), and the ``Resharder`` inserts the
comm ops (reshard.py). On the TPU substrate XLA/GSPMD plays Partitioner +
Resharder; what was genuinely missing (VERDICT r2 #5) is the *planning*
step: deciding, with no user placements, how every parameter should be
laid out over the mesh.

This module is that planner. It walks the recorded ``static.Program`` op
DAG (the ops carry registered SPMD rules — the same single source of
truth the dispatch path uses) and greedily assigns each >=2-D parameter
one of {replicated, Shard(d, model_axis)} by scoring every candidate
with a comm/compute/memory cost model:

- reshard cost: bytes moved when an input's current placement differs
  from what the op's SPMD rule wants (all-gather ~ (n-1)/n * bytes,
  partial clearing ~ ring all-reduce 2(n-1)/n * bytes);
- one-step lookahead: each candidate's output specs are pushed through
  the IMMEDIATE consumer ops' rules so a placement that looks free now
  but forces an all-gather one op later is charged today (the myopia
  that pure greedy propagation suffers);
- compute: matmul-class FLOPs divided by the mesh axes the candidate
  actually parallelizes;
- memory: replicated parameter bytes are charged per step (HBM is the
  scarce resource the reference's planner also optimizes).

The classic Megatron column->row alternation (qkv/gate/up column, o/down
row — mp_layers.py:47,333,540) falls out of the cost model rather than
being pattern-matched, so unconventional graphs still get a consistent
plan. Everything here is pure metadata over DistTensorSpec: no devices
are touched, mirroring the reference's device-free SPMD-rule tests.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

import numpy as np

from .spmd_rules import DistTensorSpec, SPMD_RULES, replicated

__all__ = ["Completer", "derive_param_specs", "plan_rule_stats",
           "reset_plan_rule_stats"]

logger = logging.getLogger(__name__)

# Observability for the planner's rule path (VERDICT r3 #5a: the same
# counted-never-silent discipline dispatch got in r3, core/dispatch.py:264;
# FLAGS_spmd_strict turns a counted fallback into a raise for tests).
_PLAN_STATS = {"rules_applied": 0, "rule_fallbacks": 0, "no_rule": 0}


def plan_rule_stats() -> dict:
    return dict(_PLAN_STATS)


def reset_plan_rule_stats() -> None:
    for k in _PLAN_STATS:
        _PLAN_STATS[k] = 0

# relative weights of the cost terms (comm bytes are the unit)
_W_COMM = 1.0      # per byte moved over ICI
_W_FLOP = 0.02     # per matmul FLOP (MXU flops are ~50x cheaper than bytes)
_W_MEM = 2.0       # per byte of replicated parameter per step


def _bytes(shape, itemsize: int = 4) -> float:
    return float(np.prod([d or 1 for d in shape])) * itemsize


class Completer:
    """Derive a dims_mapping for every parameter of a recorded program.

    Parameters
    ----------
    axis_sizes: ordered {axis_name: size} of the target mesh.
    data_axis / model_axis: which axes carry batch / model parallelism.
    """

    def __init__(self, axis_sizes: Dict[str, int], data_axis: str = "dp",
                 model_axis: str = "tp",
                 axis_bandwidth: Optional[Dict[str, float]] = None):
        self.axis_sizes = dict(axis_sizes)
        self.axis_names = list(axis_sizes)
        self.data_axis = data_axis
        self.model_axis = model_axis
        # relative bandwidth per mesh axis (VERDICT r4 #4): 1.0 = the
        # ICI-class reference; an axis laid over DCN gets e.g. 0.04, so
        # collectives riding it cost 25x the bytes. The reference encodes
        # the same hierarchy in its Cluster beta/alpha tables
        # (auto_parallel/static/cluster.py + cost/comm_op_cost.py).
        self.axis_bandwidth = dict(axis_bandwidth or {})
        self._tp_idx = (self.axis_names.index(model_axis)
                        if model_axis in self.axis_names else -1)
        self._dp_idx = (self.axis_names.index(data_axis)
                        if data_axis in self.axis_names else -1)

    # -- cost primitives ----------------------------------------------------
    def _axis_size(self, idx: int) -> int:
        if idx < 0 or idx >= len(self.axis_names):
            return 1
        return self.axis_sizes[self.axis_names[idx]]

    def _axis_cost_scale(self, idx: int) -> float:
        """1/bandwidth for the axis: comm bytes over a slow link cost
        proportionally more."""
        if idx < 0 or idx >= len(self.axis_names):
            return 1.0
        return 1.0 / max(self.axis_bandwidth.get(self.axis_names[idx],
                                                 1.0), 1e-9)

    def _local_bytes(self, spec: DistTensorSpec) -> float:
        denom = 1
        for ax in spec.dims_mapping:
            if ax != -1:
                denom *= self._axis_size(ax)
        return _bytes(spec.shape) / denom

    def _move_cost(self, cur: DistTensorSpec, want: DistTensorSpec) -> float:
        """Bytes moved to turn ``cur`` into ``want`` (coarse reshard model:
        r_to_s slicing is free; s_to_r all-gather (n-1)/n; axis moves
        ~all-to-all counted as a gather; partial clear = ring all-reduce)."""
        cost = 0.0
        for ax in cur.partial_dims - want.partial_dims:
            n = self._axis_size(ax)
            cost += 2.0 * (n - 1) / n * _bytes(cur.shape) \
                * self._axis_cost_scale(ax)
        for d, (c, w) in enumerate(zip(cur.dims_mapping, want.dims_mapping)):
            if c == w:
                continue
            if c == -1 and w != -1:
                continue  # slice locally: free
            n = self._axis_size(c)
            cost += (n - 1) / n * _bytes(cur.shape) \
                * self._axis_cost_scale(c)
        return cost

    def _clear_partial(self, spec: DistTensorSpec) -> Tuple[DistTensorSpec,
                                                            float]:
        if not spec.partial_dims:
            return spec, 0.0
        cost = 0.0
        for ax in spec.partial_dims:
            n = self._axis_size(ax)
            cost += 2.0 * (n - 1) / n * _bytes(spec.shape) \
                * self._axis_cost_scale(ax)
        return DistTensorSpec(spec.shape, spec.dims_mapping), cost

    def _flops_cost(self, op_name: str, out_specs, in_specs) -> float:
        if op_name not in ("matmul", "linear", "fused_linear",
                           "flash_attention"):
            return 0.0
        out = out_specs[0]
        x = in_specs[0]
        if not out.shape or not x.shape:
            return 0.0
        # 2 * prod(out) * contracted extent
        k = x.shape[-1] if x.ndim else 1
        flops = 2.0 * float(np.prod([d or 1 for d in out.shape])) * float(k)
        par = 1
        used = {ax for ax in out.dims_mapping if ax != -1} | out.partial_dims
        for ax in used:
            par *= self._axis_size(ax)
        return flops / par

    # -- rule plumbing ------------------------------------------------------
    @staticmethod
    def _rule_for(op_name: str):
        from ...core.op_registry import get_op_def
        rule_name = getattr(get_op_def(op_name), "spmd_rule", None)
        return SPMD_RULES.get(rule_name) if rule_name else None

    @staticmethod
    def _op_attrs(node) -> dict:
        attrs = dict(getattr(node, "attrs", None) or {})
        if node.name in ("reshape", "flatten", "squeeze", "unsqueeze") \
                and "shape" not in attrs and node.outputs:
            attrs["shape"] = [d or 1 for d in node.outputs[0].shape]
        return attrs

    def _apply_rule(self, node, in_specs):
        """Run the op's SPMD rule; on failure fall back to replicated outs —
        COUNTED (plan_rule_stats), and a raise under FLAGS_spmd_strict so
        tests can pin rules down (the silent-degrade class VERDICT r2
        flagged in dispatch and r3 flagged here). Returns
        (wanted_in_specs, out_specs)."""
        rule = self._rule_for(node.name)
        shapes = [tuple(d or 1 for d in v.shape) for v in node.outputs]
        if rule is None:
            _PLAN_STATS["no_rule"] += 1
            return in_specs, [replicated(s) for s in shapes]
        try:
            ins, outs = rule.infer_forward(*in_specs, **self._op_attrs(node))
        except (ValueError, AssertionError, IndexError, KeyError,
                NotImplementedError, TypeError) as e:
            # rule rejects the call shape: treat as opaque — but never
            # silently (anything outside these types is a rule bug and
            # propagates)
            _PLAN_STATS["rule_fallbacks"] += 1
            from ...core import flags as _flags
            if _flags.get_flag("spmd_strict"):
                raise RuntimeError(
                    f"spmd_strict: planner rule for op '{node.name}' fell "
                    f"back ({type(e).__name__}: {e})") from e
            return in_specs, [replicated(s) for s in shapes]
        _PLAN_STATS["rules_applied"] += 1
        outs = list(outs)
        while len(outs) < len(shapes):
            outs.append(replicated(shapes[len(outs)]))
        return list(ins), outs

    # -- the completion pass ------------------------------------------------
    def complete(self, program, input_mappings: Dict[str, Tuple[int, ...]],
                 param_names: Dict[int, str]) -> Dict[str, Tuple[int, ...]]:
        """Walk the DAG; return {param_name: dims_mapping}.

        input_mappings: {feed Variable name: dims_mapping} seeds (usually
        batch dim -> data axis). param_names: {id(param Tensor): name}.
        """
        from ...core.tensor import Tensor
        from ...static import Variable

        var_specs: Dict[int, DistTensorSpec] = {}
        for v in program.inputs.values():
            shape = tuple(d or 1 for d in v.shape)
            m = input_mappings.get(v.name, (-1,) * len(shape))
            var_specs[id(v)] = DistTensorSpec(shape, m)

        assigned: Dict[int, Tuple[int, ...]] = {}   # id(param) -> mapping
        result: Dict[str, Tuple[int, ...]] = {}
        consumers = self._build_consumers(program)

        def spec_of(o, cand: Optional[Dict[int, Tuple[int, ...]]] = None):
            if isinstance(o, Variable):
                s = var_specs.get(id(o))
                return s if s is not None else replicated(
                    tuple(d or 1 for d in o.shape))
            if isinstance(o, Tensor):
                shape = tuple(o._data.shape)
                if cand and id(o) in cand:
                    return DistTensorSpec(shape, cand[id(o)])
                if id(o) in assigned:
                    return DistTensorSpec(shape, assigned[id(o)])
                return replicated(shape)
            arr = np.asarray(o) if not hasattr(o, "shape") else o
            return replicated(tuple(getattr(arr, "shape", ())))

        def candidates(param) -> List[Tuple[int, ...]]:
            shape = tuple(param._data.shape)
            nd = len(shape)
            cands = [(-1,) * nd]
            if self._tp_idx >= 0 and nd >= 2:
                tp = self.axis_sizes.get(self.model_axis, 1)
                # last dim first: on a cost tie (e.g. an isolated linear,
                # where partial-out vs sharded-out both look free locally)
                # column-parallel is the Megatron default
                for d in reversed(range(nd)):
                    if shape[d] % tp == 0 and shape[d] >= tp:
                        m = [-1] * nd
                        m[d] = self._tp_idx
                        cands.append(tuple(m))
            return cands

        def eval_op(node, cand):
            """Cost of running node with candidate param mappings: input
            reshard + flops + replicated-param memory; returns
            (cost, out_specs)."""
            cost = 0.0
            in_specs = []
            for o in node.operands:
                s = spec_of(o, cand)
                s, c = self._clear_partial(s)
                cost += c
                in_specs.append(s)
            want, outs = self._apply_rule(node, in_specs)
            for o, s, w in zip(node.operands, in_specs, want):
                if tuple(s.dims_mapping) != tuple(w.dims_mapping):
                    cost += self._move_cost(s, w)
            cost += _W_FLOP / _W_COMM * self._flops_cost(
                node.name, outs, want)
            for o in node.operands:
                if isinstance(o, Tensor) and id(o) in (cand or {}):
                    if all(m == -1 for m in cand[id(o)]):
                        cost += _W_MEM / _W_COMM * _bytes(o._data.shape)
            return cost, outs

        def lookahead(node, outs):
            """Charge next-op reshard/clear costs for these output specs."""
            cost = 0.0
            for v, s in zip(node.outputs, outs):
                for nxt in consumers.get(id(v), []):
                    nxt_in = []
                    for o in nxt.operands:
                        if isinstance(o, Variable) and id(o) == id(v):
                            cs, cc = self._clear_partial(s)
                            cost += cc
                            nxt_in.append(cs)
                        else:
                            nxt_in.append(self._clear_partial(
                                spec_of(o))[0])
                    want, _ = self._apply_rule(nxt, nxt_in)
                    for o, si, w in zip(nxt.operands, nxt_in, want):
                        if isinstance(o, Variable) and id(o) == id(v) \
                                and tuple(si.dims_mapping) != \
                                tuple(w.dims_mapping):
                            cost += self._move_cost(si, w)
            return cost

        # total plan cost at the final assignment (reshard + flops + memory
        # over the whole program): the degree planner (planner.py) compares
        # candidate (dp, tp) meshes by this number
        self.total_cost = 0.0
        for node in program.nodes:
            free = [o for o in node.operands
                    if isinstance(o, Tensor) and id(o) in param_names
                    and id(o) not in assigned and o._data.ndim >= 2]
            if free:
                # enumerate jointly only over the first free weight; other
                # free params of the same op follow the rule's wanted spec
                w0 = free[0]
                best, best_cost = None, float("inf")
                for m in candidates(w0):
                    cost, outs = eval_op(node, {id(w0): m})
                    cost += lookahead(node, outs)
                    if cost < best_cost - 1e-9:
                        best, best_cost = m, cost
                assigned[id(w0)] = best
                result[param_names[id(w0)]] = best
            # 1-D / remaining free params adopt what the rule asks of them
            cost0 = 0.0
            in_specs = []
            for o in node.operands:
                s, c = self._clear_partial(spec_of(o))
                in_specs.append(s)
                cost0 += c
            want, outs = self._apply_rule(node, in_specs)
            for o, w in zip(node.operands, want):
                if isinstance(o, Tensor) and id(o) in param_names \
                        and id(o) not in assigned:
                    assigned[id(o)] = tuple(w.dims_mapping)
                    result[param_names[id(o)]] = tuple(w.dims_mapping)
            for o, s, w in zip(node.operands, in_specs, want):
                if tuple(s.dims_mapping) != tuple(w.dims_mapping):
                    cost0 += self._move_cost(s, w)
            cost0 += _W_FLOP / _W_COMM * self._flops_cost(
                node.name, outs, want)
            for o in node.operands:
                if isinstance(o, Tensor) and id(o) in param_names \
                        and all(m == -1 for m in assigned.get(id(o), (0,))):
                    cost0 += _W_MEM / _W_COMM * _bytes(o._data.shape)
            self.total_cost += cost0
            for v, s in zip(node.outputs, outs):
                var_specs[id(v)] = s

        return result

    @staticmethod
    def _build_consumers(program):
        consumers: Dict[int, list] = {}
        for node in program.nodes:
            for o in node.operands:
                consumers.setdefault(id(o), []).append(node)
        return consumers

    # reference-parity alias (completion.py:219)
    complete_forward_annotation = complete


def derive_param_specs(layer, mesh, sample_feed, loss_fn=None,
                       data_axis: str = "dp", model_axis: str = "tp",
                       return_cost: bool = False, axis_bandwidth=None):
    """Record ``layer``'s forward (+ loss) as a static Program and complete
    it: returns {param_name: PartitionSpec} with NO user placements needed
    (the reference's Completer+Planner step of dist.to_static,
    engine.py:611).

    sample_feed: (x, y) numpy/jax arrays or ShapeDtypeStructs fixing the
    feed shapes; loss_fn(out_var, label_var) defaults to the layer's
    ``loss`` method when present.
    """
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec

    from ... import static
    from ...static import Variable  # noqa: F401 — recording substrate

    jmesh = mesh.to_jax() if hasattr(mesh, "to_jax") else mesh
    axis_sizes = dict(zip(jmesh.axis_names, jmesh.devices.shape))

    x, y = sample_feed if isinstance(sample_feed, tuple) else (sample_feed,
                                                               None)

    was_static = static.in_static_mode()
    static.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog):
            xv = static.data("x", list(x.shape), jnp.dtype(x.dtype).name)
            args = [xv]
            if y is not None:
                yv = static.data("y", list(y.shape), jnp.dtype(y.dtype).name)
                args.append(yv)
            if loss_fn is not None:
                out = layer(xv)
                loss_fn(out, args[1] if y is not None else None)
            elif hasattr(layer, "loss") and y is not None:
                layer.loss(*args)
            else:
                layer(*args)
    except Exception as e:
        logger.warning(
            "auto-shard: static recording failed (%s); parameters stay "
            "replicated — annotate with shard_tensor/shard_layer or pass "
            "param_spec_fn", e)
        return ({}, float("inf")) if return_cost else {}
    finally:
        if not was_static:
            static.disable_static()

    param_names = {id(p): n for n, p in layer.named_parameters()}
    completer = Completer(axis_sizes, data_axis=data_axis,
                          model_axis=model_axis,
                          axis_bandwidth=axis_bandwidth)
    seeds = {}
    for name, v in prog.inputs.items():
        m = [-1] * len(v.shape)
        if len(v.shape) >= 1 and completer._dp_idx >= 0:
            m[0] = completer._dp_idx
        seeds[name] = tuple(m)
    mappings = completer.complete(prog, seeds, param_names)

    specs = {}
    for name, mapping in mappings.items():
        entries = [None if ax == -1 else completer.axis_names[ax]
                   for ax in mapping]
        while entries and entries[-1] is None:  # P(None,) == P()
            entries.pop()
        specs[name] = PartitionSpec(*entries)
    if return_cost:
        return specs, completer.total_cost
    return specs
