"""Parallel environment bootstrap.

Capability parity with the reference's env layer (reference:
python/paddle/distributed/parallel.py init_parallel_env:395-443 + TCPStore
rendezvous). TPU-native: jax.distributed owns multi-host rendezvous
(coordinator address from the launch env contract); within a host,
single-controller SPMD over jax.devices(). rank/world_size are
PROCESS-level (per host), matching how data loading shards; device-level
parallelism lives in mesh axes/groups.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

__all__ = ["init_parallel_env", "get_rank", "get_world_size", "ParallelEnv",
           "is_initialized", "DataParallel"]

_INITIALIZED = [False]


def _maybe_init_jax_distributed():
    """Multi-host init from the launch env contract (PADDLE_TRAINER_* /
    MASTER_ADDR, parity with the reference's env contract at
    launch/controllers/collective.py)."""
    import jax
    n_procs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if n_procs <= 1:
        return
    # must not touch any backend-initializing API before initialize();
    # check the distributed client state directly
    try:
        from jax._src import distributed as _jd
        already = _jd.global_state.client is not None
    except Exception:
        already = False
    if already:
        return
    addr = os.environ.get("MASTER_ADDR")
    port = os.environ.get("MASTER_PORT")
    pid = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if addr and port:
        jax.distributed.initialize(f"{addr}:{port}", num_processes=n_procs,
                                   process_id=pid)


def init_parallel_env():
    """Initialize the parallel env and the world group (parity:
    paddle.distributed.init_parallel_env)."""
    import jax

    from .communication_impl import Group, _set_world_group
    from .process_mesh import ProcessMesh

    _maybe_init_jax_distributed()
    if not _INITIALIZED[0]:
        n = jax.device_count()
        world_mesh = ProcessMesh(np.arange(n), ["world"])
        _set_world_group(Group("world", list(range(n)), mesh=world_mesh))
        _INITIALIZED[0] = True
    return ParallelEnv()


def is_initialized() -> bool:
    return _INITIALIZED[0]


def get_rank(group=None) -> int:
    import jax
    if group is not None:
        return group.rank
    return jax.process_index()


def get_world_size(group=None) -> int:
    import jax
    if group is not None:
        return group.nranks
    return jax.process_count()


class ParallelEnv:
    """Parity: paddle.distributed.ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:0")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else ["127.0.0.1:0"]

    @property
    def nranks(self):
        return get_world_size()

    @property
    def local_rank(self):
        return get_rank()


class DataParallel:
    """paddle.DataParallel parity wrapper.

    The reference implements DP with a C++ EagerReducer doing bucketed
    grad all-reduce on a comm stream (reducer.cc). TPU-native: under SPMD
    compilation the data axis IS the reduction — jax.grad of a batch-sharded
    loss produces grads that XLA all-reduces automatically (or the fleet
    train loop calls fused_allreduce_gradients). This wrapper keeps the API
    (forward delegation, no_sync, state_dict passthrough) and marks the
    layer for gradient synchronization in the eager path.
    """

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        self._layers = layers
        self.group = group
        self.find_unused_parameters = find_unused_parameters
        self._grad_sync_enabled = True
        init_parallel_env()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    __call__ = forward

    def no_sync(self):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            prev = self._grad_sync_enabled
            self._grad_sync_enabled = False
            try:
                yield
            finally:
                self._grad_sync_enabled = prev
        return ctx()

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)

    def parameters(self, *args, **kwargs):
        return self._layers.parameters(*args, **kwargs)

    def named_parameters(self, *args, **kwargs):
        return self._layers.named_parameters(*args, **kwargs)

    def train(self):
        self._layers.train()
        return self

    def eval(self):
        self._layers.eval()
        return self

    def sync_gradients(self):
        """Grad reduce over the wrapper's comm group(s) (reference:
        fused_allreduce_gradients over dp / sharding / sep per wrapper,
        hybrid_parallel_util.py:246-259). Under SPMD most grads are already
        whole global arrays; this normalizes any Partial-represented ones."""
        from .fleet.utils.hybrid_parallel_util import \
            fused_allreduce_gradients
        fused_allreduce_gradients(list(self._layers.parameters()),
                                  getattr(self, "_hcg", None))

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)
