"""paddle.onnx (parity: python/paddle/onnx/__init__.py — export).

The reference delegates to the external paddle2onnx converter. This build
has no ONNX runtime in-image; export() lowers the traced model through the
jit.save StableHLO path (the portable interchange format of the XLA
stack) and writes <path>.onnx.* artifacts. A true ONNX protobuf writer
would require the onnx package (not in-image).
"""
from __future__ import annotations

import os

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Export a Layer for interchange (parity: paddle.onnx.export's
    signature; artifact format is StableHLO, see module docstring)."""
    from ..jit import save as jit_save
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    jit_save(layer, path + ".onnx", input_spec=input_spec)
    return path + ".onnx"
