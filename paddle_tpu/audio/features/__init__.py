"""Path-faithful module (parity: python/paddle/audio/features/)."""
from .. import features as _ns

Spectrogram = _ns.Spectrogram
MelSpectrogram = _ns.MelSpectrogram
LogMelSpectrogram = _ns.LogMelSpectrogram
MFCC = _ns.MFCC

__all__ = ["LogMelSpectrogram", "MelSpectrogram", "MFCC", "Spectrogram"]
