"""Audio domain library (parity: python/paddle/audio/ — functional window/
mel/dct utilities and the Spectrogram/MelSpectrogram/LogMelSpectrogram/
MFCC feature layers).

TPU-native: framing + windowing + rFFT compose into one XLA program (the
MXU eats the mel-filterbank matmul); everything is differentiable and
batchable, unlike the reference's CPU feature path."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import run_op
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["functional", "features", "backends", "datasets", "load",
           "save", "info"]


class functional:
    """paddle.audio.functional."""

    @staticmethod
    def fft_frequencies(sr, n_fft, dtype="float32"):
        """(parity: audio.functional.fft_frequencies)"""
        import numpy as _np
        return Tensor(jnp.asarray(_np.linspace(
            0, sr / 2, 1 + n_fft // 2).astype(dtype)))

    @staticmethod
    def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                        dtype="float32"):
        """(parity: audio.functional.mel_frequencies)"""
        lo = functional.hz_to_mel(f_min, htk)
        hi = functional.hz_to_mel(f_max, htk)
        import numpy as _np
        lo = float(lo) if not hasattr(lo, "numpy") else float(lo.numpy())
        hi = float(hi) if not hasattr(hi, "numpy") else float(hi.numpy())
        mels = _np.linspace(lo, hi, n_mels)
        out = [functional.mel_to_hz(float(m), htk) for m in mels]
        out = [float(o.numpy()) if hasattr(o, "numpy") else float(o)
               for o in out]
        return Tensor(jnp.asarray(_np.asarray(out, dtype)))

    @staticmethod
    def hz_to_mel(freq, htk: bool = False):
        f = np.asarray(freq, np.float64)
        if htk:
            out = 2595.0 * np.log10(1.0 + f / 700.0)
        else:
            f_min, f_sp = 0.0, 200.0 / 3
            out = (f - f_min) / f_sp
            min_log_hz = 1000.0
            min_log_mel = (min_log_hz - f_min) / f_sp
            logstep = math.log(6.4) / 27.0
            safe = np.maximum(f, 1e-30)  # both where-branches evaluate
            out = np.where(f >= min_log_hz,
                           min_log_mel + np.log(safe / min_log_hz) / logstep,
                           out)
        return float(out) if np.isscalar(freq) else out

    @staticmethod
    def mel_to_hz(mel, htk: bool = False):
        m = np.asarray(mel, np.float64)
        if htk:
            out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
        else:
            f_min, f_sp = 0.0, 200.0 / 3
            out = f_min + f_sp * m
            min_log_hz = 1000.0
            min_log_mel = (min_log_hz - f_min) / f_sp
            logstep = math.log(6.4) / 27.0
            out = np.where(m >= min_log_mel,
                           min_log_hz * np.exp(logstep * (m - min_log_mel)),
                           out)
        return float(out) if np.isscalar(mel) else out

    @staticmethod
    def get_window(window: str, win_length: int, fftbins: bool = True):
        """hann/hamming/blackman/bartlett/kaiser (parity:
        audio/functional/window.py)."""
        n = win_length
        sym = not fftbins
        m = n if sym else n + 1
        k = np.arange(m)
        if window == "hann":
            w = 0.5 - 0.5 * np.cos(2 * np.pi * k / (m - 1))
        elif window == "hamming":
            w = 0.54 - 0.46 * np.cos(2 * np.pi * k / (m - 1))
        elif window == "blackman":
            w = (0.42 - 0.5 * np.cos(2 * np.pi * k / (m - 1))
                 + 0.08 * np.cos(4 * np.pi * k / (m - 1)))
        elif window == "bartlett":
            w = 1.0 - np.abs(2 * k / (m - 1) - 1)
        elif window == "kaiser":
            w = np.kaiser(m, 12.0)
        else:
            raise ValueError(f"unknown window {window!r}")
        if not sym:
            w = w[:-1]
        return Tensor(jnp.asarray(w, jnp.float32))

    @staticmethod
    def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                             f_min: float = 0.0, f_max=None,
                             htk: bool = False, norm="slaney"):
        """Mel filterbank [n_mels, n_fft//2+1] (parity:
        audio/functional/functional.py compute_fbank_matrix)."""
        f_max = f_max or sr / 2.0
        n_bins = n_fft // 2 + 1
        fft_freqs = np.linspace(0, sr / 2.0, n_bins)
        mel_pts = np.linspace(functional.hz_to_mel(f_min, htk),
                              functional.hz_to_mel(f_max, htk), n_mels + 2)
        hz_pts = functional.mel_to_hz(mel_pts, htk)
        fb = np.zeros((n_mels, n_bins))
        for i in range(n_mels):
            lo, c, hi = hz_pts[i], hz_pts[i + 1], hz_pts[i + 2]
            up = (fft_freqs - lo) / max(c - lo, 1e-10)
            down = (hi - fft_freqs) / max(hi - c, 1e-10)
            fb[i] = np.maximum(0.0, np.minimum(up, down))
        if norm == "slaney":
            enorm = 2.0 / (hz_pts[2:n_mels + 2] - hz_pts[:n_mels])
            fb *= enorm[:, None]
        return Tensor(jnp.asarray(fb, jnp.float32))

    @staticmethod
    def create_dct(n_mfcc: int, n_mels: int, norm="ortho"):
        """DCT-II matrix [n_mels, n_mfcc] (parity: create_dct)."""
        k = np.arange(n_mels)
        dct = np.cos(np.pi / n_mels * (k[:, None] + 0.5)
                     * np.arange(n_mfcc)[None, :])
        if norm == "ortho":
            dct[:, 0] *= 1.0 / math.sqrt(n_mels)
            dct[:, 1:] *= math.sqrt(2.0 / n_mels)
        else:
            dct *= 2.0
        return Tensor(jnp.asarray(dct, jnp.float32))

    @staticmethod
    def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                    top_db=80.0):
        def fn(s):
            log_spec = 10.0 * jnp.log10(jnp.maximum(s, amin))
            log_spec = log_spec - 10.0 * jnp.log10(
                jnp.maximum(amin, ref_value))
            if top_db is not None:
                log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
            return log_spec
        return run_op("power_to_db", fn, (spect,))


def _stft_mag(x, n_fft, hop_length, window, power, center):
    """|STFT|^power over the last axis: frame -> window -> rfft."""
    if center:
        pad = n_fft // 2
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(pad, pad)],
                    mode="reflect")
    n = x.shape[-1]
    n_frames = 1 + (n - n_fft) // hop_length
    idx = (jnp.arange(n_frames)[:, None] * hop_length
           + jnp.arange(n_fft)[None, :])
    frames = x[..., idx] * window            # [..., frames, n_fft]
    spec = jnp.abs(jnp.fft.rfft(frames, axis=-1)) ** power
    return jnp.swapaxes(spec, -1, -2)        # [..., freq, frames]


class features:
    """paddle.audio.features layers."""

    class Spectrogram(Layer):
        def __init__(self, n_fft: int = 512, hop_length=None,
                     win_length=None, window: str = "hann", power: float = 2.0,
                     center: bool = True, pad_mode: str = "reflect",
                     dtype: str = "float32"):
            super().__init__()
            self.n_fft = n_fft
            self.hop_length = hop_length or n_fft // 4
            self.power = power
            self.center = center
            win_length = win_length or n_fft
            w = functional.get_window(window, win_length)._data
            if win_length < n_fft:  # center-pad the window to n_fft
                lpad = (n_fft - win_length) // 2
                w = jnp.pad(w, (lpad, n_fft - win_length - lpad))
            self.register_buffer("window", Tensor(w))

        def forward(self, x):
            win = self.window._data
            return run_op(
                "spectrogram",
                lambda a: _stft_mag(a, self.n_fft, self.hop_length, win,
                                    self.power, self.center), (x,))

    class MelSpectrogram(Layer):
        def __init__(self, sr: int = 22050, n_fft: int = 512,
                     hop_length=None, win_length=None, window: str = "hann",
                     power: float = 2.0, center: bool = True,
                     n_mels: int = 64, f_min: float = 50.0, f_max=None,
                     htk: bool = False, norm="slaney", dtype="float32"):
            super().__init__()
            self.spectrogram = features.Spectrogram(
                n_fft, hop_length, win_length, window, power, center)
            fb = functional.compute_fbank_matrix(
                sr, n_fft, n_mels, f_min, f_max, htk, norm)
            self.register_buffer("fbank", fb)

        def forward(self, x):
            spec = self.spectrogram(x)
            fb = self.fbank._data
            return run_op("mel_spectrogram",
                          lambda s: jnp.einsum("mf,...ft->...mt", fb, s),
                          (spec,))

    class LogMelSpectrogram(Layer):
        def __init__(self, *args, ref_value: float = 1.0, amin: float = 1e-10,
                     top_db=None, **kwargs):
            super().__init__()
            self.mel = features.MelSpectrogram(*args, **kwargs)
            self.ref_value = ref_value
            self.amin = amin
            self.top_db = top_db

        def forward(self, x):
            return functional.power_to_db(self.mel(x), self.ref_value,
                                          self.amin, self.top_db)

    class MFCC(Layer):
        def __init__(self, sr: int = 22050, n_mfcc: int = 40,
                     n_mels: int = 64, **kwargs):
            super().__init__()
            self.logmel = features.LogMelSpectrogram(sr, n_mels=n_mels,
                                                     **kwargs)
            self.register_buffer("dct", functional.create_dct(n_mfcc,
                                                              n_mels))

        def forward(self, x):
            lm = self.logmel(x)
            dct = self.dct._data
            return run_op("mfcc",
                          lambda s: jnp.einsum("mk,...mt->...kt", dct, s),
                          (lm,))


from . import backends  # noqa: E402
from . import datasets  # noqa: E402
from .backends import info, load, save  # noqa: E402,F401
