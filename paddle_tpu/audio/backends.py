"""Audio IO backends (parity: python/paddle/audio/backends/ — load/save/
info dispatch over a selected backend). The in-tree backend decodes
16/32-bit PCM WAV with the stdlib wave module — no soundfile dependency;
the reference's default ("wave_backend") has the same scope.
"""
from __future__ import annotations

import wave as _wave

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["list_available_backends", "get_current_backend", "set_backend",
           "load", "save", "info", "AudioInfo"]

_BACKEND = "wave_backend"


def list_available_backends():
    return ["wave_backend"]


def get_current_backend():
    return _BACKEND


def set_backend(backend_name):
    if backend_name not in list_available_backends():
        raise NotImplementedError(
            f"backend {backend_name} is unavailable; only the stdlib "
            "wave_backend ships in the TPU build")


class AudioInfo:
    """(parity: paddle.audio.backends.backend.AudioInfo)"""

    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding="PCM_S"):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def info(filepath):
    """(parity: paddle.audio.info)"""
    with _wave.open(filepath, "rb") as w:
        return AudioInfo(w.getframerate(), w.getnframes(),
                         w.getnchannels(), w.getsampwidth() * 8)


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """Load a PCM WAV file (parity: paddle.audio.load). Returns
    (waveform Tensor, sample_rate)."""
    with _wave.open(filepath, "rb") as w:
        sr = w.getframerate()
        n = w.getnframes()
        ch = w.getnchannels()
        width = w.getsampwidth()
        w.setpos(min(frame_offset, n))
        count = n - frame_offset if num_frames < 0 else num_frames
        raw = w.readframes(count)
    dt = {1: np.uint8, 2: np.int16, 4: np.int32}[width]
    data = np.frombuffer(raw, dt).reshape(-1, ch)
    if normalize:
        if width == 1:
            arr = (data.astype(np.float32) - 128.0) / 128.0
        else:
            arr = data.astype(np.float32) / float(2 ** (8 * width - 1))
    else:
        arr = data
    if channels_first:
        arr = arr.T
    return Tensor(jnp.asarray(arr)), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_16", bits_per_sample=16):
    """Write a PCM WAV file (parity: paddle.audio.save)."""
    arr = np.asarray(src._data if isinstance(src, Tensor) else src)
    if channels_first:
        arr = arr.T  # (T, C)
    if arr.ndim == 1:
        arr = arr[:, None]
    width = bits_per_sample // 8
    if np.issubdtype(arr.dtype, np.floating):
        scale = float(2 ** (bits_per_sample - 1) - 1)
        arr = np.clip(arr, -1.0, 1.0) * scale
        arr = arr.astype({2: np.int16, 4: np.int32}[width])
    with _wave.open(filepath, "wb") as w:
        w.setnchannels(arr.shape[1])
        w.setsampwidth(width)
        w.setframerate(int(sample_rate))
        w.writeframes(arr.tobytes())
