"""Audio datasets (parity: python/paddle/audio/datasets/ — TESS, ESC50).
Local-directory contract (no network egress in this environment)."""
from __future__ import annotations

import os

import numpy as np

from ..io import Dataset
from . import backends as _backends

__all__ = ["TESS", "ESC50"]


def _need_dir(path, what):
    if path is None or not os.path.isdir(path):
        raise FileNotFoundError(
            f"{what}: this environment has no network egress — pass the "
            "local dataset directory (the reference downloads an archive)")


class _FolderAudioDataset(Dataset):
    def __init__(self, data_dir, feat_type="raw", archive=None, **kwargs):
        super().__init__()
        self.feat_type = feat_type
        self.files = []
        self.labels = []
        self._scan(data_dir)
        self._feat_kwargs = kwargs

    def _scan(self, data_dir):
        raise NotImplementedError

    def _features(self, wav, sr):
        if self.feat_type == "raw":
            return wav
        if self.feat_type == "melspectrogram":
            from . import features
            mel = features.MelSpectrogram(sr=sr, **self._feat_kwargs)
            return mel(wav)
        raise ValueError(f"unsupported feat_type {self.feat_type}")

    def __getitem__(self, idx):
        wav, sr = _backends.load(self.files[idx])
        feat = self._features(wav, sr)
        return feat, np.int64(self.labels[idx])

    def __len__(self):
        return len(self.files)


class TESS(_FolderAudioDataset):
    """Toronto emotional speech set (parity: paddle.audio.datasets.TESS):
    <data_dir>/<speaker>_<word>_<emotion>.wav layout or nested dirs."""

    emotions = ["angry", "disgust", "fear", "happy", "neutral", "ps",
                "sad"]

    def __init__(self, mode="train", data_dir=None, n_folds=5,
                 split=1, feat_type="raw", archive=None, **kwargs):
        _need_dir(data_dir, "TESS")
        self.mode = mode
        self.n_folds = n_folds
        self.split = split
        super().__init__(data_dir, feat_type, archive, **kwargs)

    def _scan(self, data_dir):
        wavs = []
        for root, _, files in os.walk(data_dir):
            for f in sorted(files):
                if f.lower().endswith(".wav"):
                    wavs.append(os.path.join(root, f))
        for i, path in enumerate(wavs):
            emotion = os.path.splitext(os.path.basename(path))[0] \
                .split("_")[-1].lower()
            if emotion not in self.emotions:
                continue
            fold = i % self.n_folds + 1
            keep = (fold != self.split) if self.mode == "train" \
                else (fold == self.split)
            if keep:
                self.files.append(path)
                self.labels.append(self.emotions.index(emotion))


class ESC50(_FolderAudioDataset):
    """ESC-50 environmental sounds (parity: paddle.audio.datasets.ESC50):
    <data_dir>/audio/<fold>-*.wav names '{fold}-{src}-{take}-{target}.wav'."""

    def __init__(self, mode="train", data_dir=None, split=1,
                 feat_type="raw", archive=None, **kwargs):
        _need_dir(data_dir, "ESC50")
        self.mode = mode
        self.split = split
        super().__init__(data_dir, feat_type, archive, **kwargs)

    def _scan(self, data_dir):
        audio_dir = os.path.join(data_dir, "audio")
        if not os.path.isdir(audio_dir):
            audio_dir = data_dir
        for f in sorted(os.listdir(audio_dir)):
            if not f.lower().endswith(".wav"):
                continue
            parts = os.path.splitext(f)[0].split("-")
            if len(parts) != 4:
                continue
            fold, target = int(parts[0]), int(parts[3])
            keep = (fold != self.split) if self.mode == "train" \
                else (fold == self.split)
            if keep:
                self.files.append(os.path.join(audio_dir, f))
                self.labels.append(target)
