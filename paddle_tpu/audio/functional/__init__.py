"""Path-faithful module (parity: python/paddle/audio/functional/)."""
from .. import functional as _ns

compute_fbank_matrix = _ns.compute_fbank_matrix
create_dct = _ns.create_dct
fft_frequencies = _ns.fft_frequencies
hz_to_mel = _ns.hz_to_mel
mel_frequencies = _ns.mel_frequencies
mel_to_hz = _ns.mel_to_hz
power_to_db = _ns.power_to_db
get_window = _ns.get_window

__all__ = ["compute_fbank_matrix", "create_dct", "fft_frequencies",
           "hz_to_mel", "mel_frequencies", "mel_to_hz", "power_to_db",
           "get_window"]
