"""(parity: python/paddle/quantization/quanters/)"""
from .. import FakeQuanterWithAbsMax as FakeQuanterWithAbsMaxObserver  # noqa: F401

__all__ = ["FakeQuanterWithAbsMaxObserver"]
