"""Quantization (capability parity: python/paddle/quantization/ — QAT
fake-quant + PTQ observers + weight-only quantized linear; reference
kernels under paddle/phi/kernels/ quantize_linear etc.).

TPU-native: int8 weight-only is the practical TPU quantization mode
(int8 matmuls run on the MXU); fake-quant (QAT) is a straight-through
estimator implemented with a custom vjp.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn as _nn
from ..core.dispatch import run_op
from ..core.tensor import Tensor

__all__ = ["quantize_linear", "dequantize_linear", "abs_max_scale",
           "FakeQuanterWithAbsMax", "QuantConfig", "QAT",
           "WeightOnlyLinear", "weight_quantize", "weight_dequantize"]


def abs_max_scale(x, bit_length: int = 8):
    """Per-tensor abs-max scale (parity: the AbsmaxObserver)."""
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    qmax = float(2 ** (bit_length - 1) - 1)
    return jnp.maximum(jnp.max(jnp.abs(arr)), 1e-8) / qmax


def quantize_linear(x, scale, zero_point=0, bit_length: int = 8):
    """Symmetric linear quantize to int8 (parity: quantize_linear op)."""
    qmax = 2 ** (bit_length - 1) - 1

    def fn(a, s):
        q = jnp.clip(jnp.round(a / s) + zero_point, -qmax - 1, qmax)
        return q.astype(jnp.int8)
    return run_op("quantize_linear", fn,
                  (x, scale), out_stop_gradient=True)


def dequantize_linear(q, scale, zero_point=0):
    def fn(a, s):
        return (a.astype(jnp.float32) - zero_point) * s
    return run_op("dequantize_linear", fn, (q, scale))


@jax.custom_vjp
def _fake_quant(x, scale, qmax):
    q = jnp.clip(jnp.round(x / scale), -qmax - 1, qmax)
    return q * scale


def _fq_fwd(x, scale, qmax):
    return _fake_quant(x, scale, qmax), None


def _fq_bwd(_, g):
    return (g, None, None)  # straight-through estimator


_fake_quant.defvjp(_fq_fwd, _fq_bwd)


class FakeQuanterWithAbsMax(_nn.Layer):
    """QAT fake-quant layer (parity: FakeQuanterWithAbsMaxObserver):
    forward quantize-dequantizes with a running abs-max scale; backward is
    straight-through."""

    def __init__(self, bit_length: int = 8, moving_rate: float = 0.9,
                 name=None):
        super().__init__()
        del name
        self.bit_length = bit_length
        self.moving_rate = moving_rate
        self.register_buffer("scale",
                             Tensor(jnp.asarray(1.0, jnp.float32)))
        self._initialized = False

    def forward(self, x):
        qmax = float(2 ** (self.bit_length - 1) - 1)
        if self.training:
            cur = abs_max_scale(x, self.bit_length)
            if not self._initialized:
                new = cur
                self._initialized = True
            else:
                new = (self.moving_rate * self.scale._data
                       + (1 - self.moving_rate) * cur)
            self.scale._data = jnp.asarray(new, jnp.float32)
        return run_op("fake_quant",
                      lambda a, s: _fake_quant(a, s, qmax),
                      (x, Tensor(self.scale._data)))


class QuantConfig:
    """Parity: paddle.quantization.QuantConfig — maps layer types to
    quanters."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._type_configs = {}

    def add_type_config(self, layer_type, activation=None, weight=None):
        for t in (layer_type if isinstance(layer_type, (list, tuple))
                  else [layer_type]):
            self._type_configs[t] = (activation, weight)
        return self

    def config_for(self, layer):
        for t, cfg in self._type_configs.items():
            if isinstance(layer, t):
                return cfg
        return (self.activation, self.weight)


class _QuantedLinear(_nn.Layer):
    def __init__(self, linear, a_quanter, w_quanter):
        super().__init__()
        self.linear = linear
        self.a_quanter = a_quanter() if callable(a_quanter) else a_quanter
        self.w_quanter = w_quanter() if callable(w_quanter) else w_quanter

    def forward(self, x):
        if self.a_quanter is not None:
            x = self.a_quanter(x)
        w = self.linear.weight
        if self.w_quanter is not None:
            w = self.w_quanter(w)
        out = run_op("quant_linear",
                     lambda a, ww: jnp.matmul(a, ww), (x, w))
        if self.linear.bias is not None:
            out = out + self.linear.bias
        return out


class QAT:
    """Quantization-aware-training converter (parity:
    paddle.quantization.QAT.quantize)."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model, inplace=False):
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        self._convert(model)
        return model

    def _convert(self, layer):
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, _nn.Linear):
                a_q, w_q = self.config.config_for(sub)
                layer.add_sublayer(name, _QuantedLinear(sub, a_q, w_q))
            else:
                self._convert(sub)


# -- weight-only int8 (the TPU serving mode) --------------------------------

def weight_quantize(weight, algo: str = "weight_only_int8"):
    """-> (int8 weight, per-out-channel scales) (parity:
    paddle.nn.quant.weight_quantize)."""
    if algo != "weight_only_int8":
        raise NotImplementedError(f"algo {algo}")
    arr = weight._data if isinstance(weight, Tensor) else jnp.asarray(weight)
    scales = jnp.maximum(jnp.max(jnp.abs(arr), axis=0), 1e-8) / 127.0
    q = jnp.clip(jnp.round(arr / scales[None, :]), -128, 127)
    return Tensor(q.astype(jnp.int8)), Tensor(scales)


def weight_dequantize(qweight, scales):
    q = qweight._data if isinstance(qweight, Tensor) else qweight
    s = scales._data if isinstance(scales, Tensor) else scales
    return Tensor(q.astype(jnp.float32) * s[None, :])


class WeightOnlyLinear(_nn.Layer):
    """int8-weight linear (parity: paddle.nn.quant.llm_int8_linear /
    weight_only_linear): weights stored int8 + f32 scales, dequantized
    into the matmul (XLA fuses the scale multiply into the MXU op)."""

    def __init__(self, linear: _nn.Linear):
        super().__init__()
        qw, scales = weight_quantize(linear.weight)
        self.register_buffer("qweight", qw)
        self.register_buffer("scales", scales)
        self.bias = linear.bias

    def forward(self, x):
        def fn(a, q, s):
            return jnp.matmul(a, q.astype(a.dtype) * s[None, :])
        out = run_op("weight_only_linear", fn,
                     (x, Tensor(self.qweight._data),
                      Tensor(self.scales._data)))
        if self.bias is not None:
            out = out + self.bias
        return out
