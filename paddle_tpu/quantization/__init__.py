"""Quantization (capability parity: python/paddle/quantization/ — QAT
fake-quant + PTQ observers + weight-only quantized linear; reference
kernels under paddle/phi/kernels/ quantize_linear etc.).

TPU-native: int8 weight-only is the practical TPU quantization mode
(int8 matmuls run on the MXU); fake-quant (QAT) is a straight-through
estimator implemented with a custom vjp.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn as _nn
from ..core.dispatch import run_op
from ..core.tensor import Tensor

__all__ = ["BaseQuanter", "BaseObserver", "quanter",
           "quantize_linear", "dequantize_linear", "abs_max_scale",
           "channel_wise_abs_max_scale", "FakeQuanterWithAbsMax",
           "FakeQuanterChannelWiseAbsMax", "AbsmaxObserver", "HistObserver",
           "QuantConfig", "QAT", "PTQ", "WeightOnlyLinear",
           "weight_quantize", "weight_dequantize"]


def abs_max_scale(x, bit_length: int = 8):
    """Per-tensor abs-max scale (parity: the AbsmaxObserver)."""
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    qmax = float(2 ** (bit_length - 1) - 1)
    return jnp.maximum(jnp.max(jnp.abs(arr)), 1e-8) / qmax


def quantize_linear(x, scale, zero_point=0, bit_length: int = 8):
    """Symmetric linear quantize to int8 (parity: quantize_linear op)."""
    qmax = 2 ** (bit_length - 1) - 1

    def fn(a, s):
        q = jnp.clip(jnp.round(a / s) + zero_point, -qmax - 1, qmax)
        return q.astype(jnp.int8)
    return run_op("quantize_linear", fn,
                  (x, scale), out_stop_gradient=True)


def dequantize_linear(q, scale, zero_point=0):
    def fn(a, s):
        return (a.astype(jnp.float32) - zero_point) * s
    return run_op("dequantize_linear", fn, (q, scale))


@jax.custom_vjp
def _fake_quant(x, scale, qmax):
    q = jnp.clip(jnp.round(x / scale), -qmax - 1, qmax)
    return q * scale


def _fq_fwd(x, scale, qmax):
    return _fake_quant(x, scale, qmax), None


def _fq_bwd(_, g):
    return (g, None, None)  # straight-through estimator


_fake_quant.defvjp(_fq_fwd, _fq_bwd)


class FakeQuanterWithAbsMax(_nn.Layer):
    """QAT fake-quant layer (parity: FakeQuanterWithAbsMaxObserver):
    forward quantize-dequantizes with a running abs-max scale; backward is
    straight-through."""

    def __init__(self, bit_length: int = 8, moving_rate: float = 0.9,
                 name=None):
        super().__init__()
        del name
        self.bit_length = bit_length
        self.moving_rate = moving_rate
        self.register_buffer("scale",
                             Tensor(jnp.asarray(1.0, jnp.float32)))
        self._initialized = False

    def forward(self, x):
        qmax = float(2 ** (self.bit_length - 1) - 1)
        if self.training:
            cur = abs_max_scale(x, self.bit_length)
            if not self._initialized:
                new = cur
                self._initialized = True
            else:
                new = (self.moving_rate * self.scale._data
                       + (1 - self.moving_rate) * cur)
            self.scale._data = jnp.asarray(new, jnp.float32)
        return run_op("fake_quant",
                      lambda a, s: _fake_quant(a, s, qmax),
                      (x, Tensor(self.scale._data)))


def channel_wise_abs_max_scale(x, quant_axis: int = 0,
                               bit_length: int = 8):
    """Per-channel abs-max scales along ``quant_axis`` (parity: the
    reference's channel_wise_quantize_max_abs kernel /
    ChannelWiseAbsMaxObserver)."""
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    qmax = float(2 ** (bit_length - 1) - 1)
    quant_axis = quant_axis % arr.ndim  # paddle-style negative axes
    reduce_axes = tuple(d for d in range(arr.ndim) if d != quant_axis)
    return jnp.maximum(jnp.max(jnp.abs(arr), axis=reduce_axes), 1e-8) / qmax


class FakeQuanterChannelWiseAbsMax(_nn.Layer):
    """Per-channel QAT fake-quant (parity:
    FakeQuanterChannelWiseAbsMaxObserver): one scale per channel of
    ``quant_axis``, straight-through backward. Weights quantize per
    out-channel, which preserves accuracy that per-tensor scales lose on
    channels with very different ranges."""

    def __init__(self, bit_length: int = 8, quant_axis: int = 0, name=None):
        super().__init__()
        del name
        self.bit_length = bit_length
        self.quant_axis = quant_axis

    def forward(self, x):
        qmax = float(2 ** (self.bit_length - 1) - 1)
        axis = self.quant_axis % (len(x.shape))
        scales = channel_wise_abs_max_scale(x, axis, self.bit_length)
        bshape = [1] * len(x.shape)
        bshape[axis] = -1
        return run_op("fake_quant_channel",
                      lambda a, s: _fake_quant(a, s.reshape(bshape), qmax),
                      (x, Tensor(scales)))


# -- PTQ observers (parity: paddle/quantization/observers/) -----------------

class AbsmaxObserver(_nn.Layer):
    """Running abs-max calibration observer (parity: AbsmaxObserver):
    forward is identity; ``scale()`` yields the calibrated scale."""

    def __init__(self, bit_length: int = 8):
        super().__init__()
        self.bit_length = bit_length
        self._max = 0.0

    def forward(self, x):
        arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        self._max = max(self._max, float(jnp.max(jnp.abs(arr))))
        return x

    def scale(self) -> float:
        qmax = float(2 ** (self.bit_length - 1) - 1)
        return max(self._max, 1e-8) / qmax


class HistObserver(_nn.Layer):
    """Histogram percentile observer (parity: HistObserver /
    PercentHistObserver): accumulates an |x| histogram during calibration
    and picks the scale at a percentile, clipping rare outliers that would
    waste int8 range."""

    def __init__(self, bit_length: int = 8, bins_count: int = 2048,
                 percent: float = 0.999):
        super().__init__()
        self.bit_length = bit_length
        self.bins = bins_count
        self.percent = percent
        self._hist = np.zeros(bins_count, np.float64)
        self._range = 1e-8

    def forward(self, x):
        arr = np.abs(np.asarray(
            x._data if isinstance(x, Tensor) else x, np.float32)).ravel()
        top = float(arr.max()) if arr.size else 0.0
        if top > self._range:
            # stretch: rebin the existing histogram into the new range
            old_edges = np.linspace(0, self._range, self.bins + 1)
            new_range = top
            scaled = np.zeros_like(self._hist)
            centers = (old_edges[:-1] + old_edges[1:]) / 2
            idx = np.minimum(
                (centers / new_range * self.bins).astype(np.int64),
                self.bins - 1)
            np.add.at(scaled, idx, self._hist)
            self._hist = scaled
            self._range = new_range
        h, _ = np.histogram(arr, bins=self.bins, range=(0, self._range))
        self._hist += h
        return x

    def scale(self) -> float:
        total = self._hist.sum()
        qmax = float(2 ** (self.bit_length - 1) - 1)
        if total == 0:
            return 1e-8 / qmax
        cdf = np.cumsum(self._hist) / total
        bin_i = int(np.searchsorted(cdf, self.percent))
        threshold = (bin_i + 1) / self.bins * self._range
        return max(threshold, 1e-8) / qmax


class PTQ:
    """Post-training quantization driver (parity: paddle.quantization.PTQ):
    ``quantize`` inserts observers, the user runs calibration batches, and
    ``convert`` freezes observed scales into quantized layers."""

    def __init__(self, config: "QuantConfig"):
        self.config = config

    def quantize(self, model, inplace=False):
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        self._insert(model)
        return model

    def _insert(self, layer):
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, _nn.Linear):
                a_q, _ = self.config.config_for(sub)
                obs = a_q() if callable(a_q) else (a_q or AbsmaxObserver())
                if not callable(getattr(obs, "scale", None)):
                    raise TypeError(
                        f"PTQ needs an observer with a scale() method for "
                        f"calibration, got {type(obs).__name__} — QAT "
                        "quanters (FakeQuanter*) go through QAT.quantize, "
                        "not PTQ")
                layer.add_sublayer(name, _ObservedLinear(sub, obs))
            else:
                self._insert(sub)

    def convert(self, model, inplace=False):
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        self._freeze(model)
        return model

    def _freeze(self, layer):
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, _ObservedLinear):
                layer.add_sublayer(
                    name, _FrozenQuantLinear(sub.linear,
                                             sub.observer.scale()))
            else:
                self._freeze(sub)


class _ObservedLinear(_nn.Layer):
    def __init__(self, linear, observer):
        super().__init__()
        self.linear = linear
        self.observer = observer

    def forward(self, x):
        return self.linear(self.observer(x))


class _FrozenQuantLinear(_nn.Layer):
    """Inference-time int8 simulation: activations quant-dequant with the
    frozen observed scale; weights per-out-channel int8."""

    def __init__(self, linear, act_scale: float, w_scales=None):
        super().__init__()
        self.act_scale = float(act_scale)
        if w_scales is None:
            qw, scales = weight_quantize(linear.weight)
        else:
            # calibrated per-out-channel (or broadcast per-tensor) scales
            # from the PTQ weight quantizer
            arr = linear.weight._data
            scales = Tensor(jnp.broadcast_to(
                jnp.maximum(jnp.asarray(w_scales, jnp.float32), 1e-8)
                / 127.0, (arr.shape[-1],)))
            qw = Tensor(jnp.clip(jnp.round(arr / scales._data[None, :]),
                                 -128, 127).astype(jnp.int8))
        self.register_buffer("qweight", qw)
        self.register_buffer("wscales", scales)
        self.bias = linear.bias

    def forward(self, x):
        def fn(a, q, s):
            aq = jnp.clip(jnp.round(a / self.act_scale), -128, 127)
            a_dq = aq * self.act_scale
            return jnp.matmul(a_dq, q.astype(a.dtype) * s[None, :])
        out = run_op("ptq_linear", fn,
                     (x, Tensor(self.qweight._data),
                      Tensor(self.wscales._data)))
        if self.bias is not None:
            out = out + self.bias
        return out


class QuantConfig:
    """Parity: paddle.quantization.QuantConfig — maps layer types to
    quanters."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._type_configs = {}

    def add_type_config(self, layer_type, activation=None, weight=None):
        for t in (layer_type if isinstance(layer_type, (list, tuple))
                  else [layer_type]):
            self._type_configs[t] = (activation, weight)
        return self

    def config_for(self, layer):
        for t, cfg in self._type_configs.items():
            if isinstance(layer, t):
                return cfg
        return (self.activation, self.weight)


class _QuantedLinear(_nn.Layer):
    def __init__(self, linear, a_quanter, w_quanter):
        super().__init__()
        self.linear = linear
        self.a_quanter = a_quanter() if callable(a_quanter) else a_quanter
        self.w_quanter = w_quanter() if callable(w_quanter) else w_quanter

    def forward(self, x):
        if self.a_quanter is not None:
            x = self.a_quanter(x)
        w = self.linear.weight
        if self.w_quanter is not None:
            w = self.w_quanter(w)
        out = run_op("quant_linear",
                     lambda a, ww: jnp.matmul(a, ww), (x, w))
        if self.linear.bias is not None:
            out = out + self.linear.bias
        return out


class QAT:
    """Quantization-aware-training converter (parity:
    paddle.quantization.QAT.quantize)."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model, inplace=False):
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        self._convert(model)
        return model

    def _convert(self, layer):
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, _nn.Linear):
                a_q, w_q = self.config.config_for(sub)
                layer.add_sublayer(name, _QuantedLinear(sub, a_q, w_q))
            else:
                self._convert(sub)


# -- weight-only int8 (the TPU serving mode) --------------------------------

def weight_quantize(weight, algo: str = "weight_only_int8"):
    """-> (int8 weight, per-out-channel scales) (parity:
    paddle.nn.quant.weight_quantize)."""
    if algo != "weight_only_int8":
        raise NotImplementedError(f"algo {algo}")
    arr = weight._data if isinstance(weight, Tensor) else jnp.asarray(weight)
    scales = jnp.maximum(jnp.max(jnp.abs(arr), axis=0), 1e-8) / 127.0
    q = jnp.clip(jnp.round(arr / scales[None, :]), -128, 127)
    return Tensor(q.astype(jnp.int8)), Tensor(scales)


def weight_dequantize(qweight, scales):
    q = qweight._data if isinstance(qweight, Tensor) else qweight
    s = scales._data if isinstance(scales, Tensor) else scales
    return Tensor(q.astype(jnp.float32) * s[None, :])


class WeightOnlyLinear(_nn.Layer):
    """int8-weight linear (parity: paddle.nn.quant.llm_int8_linear /
    weight_only_linear): weights stored int8 + f32 scales, dequantized
    into the matmul (XLA fuses the scale multiply into the MXU op)."""

    def __init__(self, linear: _nn.Linear):
        super().__init__()
        qw, scales = weight_quantize(linear.weight)
        self.register_buffer("qweight", qw)
        self.register_buffer("scales", scales)
        self.bias = linear.bias

    def forward(self, x):
        def fn(a, q, s):
            return jnp.matmul(a, q.astype(a.dtype) * s[None, :])
        out = run_op("weight_only_linear", fn,
                     (x, Tensor(self.qweight._data),
                      Tensor(self.scales._data)))
        if self.bias is not None:
            out = out + self.bias
        return out


class BaseQuanter:
    """Abstract trainable quanter (parity: paddle.quantization.BaseQuanter,
    python/paddle/quantization/factory.py). Subclasses implement
    forward/scales/zero_points."""

    def forward(self, input):
        raise NotImplementedError

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        raise NotImplementedError

    def bit_length(self):
        return 8


class BaseObserver(BaseQuanter):
    """Abstract calibration observer (parity:
    paddle.quantization.BaseObserver)."""

    def cal_thresholds(self):
        raise NotImplementedError


class _QuanterFactory:
    def __init__(self, cls, *args, **kwargs):
        self.cls = cls
        self.args = args
        self.kwargs = kwargs

    def _instance(self, layer=None):
        return self.cls(*self.args, **self.kwargs)

    def __call__(self, *args, **kwargs):
        return self.cls(*args, **kwargs)


def quanter(name):
    """Class decorator registering a quanter and generating its partial-
    construction factory (parity: paddle.quantization.quanter)."""
    def deco(cls):
        import sys
        mod = sys.modules[__name__]

        def factory(*args, **kwargs):
            return _QuanterFactory(cls, *args, **kwargs)
        factory.__name__ = name
        setattr(mod, name, factory)
        return cls
    return deco

from .imperative import (BaseQuantizer, AbsmaxQuantizer,  # noqa: E402,F401
                         PerChannelAbsmaxQuantizer, HistQuantizer,
                         KLQuantizer, PTQConfig, default_ptq_config,
                         ImperativePTQ, ImperativeQuantAware,
                         SUPPORT_ACT_QUANTIZERS, SUPPORT_WT_QUANTIZERS,
                         PTQRegistry)
