"""Legacy imperative PTQ/QAT surface (parity:
python/paddle/quantization/imperative/ — ImperativePTQ + the PTQ
quantizer zoo). Built over this package's observer machinery; thresholds
are computed in NumPy on host (calibration is a host-side pass in the
reference too).
"""
from __future__ import annotations

import abc

import numpy as np

from .. import nn as _nn

__all__ = ["BaseQuantizer", "AbsmaxQuantizer", "PerChannelAbsmaxQuantizer",
           "HistQuantizer", "KLQuantizer", "PTQConfig", "default_ptq_config",
           "ImperativePTQ", "ImperativeQuantAware",
           "SUPPORT_ACT_QUANTIZERS", "SUPPORT_WT_QUANTIZERS",
           "PTQRegistry"]


def abs_max_value(tensor):
    return float(np.max(np.abs(np.asarray(
        tensor._data if hasattr(tensor, "_data") else tensor))))


class BaseQuantizer(metaclass=abc.ABCMeta):
    """(reference ptq_quantizer.py:95) — sample values during
    calibration, then cal_thresholds() fixes the quant threshold."""

    def __init__(self, quant_bits=8):
        self.quant_bits = quant_bits
        self.thresholds: list = []

    @abc.abstractmethod
    def sample_data(self, layer, tensors):
        ...

    @abc.abstractmethod
    def cal_thresholds(self):
        ...


class AbsmaxQuantizer(BaseQuantizer):
    """Running abs-max over calibration batches (ptq_quantizer.py:119)."""

    def __init__(self, quant_bits=8):
        super().__init__(quant_bits)
        self.abs_max_vals: list = []

    def sample_data(self, layer, tensors):
        if not isinstance(tensors, (list, tuple)):
            tensors = (tensors,)
        vals = [abs_max_value(t) for t in tensors]
        if not self.abs_max_vals:
            self.abs_max_vals = vals
        else:
            self.abs_max_vals = [max(o, n) for o, n in
                                 zip(self.abs_max_vals, vals)]

    def cal_thresholds(self):
        self.thresholds = list(self.abs_max_vals)


class PerChannelAbsmaxQuantizer(BaseQuantizer):
    """Per-output-channel abs-max for weights (ptq_quantizer.py:137)."""

    def __init__(self, quant_bits=8):
        super().__init__(quant_bits)
        self.abs_max_vals: list = []

    def sample_data(self, layer, tensors):
        if not isinstance(tensors, (list, tuple)):
            tensors = (tensors,)
        vals = []
        for t in tensors:
            arr = np.asarray(t._data if hasattr(t, "_data") else t)
            # Linear weights are (in, out): channel axis is the last
            flat = np.abs(arr.reshape(-1, arr.shape[-1]))
            vals.append(flat.max(axis=0).tolist())
        self.abs_max_vals = vals

    def cal_thresholds(self):
        self.thresholds = list(self.abs_max_vals)


class BaseHistQuantizer(BaseQuantizer, metaclass=abc.ABCMeta):
    def __init__(self, quant_bits=8, bins=1024):
        super().__init__(quant_bits)
        self.bins = bins
        self.hists: list = []
        self.abs_max_vals: list = []

    def sample_data(self, layer, tensors):
        if not isinstance(tensors, (list, tuple)):
            tensors = (tensors,)
        for i, t in enumerate(tensors):
            arr = np.abs(np.asarray(
                t._data if hasattr(t, "_data") else t)).ravel()
            amax = float(arr.max()) if arr.size else 0.0
            if len(self.hists) <= i:
                self.abs_max_vals.append(max(amax, 1e-8))
                h, _ = np.histogram(arr, bins=self.bins,
                                    range=(0, self.abs_max_vals[i]))
                self.hists.append(h.astype(np.float64))
            else:
                if amax > self.abs_max_vals[i]:
                    # re-bin the old histogram onto the wider range
                    ratio = self.abs_max_vals[i] / amax
                    old = self.hists[i]
                    new = np.zeros_like(old)
                    idx = (np.arange(self.bins) * ratio).astype(int)
                    np.add.at(new, np.clip(idx, 0, self.bins - 1), old)
                    self.hists[i] = new
                    self.abs_max_vals[i] = amax
                h, _ = np.histogram(arr, bins=self.bins,
                                    range=(0, self.abs_max_vals[i]))
                self.hists[i] += h


class HistQuantizer(BaseHistQuantizer):
    """Percentile-of-histogram threshold (ptq_quantizer.py:218)."""

    def __init__(self, quant_bits=8, bins=1024, upsample_bins=64,
                 hist_percent=0.99999):
        super().__init__(quant_bits, bins)
        self.hist_percent = hist_percent

    def cal_thresholds(self):
        self.thresholds = []
        for h, amax in zip(self.hists, self.abs_max_vals):
            total = h.sum()
            if total == 0:
                self.thresholds.append(amax)
                continue
            cum = np.cumsum(h) / total
            idx = int(np.searchsorted(cum, self.hist_percent))
            self.thresholds.append(
                (idx + 0.5) * amax / self.bins)


class KLQuantizer(BaseHistQuantizer):
    """KL-divergence-optimal threshold (ptq_quantizer.py:245 — the
    TensorRT-style calibration): pick the clip bin whose quantized
    distribution diverges least from the observed one."""

    def cal_thresholds(self):
        self.thresholds = []
        levels = 2 ** (self.quant_bits - 1)
        for h, amax in zip(self.hists, self.abs_max_vals):
            if h.sum() == 0:
                self.thresholds.append(amax)
                continue
            best_kl, best_i = float("inf"), self.bins - 1
            for i in range(levels, self.bins):
                p = h[:i].copy()
                p[-1] += h[i:].sum()          # clip tail into last bin
                p /= p.sum()
                # quantize the i bins down to `levels` buckets
                factor = i / levels
                q = np.zeros(i)
                for b in range(levels):
                    lo, hi = int(b * factor), max(int((b + 1) * factor), 1)
                    seg = h[lo:hi]
                    nz = (seg > 0).sum()
                    if nz:
                        q[lo:hi] = np.where(seg > 0, seg.sum() / nz, 0)
                if q.sum() == 0:
                    continue
                q /= q.sum()
                mask = p > 0
                kl = float(np.sum(p[mask] * np.log(
                    p[mask] / np.maximum(q[mask], 1e-12))))
                if kl < best_kl:
                    best_kl, best_i = kl, i
            self.thresholds.append((best_i + 0.5) * amax / self.bins)


SUPPORT_ACT_QUANTIZERS = [AbsmaxQuantizer, HistQuantizer, KLQuantizer]
SUPPORT_WT_QUANTIZERS = [AbsmaxQuantizer, PerChannelAbsmaxQuantizer]


class PTQConfig:
    """(reference ptq_config.py:25)"""

    def __init__(self, activation_quantizer=None, weight_quantizer=None):
        act = activation_quantizer or KLQuantizer()
        wt = weight_quantizer or PerChannelAbsmaxQuantizer()
        if not isinstance(act, tuple(SUPPORT_ACT_QUANTIZERS)):
            raise ValueError(
                f"activation_quantizer {type(act).__name__} not supported")
        if not isinstance(wt, tuple(SUPPORT_WT_QUANTIZERS)):
            raise ValueError(
                f"weight_quantizer {type(wt).__name__} not supported")
        self.in_act_quantizer = act
        self.wt_quantizer = wt


def default_ptq_config():
    return PTQConfig(KLQuantizer(), PerChannelAbsmaxQuantizer())


class PTQRegistry:
    """Quantizable-layer registry (reference ptq_registry.py); Linear is
    the quantized surface on this substrate."""

    @classmethod
    def is_supported_layer(cls, layer):
        return isinstance(layer, _nn.Linear)


class _CalibratedLinear(_nn.Layer):
    def __init__(self, linear, cfg: PTQConfig):
        super().__init__()
        self.linear = linear
        import copy
        self.act_quantizer = copy.deepcopy(cfg.in_act_quantizer)
        self.wt_quantizer = copy.deepcopy(cfg.wt_quantizer)
        self.wt_quantizer.sample_data(linear, (linear.weight,))

    def forward(self, x):
        self.act_quantizer.sample_data(self.linear, (x,))
        return self.linear(x)


class ImperativePTQ:
    """(reference imperative/ptq.py:42): quantize() inserts calibration
    wrappers; after running calibration batches, save_quantized_model
    fixes thresholds and exports through jit.save."""

    def __init__(self, quant_config=None):
        if callable(quant_config) and not isinstance(quant_config,
                                                     PTQConfig):
            quant_config = quant_config()
        self._config = quant_config or default_ptq_config()

    def quantize(self, model, inplace=False, fuse=False, fuse_list=None):
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        self._insert(model)
        return model

    def _insert(self, layer):
        for name, sub in list(layer._sub_layers.items()):
            if PTQRegistry.is_supported_layer(sub):
                layer.add_sublayer(name, _CalibratedLinear(sub,
                                                           self._config))
            else:
                self._insert(sub)

    def save_quantized_model(self, model, path, input_spec=None, **config):
        # fix thresholds, unwrap to frozen fake-quant layers, export
        self._freeze(model)
        from ..jit import save as jit_save
        jit_save(model, path, input_spec=input_spec)
        return model

    def _freeze(self, layer):
        from . import _FrozenQuantLinear
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, _CalibratedLinear):
                sub.act_quantizer.cal_thresholds()
                sub.wt_quantizer.cal_thresholds()
                thr = (sub.act_quantizer.thresholds or [1.0])[0]
                wt = (sub.wt_quantizer.thresholds or [None])[0]
                layer.add_sublayer(
                    name, _FrozenQuantLinear(sub.linear, float(thr),
                                             w_scales=wt))
            else:
                self._freeze(sub)


class ImperativeQuantAware:
    """(reference imperative/qat.py ImperativeQuantAware): insert fake
    quant/dequant into Linear layers for QAT, export via jit.save.
    ``weight_bits``/``activation_bits`` size the fake-quant ranges;
    'moving_average_abs_max' activations use the running-scale quanter,
    'abs_max' re-measures per batch (moving_rate 0)."""

    def __init__(self, quantizable_layer_type=("Linear",),
                 weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 weight_bits=8, activation_bits=8, moving_rate=0.9,
                 **kwargs):
        from . import FakeQuanterWithAbsMax, QAT, QuantConfig
        act_rate = (moving_rate
                    if activation_quantize_type == "moving_average_abs_max"
                    else 0.0)
        cfg = QuantConfig(
            activation=lambda: FakeQuanterWithAbsMax(
                bit_length=activation_bits, moving_rate=act_rate),
            weight=lambda: FakeQuanterWithAbsMax(
                bit_length=weight_bits, moving_rate=0.0))
        self._qat = QAT(cfg)

    def quantize(self, model):
        return self._qat.quantize(model, inplace=True)

    def save_quantized_model(self, layer, path, input_spec=None, **config):
        from ..jit import save as jit_save
        jit_save(layer, path, input_spec=input_spec)
