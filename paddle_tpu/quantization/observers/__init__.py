"""(parity: python/paddle/quantization/observers/)"""
from .. import AbsmaxObserver  # noqa: F401

__all__ = ["AbsmaxObserver"]
