"""hapi callbacks (parity: python/paddle/hapi/callbacks.py — Callback
base, CallbackList dispatch, ProgBarLogger, ModelCheckpoint, EarlyStopping,
LRScheduler)."""
from __future__ import annotations

import os
import time

import numpy as np

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "EarlyStopping", "LRScheduler", "ReduceLROnPlateau", "VisualDL", "WandbCallback",
]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = dict(params or {})

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks=None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def dispatch(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return dispatch
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Per-epoch stdout logging (parity: hapi ProgBarLogger, text mode)."""

    def __init__(self, log_freq: int = 1, verbose: int = 2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and step % self.log_freq == 0:
            msg = " - ".join(f"{k}: {_fmt(v)}"
                             for k, v in (logs or {}).items())
            print(f"  step {step}: {msg}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            msg = " - ".join(f"{k}: {_fmt(v)}"
                             for k, v in (logs or {}).items())
            print(f"  epoch {epoch + 1} done in "
                  f"{time.time() - self._t0:.1f}s - {msg}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            msg = " - ".join(f"{k}: {_fmt(v)}"
                             for k, v in (logs or {}).items())
            print(f"  eval - {msg}")


def _fmt(v):
    if isinstance(v, (list, tuple)):
        return "[" + ", ".join(_fmt(x) for x in v) + "]"
    if isinstance(v, float):
        return f"{v:.4f}"
    return str(v)


class ModelCheckpoint(Callback):
    """Save model+optimizer every ``save_freq`` epochs (parity: hapi
    ModelCheckpoint)."""

    def __init__(self, save_freq: int = 1, save_dir: str = "checkpoint"):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model is not None and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.model is not None:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    """Stop when a monitored metric stops improving (parity: hapi
    EarlyStopping)."""

    def __init__(self, monitor="loss", mode="auto", patience=0,
                 verbose=1, min_delta=0, baseline=None,
                 save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "min" if ("loss" in monitor or "err" in monitor) else "max"
        self.mode = mode
        self.stopped_epoch = 0
        self.stop_training = False

    def on_train_begin(self, logs=None):
        self.wait_epoch = 0
        self.best_value = self.baseline if self.baseline is not None else (
            np.inf if self.mode == "min" else -np.inf)

    def on_eval_end(self, logs=None):
        value = (logs or {}).get(self.monitor)
        if value is None:
            return
        if isinstance(value, (list, tuple, np.ndarray)):
            value = float(np.asarray(value).reshape(-1)[0])
        improved = (value < self.best_value - self.min_delta
                    if self.mode == "min"
                    else value > self.best_value + self.min_delta)
        if improved:
            self.best_value = value
            self.wait_epoch = 0
            save_dir = self.params.get("save_dir")
            if self.save_best_model and save_dir and self.model is not None:
                self.model.save(os.path.join(save_dir, "best_model"))
        else:
            self.wait_epoch += 1
        if self.wait_epoch > self.patience:
            self.stop_training = True
            if self.model is not None:
                self.model.stop_training = True
            if self.verbose:
                print(f"EarlyStopping: no {self.monitor} improvement "
                      f"for {self.wait_epoch} evals, stopping")


class LRScheduler(Callback):
    """Step the optimizer's LR scheduler (parity: hapi LRScheduler)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        assert by_step != by_epoch, "exactly one of by_step/by_epoch"
        self.by_step = by_step

    def _step(self):
        opt = getattr(self.model, "_optimizer", None)
        sched = getattr(opt, "_lr", None) if opt else None
        if sched is not None and hasattr(sched, "step"):
            sched.step()

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            self._step()

    def on_epoch_end(self, epoch, logs=None):
        if not self.by_step:
            self._step()


class ReduceLROnPlateau(Callback):
    """Reduce LR when a monitored metric plateaus (parity:
    paddle.callbacks.ReduceLROnPlateau)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10,
                 verbose=1, mode="auto", min_delta=1e-4, cooldown=0,
                 min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.mode = mode
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        self._wait = 0
        self._cooldown_counter = 0
        self._best = None

    def _is_improvement(self, current):
        if self._best is None:
            return True
        if self.mode == "max" or (self.mode == "auto"
                                  and "acc" in self.monitor):
            return current > self._best + self.min_delta
        return current < self._best - self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        current = logs.get(self.monitor)
        if current is None:
            return
        current = float(current[0] if isinstance(
            current, (list, tuple)) else current)
        if self._cooldown_counter > 0:
            self._cooldown_counter -= 1
            self._wait = 0
            if self._is_improvement(current):
                self._best = current
            return
        if self._is_improvement(current):
            self._best = current
            self._wait = 0
            return
        self._wait += 1
        if self._wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is not None:
                lr = opt.get_lr() if hasattr(opt, "get_lr") else None
                if lr is not None:
                    new_lr = max(lr * self.factor, self.min_lr)
                    if hasattr(opt, "set_lr"):
                        opt.set_lr(new_lr)
                    if self.verbose:
                        print(f"ReduceLROnPlateau: lr {lr:.2e} -> "
                              f"{new_lr:.2e}")
            self._cooldown_counter = self.cooldown
            self._wait = 0


class VisualDL(Callback):
    """Scalar logger (parity: paddle.callbacks.VisualDL — the reference
    writes VisualDL event files; this build appends JSONL scalars the
    same dashboard semantics can consume)."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir
        self._step = 0

    def _write(self, tag, logs):
        import json as _json
        import os as _os
        _os.makedirs(self.log_dir, exist_ok=True)
        path = _os.path.join(self.log_dir, "scalars.jsonl")
        record = {"step": self._step, "tag": tag}
        for k, v in (logs or {}).items():
            try:
                record[k] = float(v[0] if isinstance(v, (list, tuple))
                                  else v)
            except (TypeError, ValueError):
                continue
        with open(path, "a") as f:
            f.write(_json.dumps(record) + "\n")

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        if self._step % 10 == 0:
            self._write("train", logs)

    def on_epoch_end(self, epoch, logs=None):
        self._write("train_epoch", logs)

    def on_eval_end(self, logs=None):
        self._write("eval", logs)


class WandbCallback(Callback):
    """Weights&Biases logger (parity: paddle.callbacks.WandbCallback).
    The wandb package is not in-image; construction requires it and
    raises with a clear message otherwise."""

    def __init__(self, project=None, **kwargs):
        super().__init__()
        try:
            import wandb
        except ImportError as e:
            raise ImportError(
                "WandbCallback requires the wandb package, which is not "
                "installed in this environment") from e
        self._wandb = wandb
        self._run = wandb.init(project=project, **kwargs)
        self._step = 0

    def _log(self, logs):
        record = {}
        for k, v in (logs or {}).items():
            try:
                record[k] = float(v[0] if isinstance(v, (list, tuple))
                                  else v)
            except (TypeError, ValueError):
                continue
        if record:
            self._wandb.log(record, step=self._step)

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        if self._step % 10 == 0:
            self._log(logs)

    def on_epoch_end(self, epoch, logs=None):
        self._log(logs)

    def on_eval_end(self, logs=None):
        self._log({f"eval_{k}": v for k, v in (logs or {}).items()})

    def on_train_end(self, logs=None):
        if self._run is not None:
            self._run.finish()
