"""hapi callbacks (parity: python/paddle/hapi/callbacks.py — Callback
base, CallbackList dispatch, ProgBarLogger, ModelCheckpoint, EarlyStopping,
LRScheduler)."""
from __future__ import annotations

import os
import time

import numpy as np

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "EarlyStopping", "LRScheduler"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = dict(params or {})

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks=None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def dispatch(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return dispatch
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Per-epoch stdout logging (parity: hapi ProgBarLogger, text mode)."""

    def __init__(self, log_freq: int = 1, verbose: int = 2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and step % self.log_freq == 0:
            msg = " - ".join(f"{k}: {_fmt(v)}"
                             for k, v in (logs or {}).items())
            print(f"  step {step}: {msg}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            msg = " - ".join(f"{k}: {_fmt(v)}"
                             for k, v in (logs or {}).items())
            print(f"  epoch {epoch + 1} done in "
                  f"{time.time() - self._t0:.1f}s - {msg}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            msg = " - ".join(f"{k}: {_fmt(v)}"
                             for k, v in (logs or {}).items())
            print(f"  eval - {msg}")


def _fmt(v):
    if isinstance(v, (list, tuple)):
        return "[" + ", ".join(_fmt(x) for x in v) + "]"
    if isinstance(v, float):
        return f"{v:.4f}"
    return str(v)


class ModelCheckpoint(Callback):
    """Save model+optimizer every ``save_freq`` epochs (parity: hapi
    ModelCheckpoint)."""

    def __init__(self, save_freq: int = 1, save_dir: str = "checkpoint"):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model is not None and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.model is not None:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    """Stop when a monitored metric stops improving (parity: hapi
    EarlyStopping)."""

    def __init__(self, monitor="loss", mode="auto", patience=0,
                 verbose=1, min_delta=0, baseline=None,
                 save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "min" if ("loss" in monitor or "err" in monitor) else "max"
        self.mode = mode
        self.stopped_epoch = 0
        self.stop_training = False

    def on_train_begin(self, logs=None):
        self.wait_epoch = 0
        self.best_value = self.baseline if self.baseline is not None else (
            np.inf if self.mode == "min" else -np.inf)

    def on_eval_end(self, logs=None):
        value = (logs or {}).get(self.monitor)
        if value is None:
            return
        if isinstance(value, (list, tuple, np.ndarray)):
            value = float(np.asarray(value).reshape(-1)[0])
        improved = (value < self.best_value - self.min_delta
                    if self.mode == "min"
                    else value > self.best_value + self.min_delta)
        if improved:
            self.best_value = value
            self.wait_epoch = 0
            save_dir = self.params.get("save_dir")
            if self.save_best_model and save_dir and self.model is not None:
                self.model.save(os.path.join(save_dir, "best_model"))
        else:
            self.wait_epoch += 1
        if self.wait_epoch > self.patience:
            self.stop_training = True
            if self.model is not None:
                self.model.stop_training = True
            if self.verbose:
                print(f"EarlyStopping: no {self.monitor} improvement "
                      f"for {self.wait_epoch} evals, stopping")


class LRScheduler(Callback):
    """Step the optimizer's LR scheduler (parity: hapi LRScheduler)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        assert by_step != by_epoch, "exactly one of by_step/by_epoch"
        self.by_step = by_step

    def _step(self):
        opt = getattr(self.model, "_optimizer", None)
        sched = getattr(opt, "_lr", None) if opt else None
        if sched is not None and hasattr(sched, "step"):
            sched.step()

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            self._step()

    def on_epoch_end(self, epoch, logs=None):
        if not self.by_step:
            self._step()
