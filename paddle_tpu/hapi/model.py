"""hapi high-level Model API (parity: python/paddle/hapi/model.py —
Model.fit:1054, evaluate:294-ish, predict:780, train_batch, save/load).

TPU-native: there is one execution mode — eager ops trace into XLA per op;
the reference's Dynamic/Static adapter split is unnecessary. The training
loop is plain Python over DataLoader batches.
"""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..framework import load as _load
from ..framework import save as _save
from ..io import DataLoader, Dataset
from ..metric import Metric
from .callbacks import Callback, CallbackList, ProgBarLogger

__all__ = ["Model"]


def _as_tuple(x):
    if isinstance(x, (list, tuple)):
        return tuple(x)
    return (x,)


def _to_tensors(xs):
    return tuple(x if isinstance(x, Tensor) else Tensor(np.asarray(x))
                 for x in _as_tuple(xs))


def _update_metric(m, outputs, labels):
    """compute() may return a tuple (base passthrough) or a single
    pre-processed array (e.g. Accuracy's correct matrix) — only a tuple is
    star-unpacked into update()."""
    res = m.compute(*(_as_tuple(outputs) + _as_tuple(labels)))
    if isinstance(res, tuple):
        m.update(*res)
    else:
        m.update(res)
    return m.accumulate()


class Model:
    """High-level train/eval/predict wrapper over an ``nn.Layer``."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self.stop_training = False

    # -- setup -------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        del amp_configs  # bf16-first: no loss scaling needed on TPU
        self._optimizer = optimizer
        if loss is not None and not callable(loss):
            raise TypeError("loss should be callable (a loss Layer or fn)")
        self._loss = loss
        metrics = metrics or []
        for m in _as_tuple(metrics) if metrics else ():
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m!r} is not a paddle.metric.Metric")
        self._metrics = list(_as_tuple(metrics)) if metrics else []
        return self

    # -- single-batch ops (parity: train_batch/eval_batch/predict_batch) ---
    def train_batch(self, inputs, labels=None, update=True,
                    loss_scale=1.0):
        assert self._optimizer is not None, "call prepare() first"
        self.network.train()
        inputs = _to_tensors(inputs)
        outputs = self.network(*inputs)
        metrics_out = []
        if self._loss is not None and labels is not None:
            labels = _to_tensors(labels)
            loss = self._loss(*(_as_tuple(outputs) + labels))
        else:
            loss = outputs if isinstance(outputs, Tensor) else outputs[0]
        (loss * loss_scale if loss_scale != 1.0 else loss).backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        for m in self._metrics:
            if labels is not None:
                metrics_out.append(_update_metric(m, outputs, labels))
        out = [float(loss)]
        return (out + metrics_out) if metrics_out else out

    def eval_batch(self, inputs, labels=None):
        from ..core.autograd import no_grad
        self.network.eval()
        inputs = _to_tensors(inputs)
        with no_grad():
            outputs = self.network(*inputs)
            metrics_out = []
            if self._loss is not None and labels is not None:
                labels = _to_tensors(labels)
                loss = float(self._loss(*(_as_tuple(outputs) + labels)))
            else:
                loss = None
            for m in self._metrics:
                if labels is not None:
                    metrics_out.append(
                        _update_metric(m, outputs, _to_tensors(labels)))
        out = [loss] if loss is not None else []
        return out + metrics_out

    def predict_batch(self, inputs):
        from ..core.autograd import no_grad
        self.network.eval()
        with no_grad():
            out = self.network(*_to_tensors(inputs))
        return out

    # -- loops -------------------------------------------------------------
    def _loader(self, data, batch_size, shuffle):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle)
        raise TypeError(f"expected Dataset or DataLoader, got {type(data)}")

    @staticmethod
    def _split_batch(batch):
        """DataLoader yields (input..., label): split on the loss arity
        convention — last element is the label when a loss is prepared."""
        batch = _as_tuple(batch)
        if len(batch) == 1:
            return batch[0], None
        return batch[:-1], batch[-1]

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, shuffle=True, callbacks=None, accumulate_grad_batches=1):
        """Train over epochs (parity: hapi Model.fit:1054)."""
        loader = self._loader(train_data, batch_size, shuffle)
        eval_loader = self._loader(eval_data, batch_size, False)
        cbks = CallbackList([ProgBarLogger(log_freq, verbose)]
                            + list(callbacks or []))
        if save_dir:
            from .callbacks import ModelCheckpoint
            cbks.append(ModelCheckpoint(save_freq, save_dir))
        cbks.set_model(self)
        cbks.set_params({"epochs": epochs, "verbose": verbose,
                         "save_dir": save_dir,
                         "metrics": ["loss"] + [m.name()
                                                for m in self._metrics]})
        self.stop_training = False
        cbks.on_train_begin()
        history = {"loss": []}
        epoch_logs = {}
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            epoch_logs = {}
            batch_losses = []
            pending_accum = False
            scale = 1.0 / accumulate_grad_batches
            for step, batch in enumerate(loader):
                cbks.on_train_batch_begin(step)
                x, y = self._split_batch(batch)
                update = (step + 1) % accumulate_grad_batches == 0
                res = self.train_batch(x, y, update=update,
                                       loss_scale=scale)
                pending_accum = not update
                batch_losses.append(res[0])
                epoch_logs = {"loss": res[0]}
                for m, v in zip(self._metrics, res[1:]):
                    epoch_logs[m.name() if isinstance(m.name(), str)
                               else m.name()[0]] = v
                cbks.on_train_batch_end(step, epoch_logs)
            if pending_accum:  # flush the tail accumulation window
                self._optimizer.step()
                self._optimizer.clear_grad()
            if batch_losses:  # epoch summary: mean loss, not last batch
                epoch_logs["loss"] = float(np.mean(batch_losses))
            history["loss"].append(epoch_logs.get("loss"))
            cbks.on_epoch_end(epoch, epoch_logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self._run_eval(eval_loader, cbks)
                for k, v in eval_logs.items():
                    history.setdefault(f"eval_{k}", []).append(v)
            if self.stop_training:
                break
        cbks.on_train_end(epoch_logs)
        return history

    def _run_eval(self, loader, cbks):
        cbks.on_eval_begin()
        for m in self._metrics:
            m.reset()
        losses = []
        logs = {}
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            x, y = self._split_batch(batch)
            res = self.eval_batch(x, y)
            if res and res[0] is not None:
                losses.append(res[0])
            cbks.on_eval_batch_end(step, logs)
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            name = m.name() if isinstance(m.name(), str) else m.name()[0]
            logs[name] = m.accumulate()
        cbks.on_eval_end(logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 callbacks=None):
        loader = self._loader(eval_data, batch_size, False)
        cbks = CallbackList([ProgBarLogger(log_freq, verbose)]
                            + list(callbacks or []))
        cbks.set_model(self)
        return self._run_eval(loader, cbks)

    def predict(self, test_data, batch_size=1, stack_outputs=True,
                verbose=1, callbacks=None):
        loader = self._loader(test_data, batch_size, False)
        cbks = CallbackList(list(callbacks or []))
        cbks.set_model(self)
        cbks.on_predict_begin()
        outs = []
        n_inputs = len(_as_tuple(self._inputs)) if self._inputs else None
        for step, batch in enumerate(loader):
            cbks.on_predict_batch_begin(step)
            batch = _as_tuple(batch)
            if n_inputs is not None:
                batch = batch[:n_inputs]  # declared input arity wins
            elif (self._loss is not None or self._metrics) \
                    and len(batch) > 1:
                batch, _ = self._split_batch(batch)  # drop labels
            out = self.predict_batch(batch)
            outs.append([o.numpy() for o in _as_tuple(out)])
            cbks.on_predict_batch_end(step)
        cbks.on_predict_end()
        n_out = len(outs[0]) if outs else 0
        grouped = [[b[i] for b in outs] for i in range(n_out)]
        if stack_outputs:
            grouped = [np.concatenate(g, axis=0) for g in grouped]
        return grouped

    # -- persistence --------------------------------------------------------
    def save(self, path, training=True):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = _load(path + ".pdparams")
        missing, unexpected = self.network.set_state_dict(state)
        if not skip_mismatch:
            if unexpected:
                raise ValueError(
                    f"unexpected keys in checkpoint: {unexpected}")
            if missing:
                raise ValueError(
                    f"keys missing from checkpoint: {missing}")
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None \
                and os.path.exists(opt_path):
            self._optimizer.set_state_dict(_load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        n_params = sum(int(np.prod(p.shape))
                       for p in self.network.parameters())
        lines = [f"{type(self.network).__name__}: "
                 f"{n_params:,} parameters"]
        for name, sub in self.network.named_sublayers():
            sub_n = sum(int(np.prod(p.shape))
                        for p in sub.parameters(include_sublayers=False))
            if sub_n:
                lines.append(f"  {name} ({type(sub).__name__}): {sub_n:,}")
        text = "\n".join(lines)
        print(text)
        return {"total_params": n_params, "text": text}
