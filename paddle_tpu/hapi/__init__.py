"""High-level Model API (parity: python/paddle/hapi/)."""
from . import callbacks  # noqa: F401
from .callbacks import (Callback, EarlyStopping, LRScheduler,  # noqa: F401
                        ModelCheckpoint, ProgBarLogger)
from .model import Model  # noqa: F401
