"""High-level Model API (parity: python/paddle/hapi/)."""
from . import callbacks  # noqa: F401
from .callbacks import (Callback, EarlyStopping, LRScheduler,  # noqa: F401
                        ModelCheckpoint, ProgBarLogger,
                        ReduceLROnPlateau, VisualDL, WandbCallback)
from .model import Model  # noqa: F401


def summary(net, input_size=None, dtypes=None, input=None):
    """Print a per-layer parameter summary (parity: paddle.summary,
    python/paddle/hapi/model_summary.py)."""
    import numpy as np
    n_params = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)
    lines = [f"{type(net).__name__}"]
    for name, sub in net.named_sublayers():
        sub_n = sum(int(np.prod(p.shape))
                    for p in sub.parameters(include_sublayers=False))
        if sub_n:
            lines.append(f"  {name} ({type(sub).__name__}): {sub_n:,}")
    lines.append(f"Total params: {n_params:,}")
    lines.append(f"Trainable params: {trainable:,}")
    lines.append(f"Non-trainable params: {n_params - trainable:,}")
    print("\n".join(lines))
    return {"total_params": n_params, "trainable_params": trainable}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Estimate forward FLOPs by jaxpr cost analysis (parity: paddle.flops,
    python/paddle/hapi/dynamic_flops.py — theirs hooks per-layer; XLA's
    cost analysis covers every op the layer lowers to)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    from ..core.autograd import tape_paused
    from ..nn.layer.layers import functional_state, _swapped_state

    shape = list(input_size)
    params = functional_state(net)

    def fwd(p, x):
        with _swapped_state(net, p):
            with tape_paused():
                out = net(Tensor(x))
        return out._data if isinstance(out, Tensor) else out

    x = jnp.zeros(shape, jnp.float32)
    try:
        lowered = jax.jit(fwd).lower(params, x)
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, list):
            cost = cost[0] if cost else {}
        total = int(cost.get("flops", 0))
    except Exception:
        total = 0
    if print_detail:
        print(f"Total FLOPs: {total:,}")
    return total
