"""Mixed-precision conversion of saved inference models (parity:
paddle/fluid/inference/api/analysis_passes' convert_to_mixed_precision —
python/paddle/inference/convert_to_mixed_precision wrapper).

TPU-native mechanism: the deployment artifact is a serialized StableHLO
program whose parameter inputs have baked dtypes, so the converter
RE-EXPORTS — it wraps the original program in a new traced function whose
parameter inputs are stored in the reduced dtype and cast back at the
boundary. XLA folds the casts into the consuming ops at compile time, so
the artifact's params (disk + HBM at load) halve while numerics follow the
original program. ``black_list`` keeps named parameters in f32 (the
reference's per-op black list keeps precision-sensitive ops in f32; here
precision sensitivity lives in the parameters feeding those ops)."""
from __future__ import annotations

import json
import os

import numpy as np

__all__ = ["convert_to_mixed_precision"]

_MODEL_SUFFIX = ".pdmodel"
_PARAMS_SUFFIX = ".pdiparams"
_META_SUFFIX = ".pdmeta.json"


def _strip(path: str) -> str:
    return path[:-len(_MODEL_SUFFIX)] if path.endswith(_MODEL_SUFFIX) \
        else path


def convert_to_mixed_precision(model_file: str, params_file: str,
                               mixed_model_file: str,
                               mixed_params_file: str,
                               mixed_precision: str = "bfloat16",
                               backend: str = "tpu",
                               keep_io_types: bool = True,
                               black_list=None):
    """Rewrite a jit.save artifact so its parameters are stored in
    ``mixed_precision`` ('bfloat16' | 'float16'). Returns the output
    prefix. ``keep_io_types`` is always true here (the wrapped program's
    activations keep their traced dtypes)."""
    import jax
    import jax.numpy as jnp

    del keep_io_types
    if mixed_precision in ("bfloat16", "bf16"):
        low = jnp.bfloat16
    elif mixed_precision in ("float16", "fp16", "half"):
        low = jnp.float16
    else:
        raise ValueError(
            f"convert_to_mixed_precision: unsupported precision "
            f"{mixed_precision!r} (use 'bfloat16' or 'float16')")
    black = set(black_list or ())

    src = _strip(model_file)
    dst = _strip(mixed_model_file)
    # the artifact layout is prefix-based (jit.save writes
    # prefix.pdmodel/.pdiparams/.pdmeta.json side by side): a params path
    # that disagrees with its model prefix cannot be honored — fail loud
    # rather than write somewhere the caller didn't ask for
    for label, want, prefix in (("params_file", params_file, src),
                                ("mixed_params_file", mixed_params_file,
                                 dst)):
        if want and os.path.normpath(want) != os.path.normpath(
                prefix + _PARAMS_SUFFIX):
            raise ValueError(
                f"convert_to_mixed_precision: {label}={want!r} does not "
                f"match the prefix layout ({prefix + _PARAMS_SUFFIX!r}); "
                "params live next to the model file")
    with open(src + _MODEL_SUFFIX, "rb") as f:
        exported = jax.export.deserialize(f.read())
    npz = np.load(src + _PARAMS_SUFFIX)
    state = {k: npz[k] for k in npz.files}
    meta = {}
    if os.path.exists(src + _META_SUFFIX):
        with open(src + _META_SUFFIX) as f:
            meta = json.load(f)

    def to_low(k, v):
        if k in black or not np.issubdtype(v.dtype, np.floating):
            return v
        return np.asarray(v, dtype=low)

    low_state = {k: to_low(k, v) for k, v in state.items()}
    orig_dtypes = {k: v.dtype for k, v in state.items()}

    # the re-export takes the key as raw uint32 bits (typed key dtypes
    # don't serialize — see jit.save); a pre-raw-format source program
    # still wants a typed key, so re-wrap at the boundary for those
    src_raw = meta.get("key_format") == "raw_uint32"

    def wrapped(low_params, raw_key, *args):
        full = {k: (v.astype(orig_dtypes[k])
                    if v.dtype != orig_dtypes[k] else v)
                for k, v in low_params.items()}
        key = raw_key if src_raw else jax.random.wrap_key_data(raw_key)
        return exported.call(full, key, *args)

    low_sds = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
               for k, v in low_state.items()}
    raw0 = jax.random.key_data(jax.random.key(0))
    key_sds = jax.ShapeDtypeStruct(raw0.shape, raw0.dtype)
    in_sds = [jax.ShapeDtypeStruct(tuple(m["shape"]), np.dtype(m["dtype"]))
              for m in meta.get("inputs", [])]
    if not in_sds:
        raise ValueError(
            f"{src + _META_SUFFIX}: missing input metadata; re-save the "
            "model with this framework's jit.save")
    re_exported = jax.export.export(jax.jit(wrapped))(low_sds, key_sds,
                                                      *in_sds)

    d = os.path.dirname(dst)
    if d:
        os.makedirs(d, exist_ok=True)
    # npz round-trips bfloat16 as opaque void16 — serialize it as uint16
    # bits and record the true dtype in the meta (jit.load views it back)
    param_dtypes = {}
    serial = {}
    for k, v in low_state.items():
        if v.dtype == np.dtype(low) and np.dtype(low) != np.dtype("float16"):
            param_dtypes[k] = str(np.dtype(low))
            serial[k] = v.view(np.uint16)
        else:
            serial[k] = v
    with open(dst + _MODEL_SUFFIX, "wb") as f:
        f.write(re_exported.serialize())
    with open(dst + _PARAMS_SUFFIX, "wb") as f:
        np.savez(f, **serial)
    with open(dst + _META_SUFFIX, "w") as f:
        json.dump(dict(meta, mixed_precision=str(np.dtype(low)),
                       black_list=sorted(black),
                       param_dtypes=param_dtypes,
                       key_format="raw_uint32"), f)
    return dst
