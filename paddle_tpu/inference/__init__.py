"""Inference Python API (parity: python/paddle/inference/ wrapping the
AnalysisPredictor, reference paddle/fluid/inference/api/analysis_predictor.cc).

TPU-native design: the deployment artifact is the StableHLO export that
``paddle.jit.save`` writes (SURVEY §7.1: "export path = StableHLO" — XLA
is the inference engine, so the reference's 90k-LoC analysis/TensorRT
stack has no role). ``Config`` points at the exported prefix;
``create_predictor`` loads it and compiles once per input signature;
handles copy numpy in/out like the reference's Tensor handles.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "PrecisionType",
           "convert_to_mixed_precision"]

from .convert import convert_to_mixed_precision  # noqa: E402,F401


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class PassStrategy:
    """Analysis-pass pipeline analog (reference AnalysisPredictor's
    Argument -> AnalysisPass chain, inference/api/analysis_predictor.cc +
    analysis/passes/). On the XLA substrate most of the reference's 121
    graph passes ARE the compiler (fusion, constant folding, layout,
    memory planning), so the pipeline here is short and every named pass
    maps to a real mechanism:

    - ``ir_graph_build_pass`` / ``ir_analysis_pass``: deserialize the
      StableHLO artifact and hand it to XLA — jit.load + compile (these
      markers exist so delete_pass/ordering semantics behave like the
      reference's builder).
    - ``convert_to_mixed_precision_pass``: cast stored params to the
      configured precision at load (inference/convert.py mechanism,
      applied in-memory).
    - ``memory_optimize_pass``: release host-side input staging buffers
      after each run (device buffer assignment itself is XLA's).
    """

    def __init__(self, passes):
        self._passes = list(passes)

    def all_passes(self):
        return list(self._passes)

    def delete_pass(self, name: str):
        self._passes = [p for p in self._passes if p != name]

    def append_pass(self, name: str):
        if name not in self._passes:
            self._passes.append(name)

    def insert_pass(self, idx: int, name: str):
        if name not in self._passes:
            self._passes.insert(idx, name)

    def __contains__(self, name: str):
        return name in self._passes


_DEFAULT_PASSES = ["ir_graph_build_pass", "ir_analysis_pass"]


class Config:
    """Parity: paddle.inference.Config(prog_file, params_file) — here one
    prefix, the path given to paddle.jit.save."""

    def __init__(self, model_path: Optional[str] = None,
                 params_path: Optional[str] = None):
        if model_path is not None and model_path.endswith(".pdmodel"):
            model_path = model_path[:-len(".pdmodel")]
        self.model_path = model_path
        self.params_path = params_path
        self._precision = PrecisionType.Float32
        self._memory_pool_mb = None
        self._pass_builder = PassStrategy(_DEFAULT_PASSES)

    def set_prog_file(self, path: str):
        self.model_path = path[:-len(".pdmodel")] \
            if path.endswith(".pdmodel") else path

    def prog_file(self):
        return self.model_path

    def pass_builder(self) -> PassStrategy:
        """Parity: config.pass_builder() — mutate the analysis pipeline
        (AppendPass/DeletePass, paddle_pass_builder.h)."""
        return self._pass_builder

    def delete_pass(self, name: str):
        self._pass_builder.delete_pass(name)

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        pass  # accelerator selection is the runtime's (libtpu) job

    def disable_gpu(self):
        pass

    def enable_mixed_precision(self, precision=PrecisionType.Bfloat16):
        """Store/load params in reduced precision (the in-memory form of
        convert_to_mixed_precision; analysis pass analog of
        convert_to_mixed_precision.cc)."""
        self._precision = precision
        self._pass_builder.append_pass("convert_to_mixed_precision_pass")

    def enable_memory_optim(self):
        # XLA owns device buffer assignment; the pass frees HOST staging
        # copies after each run (see PassStrategy docstring)
        self._pass_builder.append_pass("memory_optimize_pass")

    def switch_ir_optim(self, flag=True):
        pass  # XLA owns graph optimization

    def set_cpu_math_library_num_threads(self, n):
        pass

    def enable_tensorrt_engine(self, *a, **k):
        raise NotImplementedError(
            "TensorRT has no TPU analog; XLA compiles the exported "
            "StableHLO directly")


class _IOHandle:
    """Parity: the predictor's input/output Tensor handle."""

    def __init__(self, name: str, owner: "Predictor", is_input: bool):
        self.name = name
        self._owner = owner
        self._is_input = is_input

    def copy_from_cpu(self, arr: np.ndarray):
        assert self._is_input
        self._owner._inputs[self.name] = np.asarray(arr)

    def reshape(self, shape):
        pass  # shapes come from the copied array

    def copy_to_cpu(self) -> np.ndarray:
        assert not self._is_input
        return self._owner._outputs[self.name]

    def shape(self):
        src = self._owner._inputs if self._is_input else self._owner._outputs
        return list(src[self.name].shape)


class Predictor:
    def __init__(self, config: Config):
        from ..jit import load
        if config.model_path is None:
            raise ValueError("Config has no model path")
        # the analysis pipeline (PassStrategy): ir_graph_build/-analysis
        # ARE jit.load + XLA compile; the optional passes apply here
        self._layer = load(config.model_path)
        self._config = config
        passes = config.pass_builder()
        if "convert_to_mixed_precision_pass" in passes \
                and config._precision != PrecisionType.Float32:
            import ml_dtypes
            dt = {PrecisionType.Bfloat16: ml_dtypes.bfloat16,
                  PrecisionType.Half: np.float16}.get(config._precision)
            if dt is None:
                raise ValueError(
                    f"unsupported inference precision "
                    f"{config._precision!r}")
            self._layer.convert_params(dt)
        self._release_staging = "memory_optimize_pass" in passes
        n_in = len(self._layer.input_spec) or 1
        self._input_names = [f"x{i}" for i in range(n_in)]
        self._inputs: Dict[str, np.ndarray] = {}
        self._outputs: Dict[str, np.ndarray] = {}
        self._output_names: List[str] = []

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> _IOHandle:
        return _IOHandle(name, self, True)

    def get_output_names(self) -> List[str]:
        return list(self._output_names)

    def get_output_handle(self, name: str) -> _IOHandle:
        return _IOHandle(name, self, False)

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """Execute; positional ``inputs`` are accepted like the newer
        reference API, else the copy_from_cpu'd handles are used."""
        if inputs is not None:
            args = [np.asarray(a) for a in inputs]
        else:
            missing = [n for n in self._input_names
                       if n not in self._inputs]
            if missing:
                extra = (" (input staging was freed by "
                         "memory_optimize_pass after the previous run; "
                         "copy_from_cpu again or pass inputs positionally)"
                         if self._release_staging else "")
                raise RuntimeError(
                    f"Predictor.run: inputs {missing} not set{extra}")
            args = [self._inputs[n] for n in self._input_names]
        outs = self._layer(*args)
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        self._output_names = [f"out{i}" for i in range(len(outs))]
        self._outputs = {
            n: np.asarray(o.numpy() if hasattr(o, "numpy") else o)
            for n, o in zip(self._output_names, outs)}
        if self._release_staging:
            self._inputs.clear()   # memory_optimize_pass: free host copies
        if inputs is not None:
            return [self._outputs[n] for n in self._output_names]
        return True


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
