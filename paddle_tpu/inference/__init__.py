"""Inference Python API (parity: python/paddle/inference/ wrapping the
AnalysisPredictor, reference paddle/fluid/inference/api/analysis_predictor.cc).

TPU-native design: the deployment artifact is the StableHLO export that
``paddle.jit.save`` writes (SURVEY §7.1: "export path = StableHLO" — XLA
is the inference engine, so the reference's 90k-LoC analysis/TensorRT
stack has no role). ``Config`` points at the exported prefix;
``create_predictor`` loads it and compiles once per input signature;
handles copy numpy in/out like the reference's Tensor handles.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "PrecisionType",
           "convert_to_mixed_precision"]

from .convert import convert_to_mixed_precision  # noqa: E402,F401


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class Config:
    """Parity: paddle.inference.Config(prog_file, params_file) — here one
    prefix, the path given to paddle.jit.save."""

    def __init__(self, model_path: Optional[str] = None,
                 params_path: Optional[str] = None):
        if model_path is not None and model_path.endswith(".pdmodel"):
            model_path = model_path[:-len(".pdmodel")]
        self.model_path = model_path
        self.params_path = params_path
        self._precision = PrecisionType.Float32
        self._memory_pool_mb = None

    def set_prog_file(self, path: str):
        self.model_path = path[:-len(".pdmodel")] \
            if path.endswith(".pdmodel") else path

    def prog_file(self):
        return self.model_path

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        pass  # accelerator selection is the runtime's (libtpu) job

    def disable_gpu(self):
        pass

    def enable_memory_optim(self):
        pass  # XLA owns buffer assignment

    def switch_ir_optim(self, flag=True):
        pass  # XLA owns graph optimization

    def set_cpu_math_library_num_threads(self, n):
        pass

    def enable_tensorrt_engine(self, *a, **k):
        raise NotImplementedError(
            "TensorRT has no TPU analog; XLA compiles the exported "
            "StableHLO directly")


class _IOHandle:
    """Parity: the predictor's input/output Tensor handle."""

    def __init__(self, name: str, owner: "Predictor", is_input: bool):
        self.name = name
        self._owner = owner
        self._is_input = is_input

    def copy_from_cpu(self, arr: np.ndarray):
        assert self._is_input
        self._owner._inputs[self.name] = np.asarray(arr)

    def reshape(self, shape):
        pass  # shapes come from the copied array

    def copy_to_cpu(self) -> np.ndarray:
        assert not self._is_input
        return self._owner._outputs[self.name]

    def shape(self):
        src = self._owner._inputs if self._is_input else self._owner._outputs
        return list(src[self.name].shape)


class Predictor:
    def __init__(self, config: Config):
        from ..jit import load
        if config.model_path is None:
            raise ValueError("Config has no model path")
        self._layer = load(config.model_path)
        self._config = config
        n_in = len(self._layer.input_spec) or 1
        self._input_names = [f"x{i}" for i in range(n_in)]
        self._inputs: Dict[str, np.ndarray] = {}
        self._outputs: Dict[str, np.ndarray] = {}
        self._output_names: List[str] = []

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> _IOHandle:
        return _IOHandle(name, self, True)

    def get_output_names(self) -> List[str]:
        return list(self._output_names)

    def get_output_handle(self, name: str) -> _IOHandle:
        return _IOHandle(name, self, False)

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """Execute; positional ``inputs`` are accepted like the newer
        reference API, else the copy_from_cpu'd handles are used."""
        if inputs is not None:
            args = [np.asarray(a) for a in inputs]
        else:
            args = [self._inputs[n] for n in self._input_names]
        outs = self._layer(*args)
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        self._output_names = [f"out{i}" for i in range(len(outs))]
        self._outputs = {
            n: np.asarray(o.numpy() if hasattr(o, "numpy") else o)
            for n, o in zip(self._output_names, outs)}
        if inputs is not None:
            return [self._outputs[n] for n in self._output_names]
        return True


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
