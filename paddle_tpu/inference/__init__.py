"""Inference Python API (parity: python/paddle/inference/ wrapping the
AnalysisPredictor, reference paddle/fluid/inference/api/analysis_predictor.cc).

TPU-native design: the deployment artifact is the StableHLO export that
``paddle.jit.save`` writes (SURVEY §7.1: "export path = StableHLO" — XLA
is the inference engine, so the reference's 90k-LoC analysis/TensorRT
stack has no role). ``Config`` points at the exported prefix;
``create_predictor`` loads it and compiles once per input signature;
handles copy numpy in/out like the reference's Tensor handles.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "PrecisionType",
           "convert_to_mixed_precision"]

from .convert import convert_to_mixed_precision  # noqa: E402,F401


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class PassStrategy:
    """Analysis-pass pipeline analog (reference AnalysisPredictor's
    Argument -> AnalysisPass chain, inference/api/analysis_predictor.cc +
    analysis/passes/). On the XLA substrate most of the reference's 121
    graph passes ARE the compiler (fusion, constant folding, layout,
    memory planning), so the pipeline here is short and every named pass
    maps to a real mechanism:

    - ``ir_graph_build_pass`` / ``ir_analysis_pass``: deserialize the
      StableHLO artifact and hand it to XLA — jit.load + compile (these
      markers exist so delete_pass/ordering semantics behave like the
      reference's builder).
    - ``convert_to_mixed_precision_pass``: cast stored params to the
      configured precision at load (inference/convert.py mechanism,
      applied in-memory).
    - ``memory_optimize_pass``: release host-side input staging buffers
      after each run (device buffer assignment itself is XLA's).
    """

    def __init__(self, passes):
        self._passes = list(passes)

    def all_passes(self):
        return list(self._passes)

    def delete_pass(self, name: str):
        self._passes = [p for p in self._passes if p != name]

    def append_pass(self, name: str):
        if name not in self._passes:
            self._passes.append(name)

    def insert_pass(self, idx: int, name: str):
        if name not in self._passes:
            self._passes.insert(idx, name)

    def __contains__(self, name: str):
        return name in self._passes


_DEFAULT_PASSES = ["ir_graph_build_pass", "ir_analysis_pass"]


class Config:
    """Parity: paddle.inference.Config(prog_file, params_file) — here one
    prefix, the path given to paddle.jit.save."""

    def __init__(self, model_path: Optional[str] = None,
                 params_path: Optional[str] = None):
        if model_path is not None and model_path.endswith(".pdmodel"):
            model_path = model_path[:-len(".pdmodel")]
        self.model_path = model_path
        self.params_path = params_path
        self._precision = PrecisionType.Float32
        self._memory_pool_mb = None
        self._pass_builder = PassStrategy(_DEFAULT_PASSES)
        self._serving_opts = None

    def set_prog_file(self, path: str):
        self.model_path = path[:-len(".pdmodel")] \
            if path.endswith(".pdmodel") else path

    def prog_file(self):
        return self.model_path

    def pass_builder(self) -> PassStrategy:
        """Parity: config.pass_builder() — mutate the analysis pipeline
        (AppendPass/DeletePass, paddle_pass_builder.h)."""
        return self._pass_builder

    def delete_pass(self, name: str):
        self._pass_builder.delete_pass(name)

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        pass  # accelerator selection is the runtime's (libtpu) job

    def disable_gpu(self):
        pass

    def enable_mixed_precision(self, precision=PrecisionType.Bfloat16):
        """Store/load params in reduced precision (the in-memory form of
        convert_to_mixed_precision; analysis pass analog of
        convert_to_mixed_precision.cc)."""
        self._precision = precision
        self._pass_builder.append_pass("convert_to_mixed_precision_pass")

    def enable_memory_optim(self):
        # XLA owns device buffer assignment; the pass frees HOST staging
        # copies after each run (see PassStrategy docstring)
        self._pass_builder.append_pass("memory_optimize_pass")

    def switch_ir_optim(self, flag=True):
        pass  # XLA owns graph optimization

    def set_cpu_math_library_num_threads(self, n):
        pass

    def enable_tensorrt_engine(self, *a, **k):
        raise NotImplementedError(
            "TensorRT has no TPU analog; XLA compiles the exported "
            "StableHLO directly")

    def enable_serving(self, batch_timeout_ms: float = 2.0,
                       max_queue_size: int = 128,
                       default_deadline_ms: Optional[float] = None):
        """Attach a dynamic-batching server (paddle_tpu.serving) to the
        predictor: ``Predictor.submit()`` then coalesces concurrent
        single-example requests up to the exported program's batch dim,
        with a bounded queue (ServerOverloaded shedding) and optional
        per-request deadlines. The exported batch size is the one shape
        bucket, so serving adds zero extra XLA compiles."""
        self._serving_opts = {
            "batch_timeout_ms": batch_timeout_ms,
            "max_queue_size": max_queue_size,
            "default_deadline_ms": default_deadline_ms,
        }
        return self


class _IOHandle:
    """Parity: the predictor's input/output Tensor handle."""

    def __init__(self, name: str, owner: "Predictor", is_input: bool):
        self.name = name
        self._owner = owner
        self._is_input = is_input

    def copy_from_cpu(self, arr: np.ndarray):
        assert self._is_input
        self._owner._inputs[self.name] = np.asarray(arr)

    def reshape(self, shape):
        pass  # shapes come from the copied array

    def copy_to_cpu(self) -> np.ndarray:
        assert not self._is_input
        return self._owner._outputs[self.name]

    def shape(self):
        src = self._owner._inputs if self._is_input else self._owner._outputs
        return list(src[self.name].shape)


class Predictor:
    def __init__(self, config: Config):
        from ..jit import load
        if config.model_path is None:
            raise ValueError("Config has no model path")
        # the analysis pipeline (PassStrategy): ir_graph_build/-analysis
        # ARE jit.load + XLA compile; the optional passes apply here
        self._layer = load(config.model_path)
        self._config = config
        passes = config.pass_builder()
        if "convert_to_mixed_precision_pass" in passes \
                and config._precision != PrecisionType.Float32:
            import ml_dtypes
            dt = {PrecisionType.Bfloat16: ml_dtypes.bfloat16,
                  PrecisionType.Half: np.float16}.get(config._precision)
            if dt is None:
                raise ValueError(
                    f"unsupported inference precision "
                    f"{config._precision!r}")
            self._layer.convert_params(dt)
        self._release_staging = "memory_optimize_pass" in passes
        n_in = len(self._layer.input_spec) or 1
        self._input_names = [f"x{i}" for i in range(n_in)]
        self._inputs: Dict[str, np.ndarray] = {}
        self._outputs: Dict[str, np.ndarray] = {}
        self._output_names: List[str] = []
        self._server = None   # built lazily on first submit()
        self._serving_draining = None   # mid-shutdown, stats still live
        self._serving_final = None   # last shutdown's metrics snapshot
        import threading
        self._server_lock = threading.Lock()
        self._shutdown_lock = threading.Lock()   # serializes shutdowns

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> _IOHandle:
        return _IOHandle(name, self, True)

    def get_output_names(self) -> List[str]:
        return list(self._output_names)

    def get_output_handle(self, name: str) -> _IOHandle:
        return _IOHandle(name, self, False)

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """Execute; positional ``inputs`` are accepted like the newer
        reference API, else the copy_from_cpu'd handles are used."""
        if inputs is not None:
            args = [np.asarray(a) for a in inputs]
        else:
            missing = [n for n in self._input_names
                       if n not in self._inputs]
            if missing:
                extra = (" (input staging was freed by "
                         "memory_optimize_pass after the previous run; "
                         "copy_from_cpu again or pass inputs positionally)"
                         if self._release_staging else "")
                raise RuntimeError(
                    f"Predictor.run: inputs {missing} not set{extra}")
            args = [self._inputs[n] for n in self._input_names]
        outs = self._layer(*args)
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        self._output_names = [f"out{i}" for i in range(len(outs))]
        self._outputs = {
            n: np.asarray(o.numpy() if hasattr(o, "numpy") else o)
            for n, o in zip(self._output_names, outs)}
        if self._release_staging:
            self._inputs.clear()   # memory_optimize_pass: free host copies
        if inputs is not None:
            return [self._outputs[n] for n in self._output_names]
        return True

    # -- serving path (config.enable_serving()) ---------------------------
    def _serving_server(self):
        if self._config._serving_opts is None:
            raise RuntimeError(
                "serving is not enabled: call config.enable_serving() "
                "before create_predictor")
        with self._server_lock:   # first submits race in from N clients
            if self._server is None:
                from ..serving import Server
                self._server = Server(self._layer, name=None,
                                      **self._config._serving_opts)
            return self._server

    def submit(self, inputs: List[np.ndarray],
               deadline_ms: Optional[float] = None):
        """Dynamic-batching entry: each element of ``inputs`` is ONE
        example WITHOUT the batch dim (the exported program's leading
        dim); concurrent submits coalesce into one padded execute.
        Returns a serving Future; ``.result(timeout)`` yields the
        per-request output rows."""
        srv = self._serving_server()
        return srv.submit(*inputs, deadline_ms=deadline_ms)

    def serving_stats(self) -> dict:
        """Metrics snapshot of the attached server (also via
        ``paddle_tpu.profiler.serving_stats()``). Read-only: never
        constructs a server — after shutdown_serving() it returns the
        final snapshot; before any submit() it raises."""
        with self._server_lock:
            # a server mid-shutdown still answers stats: monitoring must
            # not see "no serving activity" during the drain window
            srv = self._server or self._serving_draining
            if srv is not None:
                return srv.stats()
            if self._serving_final is not None:
                return self._serving_final
        raise RuntimeError(
            "no serving activity yet: serving_stats() is available after "
            "the first submit() (and returns the final snapshot after "
            "shutdown_serving())")

    def shutdown_serving(self, drain: bool = True) -> Optional[dict]:
        """Stop the attached server (draining queued work by default).
        Returns the final metrics snapshot, or None if serving was never
        used. A later submit() starts a fresh server. Racing shutdowns
        serialize: the loser waits out the drain and gets the same final
        snapshot instead of a stale/None one."""
        with self._shutdown_lock:
            with self._server_lock:
                server, self._server = self._server, None
                if server is not None:
                    self._serving_draining = server
            if server is None:
                return self._serving_final
            server.shutdown(drain=drain)
            with self._server_lock:
                self._serving_final = server.stats()
                self._serving_draining = None
                return self._serving_final


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
