"""paddle.linalg namespace (parity: python/paddle/linalg.py — a re-export
of the tensor linear-algebra surface under a dedicated module)."""
from __future__ import annotations

from .tensor.linalg import (  # noqa: F401
    cholesky, cholesky_solve, cond, corrcoef, cov, det, eig, eigh, eigvals,
    eigvalsh, householder_product, inv, lstsq, lu, lu_unpack, matrix_exp,
    matrix_norm, matrix_power, matrix_rank, multi_dot, norm, pca_lowrank,
    pinv, qr, slogdet, solve, svd, triangular_solve, vector_norm)

__all__ = [
    "cholesky", "norm", "cond", "cov", "corrcoef", "inv", "eig", "eigvals",
    "multi_dot", "matrix_rank", "svd", "qr", "householder_product",
    "pca_lowrank", "lu", "lu_unpack", "matrix_exp", "matrix_power", "det",
    "slogdet", "eigh", "eigvalsh", "pinv", "solve", "cholesky_solve",
    "triangular_solve", "lstsq", "matrix_norm", "vector_norm",
]
