"""paddle.static.nn (parity: python/paddle/static/nn/__init__.py — the
static-graph layer builders: each call creates parameters eagerly and
records the forward ops into the current Program via the dispatch funnel's
static-mode branch; the reference's LayerHelper.append_op equivalent).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "fc", "batch_norm", "bilinear_tensor_product", "embedding", "case",
    "cond", "static_pylayer", "conv2d", "conv2d_transpose", "conv3d",
    "conv3d_transpose", "data_norm", "deform_conv2d", "group_norm",
    "instance_norm", "layer_norm", "nce", "prelu", "py_func", "row_conv",
    "spectral_norm", "switch_case", "while_loop", "sparse_embedding",
    "sequence_conv", "sequence_softmax", "sequence_pool",
    "sequence_concat", "sequence_first_step", "sequence_last_step",
    "sequence_slice", "sequence_expand", "sequence_expand_as",
    "sequence_pad", "sequence_unpad", "sequence_reshape",
    "sequence_scatter", "sequence_enumerate", "sequence_reverse",
]


def _shape_of(x):
    return [1 if s is None else s for s in x.shape]


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """(parity: static.nn.fc — flattens trailing dims, xW+b, activation)"""
    from .. import nn
    from ..nn import functional as F
    in_f = int(np.prod(_shape_of(x)[num_flatten_dims:]))
    layer = nn.Linear(in_f, size, weight_attr=weight_attr,
                      bias_attr=bias_attr)
    h = x
    if len(x.shape) > num_flatten_dims + 1:
        from ..tensor.manipulation import reshape
        h = reshape(h, _shape_of(x)[:num_flatten_dims] + [in_f])
    out = layer(h)
    if activation:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """(parity: static.nn.embedding)"""
    from .. import nn
    layer = nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                         sparse=is_sparse, weight_attr=param_attr)
    return layer(input)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """(parity: static.nn.sparse_embedding — the PS sparse table variant;
    dense embedding on this substrate)"""
    return embedding(input, size, is_sparse=True, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    """(parity: static.nn.batch_norm)"""
    from .. import nn
    from ..nn import functional as F
    c = _shape_of(input)[1 if data_layout == "NCHW" else -1]
    layer = nn.BatchNorm2D(c, momentum=momentum, epsilon=epsilon,
                           weight_attr=param_attr, bias_attr=bias_attr,
                           data_format=data_layout)
    if is_test or use_global_stats:
        layer.eval()
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              enable_scale_and_shift=False, name=None, moving_mean_name=None,
              moving_variance_name=None, do_model_average_for_mean_and_var=True,
              slot_dim=-1, summary_decay_rate=0.9999999, sync_stats=False,
              scale_w=None, bias=None):
    """(parity: static.nn.data_norm — normalization by accumulated
    batch statistics; stateless normalized form here)"""
    from ..core.dispatch import run_op

    def fn(a):
        mean = jnp.mean(a, axis=0, keepdims=True)
        var = jnp.var(a, axis=0, keepdims=True)
        return (a - mean) / jnp.sqrt(var + epsilon)
    return run_op("data_norm", fn, (input,))


def _conv_layer(cls, input, num_filters, filter_size, stride, padding,
                dilation, groups, param_attr, bias_attr, data_format, act):
    from ..nn import functional as F
    c_axis = 1 if data_format.startswith("NC") else -1
    in_c = _shape_of(input)[c_axis]
    layer = cls(in_c, num_filters, filter_size, stride=stride,
                padding=padding, dilation=dilation, groups=groups or 1,
                weight_attr=param_attr, bias_attr=bias_attr,
                data_format=data_format)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=None, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCHW"):
    from .. import nn
    return _conv_layer(nn.Conv2D, input, num_filters, filter_size, stride,
                       padding, dilation, groups, param_attr, bias_attr,
                       data_format, act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=None, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCDHW"):
    from .. import nn
    return _conv_layer(nn.Conv3D, input, num_filters, filter_size, stride,
                       padding, dilation, groups, param_attr, bias_attr,
                       data_format, act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    from .. import nn
    return _conv_layer(nn.Conv2DTranspose, input, num_filters,
                       filter_size, stride, padding, dilation, groups,
                       param_attr, bias_attr, data_format, act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    from .. import nn
    return _conv_layer(nn.Conv3DTranspose, input, num_filters,
                       filter_size, stride, padding, dilation, groups,
                       param_attr, bias_attr, data_format, act)


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, weight_attr=None, bias_attr=None,
                  name=None):
    """(parity: static.nn.deform_conv2d over the vision op)"""
    from ..nn.parameter import create_parameter
    from ..vision.ops import deform_conv2d as _dc
    ks = (filter_size, filter_size) if isinstance(filter_size, int) \
        else tuple(filter_size)
    in_c = _shape_of(x)[1]
    weight = create_parameter([num_filters, in_c // groups, *ks],
                              "float32", attr=weight_attr)
    bias = None if bias_attr is False else create_parameter(
        [num_filters], "float32", attr=bias_attr, is_bias=True)
    return _dc(x, offset, weight, bias, stride, padding, dilation,
               deformable_groups, groups, mask)


def group_norm(input, groups, epsilon=1e-05, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    from .. import nn
    from ..nn import functional as F
    c = _shape_of(input)[1 if data_layout == "NCHW" else -1]
    layer = nn.GroupNorm(groups, c, epsilon=epsilon,
                         weight_attr=param_attr, bias_attr=bias_attr)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def instance_norm(input, epsilon=1e-05, param_attr=None, bias_attr=None,
                  name=None):
    from .. import nn
    c = _shape_of(input)[1]
    layer = nn.InstanceNorm2D(c, epsilon=epsilon, weight_attr=param_attr,
                              bias_attr=bias_attr)
    return layer(input)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-05, param_attr=None, bias_attr=None, act=None,
               name=None):
    from .. import nn
    from ..nn import functional as F
    shape = _shape_of(input)[begin_norm_axis:]
    layer = nn.LayerNorm(shape, epsilon=epsilon,
                         weight_attr=param_attr if scale else False,
                         bias_attr=bias_attr if shift else False)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    from .. import nn
    from ..nn import functional as F
    layer = nn.Bilinear(_shape_of(x)[-1], _shape_of(y)[-1], size,
                        weight_attr=param_attr, bias_attr=bias_attr)
    out = layer(x, y)
    if act:
        out = getattr(F, act)(out)
    return out


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    from .. import nn
    if mode == "all":
        num = 1
    elif mode == "channel":
        num = _shape_of(x)[1 if data_format == "NCHW" else -1]
    else:
        num = int(np.prod(_shape_of(x)[1:]))
    layer = nn.PReLU(num_parameters=num, weight_attr=param_attr,
                     data_format=data_format)
    return layer(x)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (parity: static.nn.nce). Uniform
    negative sampling; logistic discrimination of true vs noise classes."""
    from ..core.dispatch import run_op
    from ..nn.parameter import create_parameter
    dim = _shape_of(input)[-1]
    weight = create_parameter([num_total_classes, dim], "float32",
                              attr=param_attr)
    bias = None if bias_attr is False else create_parameter(
        [num_total_classes], "float32", attr=bias_attr, is_bias=True)
    k = num_neg_samples or 10
    neg = np.random.RandomState(seed or 0).randint(
        0, num_total_classes, size=(k,))

    def fn(x_, lab, w, *bb):
        lab_i = lab.astype(jnp.int32).reshape(-1)
        pos_logit = jnp.sum(x_ * w[lab_i], axis=-1)
        if bb:
            pos_logit = pos_logit + bb[0][lab_i]
        neg_w = w[neg]                       # (k, dim)
        neg_logit = x_ @ neg_w.T             # (B, k)
        if bb:
            neg_logit = neg_logit + bb[0][neg]
        loss = -jax.nn.log_sigmoid(pos_logit) \
            - jnp.sum(jax.nn.log_sigmoid(-neg_logit), axis=-1)
        return loss[:, None]
    ops = (input, label, weight) + ((bias,) if bias is not None else ())
    return run_op("nce", fn, ops)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (parity: static.nn.row_conv)."""
    from ..core.dispatch import run_op
    from ..nn.parameter import create_parameter
    d = _shape_of(input)[-1]
    w = create_parameter([future_context_size + 1, d], "float32",
                         attr=param_attr)

    def fn(a, wt):
        # a: (B, T, D); out[t] = sum_{i=0..C} a[t+i] * w[i]
        T = a.shape[-2]
        out = jnp.zeros_like(a)
        for i in range(future_context_size + 1):
            pad = [(0, 0)] * (a.ndim - 2) + [(0, i), (0, 0)]
            sl = jnp.pad(a[..., i:, :], pad)
            out = out + sl * wt[i]
        return out
    return run_op("row_conv", fn, (input, w))


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    from .. import nn
    layer = nn.SpectralNorm(_shape_of(weight), dim=dim,
                            power_iters=power_iters, eps=eps)
    return layer(weight)


# -- control flow ----------------------------------------------------------

def cond(pred, true_fn=None, false_fn=None, name=None,
         return_names=None):
    """(parity: static.nn.cond). With a concrete predicate (eager) this
    picks the branch; under tracing it lowers to jax.lax.cond when both
    branches return matching structures."""
    from ..core.tensor import Tensor
    p = pred._data if isinstance(pred, Tensor) else pred
    if isinstance(p, jax.core.Tracer):
        return jax.lax.cond(p.reshape(()), lambda _: true_fn(),
                            lambda _: false_fn(), operand=None)
    if bool(np.asarray(p)):
        return true_fn() if true_fn else None
    return false_fn() if false_fn else None


def case(pred_fn_pairs, default=None, name=None):
    """(parity: static.nn.case)"""
    from ..core.tensor import Tensor
    for pred, fn in pred_fn_pairs:
        p = pred._data if isinstance(pred, Tensor) else pred
        if bool(np.asarray(p)):
            return fn()
    if default is not None:
        return default()
    return pred_fn_pairs[-1][1]()


def switch_case(branch_index, branch_fns, default=None, name=None):
    """(parity: static.nn.switch_case)"""
    from ..core.tensor import Tensor
    idx = branch_index._data if isinstance(branch_index, Tensor) \
        else branch_index
    idx = int(np.asarray(idx))
    fns = dict(branch_fns) if not isinstance(branch_fns, dict) \
        else branch_fns
    if idx in fns:
        return fns[idx]()
    if default is not None:
        return default()
    raise ValueError(f"branch {idx} not found and no default")


def while_loop(cond_fn, body, loop_vars, is_test=False, name=None):
    """(parity: static.nn.while_loop). Concrete condition: Python loop
    (dygraph semantics); traced: jax.lax.while_loop."""
    from ..core.tensor import Tensor

    def concrete(v):
        return not isinstance(v._data if isinstance(v, Tensor) else v,
                              jax.core.Tracer)
    if all(concrete(v) for v in loop_vars):
        vars_ = list(loop_vars)
        while bool(np.asarray(
                cond_fn(*vars_)._data if isinstance(cond_fn(*vars_), Tensor)
                else cond_fn(*vars_))):
            out = body(*vars_)
            vars_ = list(out) if isinstance(out, (tuple, list)) else [out]
        return vars_
    arrs = [v._data if isinstance(v, Tensor) else v for v in loop_vars]

    def c(vs):
        r = cond_fn(*[Tensor(v) for v in vs])
        return (r._data if isinstance(r, Tensor) else r).reshape(())

    def b(vs):
        out = body(*[Tensor(v) for v in vs])
        out = out if isinstance(out, (tuple, list)) else [out]
        return tuple(o._data if isinstance(o, Tensor) else o for o in out)
    res = jax.lax.while_loop(c, b, tuple(arrs))
    return [Tensor(r) for r in res]


def static_pylayer(forward_fn, inputs, backward_fn=None, name=None):
    """(parity: static.nn.static_pylayer — custom fwd/bwd block). Maps to
    the PyLayer mechanism."""
    from ..autograd import PyLayer

    class _P(PyLayer):
        @staticmethod
        def forward(ctx, *xs):
            return forward_fn(*xs)

        @staticmethod
        def backward(ctx, *gs):
            if backward_fn is None:
                return gs
            return backward_fn(*gs)
    return _P.apply(*inputs)


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    from .extras import py_func as _pf
    return _pf(func, x, out, backward_func, skip_vars_in_backward_input)


# -- sequence ops (LoD-free: padded (B, T, ...) + lengths) ------------------

def _seq_op(name, fn, *ops):
    from ..core.dispatch import run_op
    return run_op(name, fn, ops)


def sequence_softmax(input, use_cudnn=False, name=None):
    return _seq_op("sequence_softmax",
                   lambda a: jax.nn.softmax(a, axis=-1), input)


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0):
    pt = pool_type.lower()

    def fn(a):
        if pt == "sum":
            return jnp.sum(a, axis=1)
        if pt in ("average", "avg"):
            return jnp.mean(a, axis=1)
        if pt == "max":
            return jnp.max(a, axis=1)
        if pt == "sqrt":
            return jnp.sum(a, axis=1) / jnp.sqrt(float(a.shape[1]))
        if pt == "first":
            return a[:, 0]
        if pt == "last":
            return a[:, -1]
        raise ValueError(f"unknown pool_type {pool_type}")
    return _seq_op("sequence_pool", fn, input)


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def sequence_concat(input, name=None):
    from ..tensor.manipulation import concat
    return concat(list(input), axis=1)


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    """Temporal convolution over padded sequences (parity:
    static.nn.sequence_conv)."""
    from ..core.dispatch import run_op
    from ..nn.parameter import create_parameter
    d = _shape_of(input)[-1]
    w = create_parameter([filter_size * d, num_filters], "float32",
                         attr=param_attr)
    b = None if bias_attr is False else create_parameter(
        [num_filters], "float32", attr=bias_attr, is_bias=True)
    start = padding_start if padding_start is not None \
        else -(filter_size // 2)

    def fn(a, wt, *bb):
        B, T, D = a.shape
        cols = []
        for i in range(filter_size):
            off = start + i
            if off < 0:
                sl = jnp.pad(a, ((0, 0), (-off, 0), (0, 0)))[:, :T]
            else:
                sl = jnp.pad(a, ((0, 0), (0, off), (0, 0)))[:, off:T + off]
            cols.append(sl)
        col = jnp.concatenate(cols, axis=-1)  # (B, T, fs*D)
        out = col @ wt
        if bb:
            out = out + bb[0]
        return out
    ops = (input, w) + ((b,) if b is not None else ())
    out = run_op("sequence_conv", fn, ops)
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def sequence_slice(input, offset, length, name=None):
    def fn(a, off, ln):
        # static slice per batch row via gather of a length-L window
        L = int(np.asarray(ln).max())
        idx = np.asarray(off).reshape(-1, 1) + np.arange(L)[None, :]
        return jnp.take_along_axis(
            a, jnp.asarray(idx)[..., None].astype(jnp.int32), axis=1)
    return _seq_op("sequence_slice", fn, input, offset, length)


def sequence_expand(x, y, ref_level=-1, name=None):
    def fn(a, b):
        rep = b.shape[1] // max(a.shape[1], 1)
        return jnp.repeat(a, max(rep, 1), axis=1)
    return _seq_op("sequence_expand", fn, x, y)


def sequence_expand_as(x, y, name=None):
    return sequence_expand(x, y)


def sequence_pad(x, pad_value, maxlen=None, name=None):
    def fn(a, pv):
        target = maxlen or a.shape[1]
        extra = target - a.shape[1]
        if extra <= 0:
            return a[:, :target], jnp.full((a.shape[0],), a.shape[1],
                                           jnp.int64)
        pad_cfg = [(0, 0), (0, extra)] + [(0, 0)] * (a.ndim - 2)
        mask_cfg = [(0, 0), (0, extra)]
        valid = jnp.pad(jnp.ones(a.shape[:2], bool), mask_cfg)
        padded = jnp.pad(a, pad_cfg)
        shape = (1, padded.shape[1]) + (1,) * (a.ndim - 2)
        valid = valid.reshape(a.shape[0], padded.shape[1],
                              *([1] * (a.ndim - 2)))
        padded = jnp.where(valid, padded, pv.reshape((1,) * padded.ndim))
        return padded, jnp.full((a.shape[0],), a.shape[1], jnp.int64)
    return _seq_op("sequence_pad", fn, x, pad_value)


def sequence_unpad(x, length, name=None):
    def fn(a, ln):
        L = int(np.asarray(ln).max())
        return a[:, :L]
    return _seq_op("sequence_unpad", fn, x, length)


def sequence_reshape(input, new_dim):
    def fn(a):
        B = a.shape[0]
        return a.reshape(B, -1, new_dim)
    return _seq_op("sequence_reshape", fn, input)


def sequence_scatter(input, index, updates, name=None):
    def fn(a, idx, upd):
        return a.at[jnp.arange(a.shape[0])[:, None],
                    idx.astype(jnp.int32)].add(upd)
    return _seq_op("sequence_scatter", fn, input, index, updates)


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    def fn(a):
        B, T = a.shape[:2]
        out = jnp.full((B, T, win_size), pad_value, a.dtype)
        for i in range(win_size):
            valid = T - i
            out = out.at[:, :valid, i].set(a[:, i:])
        return out
    return _seq_op("sequence_enumerate", fn, input)


def sequence_reverse(x, name=None):
    return _seq_op("sequence_reverse", lambda a: jnp.flip(a, axis=1), x)
