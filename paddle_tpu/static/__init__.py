"""Static-graph front end (parity: python/paddle/static/ + the Program/
Block/Variable model of python/paddle/base/framework.py — ~30k LoC in the
reference).

TPU-native design: the reference's static mode builds a ProgramDesc that
its interpreters execute; here a ``Program`` records the op DAG at
API-call time (the dispatch funnel appends an ``OpNode`` whenever an
operand is a symbolic ``Variable``) and ``Executor.run`` compiles the
recorded DAG into ONE jitted XLA program per feed signature — the
StandaloneExecutor/_ExecutorCache pair collapses onto jax.jit and its
cache (SURVEY §7.1). ``Optimizer.minimize`` inside a program appends a
training node, so ``exe.run(feed, fetch_list)`` is a full compiled train
step, exactly the reference's usage shape:

    paddle.enable_static()
    x = static.data('x', [None, 784])
    y = static.data('y', [None, 1], 'int64')
    loss = F.cross_entropy(net(x), y)
    opt.minimize(loss)
    exe = static.Executor()
    exe.run(static.default_startup_program())
    loss_val, = exe.run(feed={'x': xs, 'y': ys}, fetch_list=[loss])
"""
from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Variable", "Program", "Executor", "Operator", "Parameter",
           "Scope", "data", "program_guard",
           "default_main_program", "default_startup_program",
           "enable_static", "disable_static", "in_static_mode", "scope_guard",
           "global_scope", "name_scope", "InputSpec"]

_STATIC_MODE = [False]
_counter = itertools.count()


def enable_static():
    _STATIC_MODE[0] = True


def disable_static(place=None):
    del place  # parity: paddle.disable_static(place)
    _STATIC_MODE[0] = False


def in_static_mode() -> bool:
    return _STATIC_MODE[0]


class Variable:
    """Symbolic tensor in a Program (parity: base/framework.py Variable).
    Shape may contain None (dynamic batch); dtype is a jnp dtype."""

    def __init__(self, program: "Program", shape, dtype, name=None,
                 producer=None, out_idx: int = 0, is_input: bool = False):
        self.program = program
        self.shape = list(shape)
        self.dtype = jnp.dtype(dtype) if not isinstance(dtype, jnp.dtype) \
            else dtype
        self.name = name or f"var_{next(_counter)}"
        self.producer = producer      # OpNode or None (feed input)
        self.out_idx = out_idx
        self.is_input = is_input
        self.stop_gradient = True

    @property
    def ndim(self):
        return len(self.shape)

    def sds(self, dynamic: Optional[Dict[str, int]] = None):
        shape = tuple(1 if d is None else d for d in self.shape)
        return jax.ShapeDtypeStruct(shape, self.dtype)

    def __repr__(self):
        return (f"Variable(name={self.name}, shape={self.shape}, "
                f"dtype={self.dtype})")

    # -- operator sugar (static-graph arithmetic) -------------------------
    def _binop(self, other, opname):
        from .. import tensor as T
        return getattr(T, opname)(self, other)

    def __add__(self, other):
        return self._binop(other, "add")

    def __radd__(self, other):
        return self._binop(other, "add")

    def __sub__(self, other):
        return self._binop(other, "subtract")

    def __mul__(self, other):
        return self._binop(other, "multiply")

    def __rmul__(self, other):
        return self._binop(other, "multiply")

    def __truediv__(self, other):
        return self._binop(other, "divide")

    def __pow__(self, other):
        from ..tensor.math import pow as _pow
        return _pow(self, other)

    def __neg__(self):
        from ..tensor.math import neg
        return neg(self)

    def __matmul__(self, other):
        from ..tensor.linalg import matmul
        return matmul(self, other)

    def reshape(self, shape):
        from ..tensor.manipulation import reshape
        return reshape(self, shape)

    def astype(self, dtype):
        from ..tensor.manipulation import cast
        return cast(self, dtype)

    def sum(self, axis=None, keepdim=False):
        from ..tensor.math import sum as _sum
        return _sum(self, axis=axis, keepdim=keepdim)

    def mean(self, axis=None, keepdim=False):
        from ..tensor.math import mean
        return mean(self, axis=axis, keepdim=keepdim)


class OpNode:
    """One recorded op: a pure jax function over resolved operand values
    (parity: one OpDesc in the reference's ProgramDesc)."""

    def __init__(self, name, jax_fn, operands, outputs, attrs=None):
        self.name = name
        self.jax_fn = jax_fn
        self.operands = list(operands)   # Variable | Tensor | raw value
        self.outputs = outputs           # list[Variable]
        self.attrs = dict(attrs) if attrs else {}  # static op attributes
        # (consumed by the auto-parallel Completer's SPMD rules)


class TrainNode:
    """Appended by Optimizer.minimize: grads of ``loss`` w.r.t. the
    program's captured parameters + the optimizer update (parity: the
    backward + optimizer ops append_backward emits)."""

    def __init__(self, loss_var: Variable, optimizer):
        self.loss = loss_var
        self.optimizer = optimizer
        self._states = None  # optimizer state, shared across feed shapes


class Program:
    """A recorded op DAG (parity: static.Program)."""

    def __init__(self):
        self.inputs: Dict[str, Variable] = {}
        self.nodes: List[OpNode] = []
        self.train_node: Optional[TrainNode] = None
        self._version = 0

    def _add_input(self, var: Variable):
        self.inputs[var.name] = var
        self._version += 1

    def _add_node(self, node: OpNode):
        self.nodes.append(node)
        self._version += 1

    def parameters(self):
        """Captured concrete Tensors (the reference's persistable vars)."""
        from ..core.tensor import Tensor
        seen, out = set(), []
        for n in self.nodes:
            for o in n.operands:
                if isinstance(o, Tensor) and not o.stop_gradient \
                        and id(o) not in seen:
                    seen.add(id(o))
                    out.append(o)
        return out

    def global_block(self):
        return self

    def clone(self, for_test=False):
        p = Program()
        p.inputs = dict(self.inputs)
        p.nodes = list(self.nodes)
        p.train_node = None if for_test else self.train_node
        return p


_MAIN = [Program()]
_STARTUP = [Program()]


def default_main_program() -> Program:
    return _MAIN[0]


def default_startup_program() -> Program:
    return _STARTUP[0]


class program_guard:
    def __init__(self, main_program, startup_program=None):
        self.main = main_program
        self.startup = startup_program or Program()

    def __enter__(self):
        self._saved = (_MAIN[0], _STARTUP[0])
        _MAIN[0] = self.main
        _STARTUP[0] = self.startup
        return self

    def __exit__(self, *exc):
        _MAIN[0], _STARTUP[0] = self._saved
        return False


def data(name: str, shape, dtype="float32", lod_level=0) -> Variable:
    """Feed placeholder (parity: static.data)."""
    del lod_level
    v = Variable(default_main_program(), shape,
                 _np_dtype(dtype), name=name, is_input=True)
    default_main_program()._add_input(v)
    return v


def _np_dtype(dtype):
    mapping = {"float32": jnp.float32, "float64": jnp.float64,
               "float16": jnp.float16, "bfloat16": jnp.bfloat16,
               "int32": jnp.int32, "int64": jnp.int64, "bool": jnp.bool_,
               "int8": jnp.int8, "uint8": jnp.uint8}
    if isinstance(dtype, str):
        return mapping.get(dtype, jnp.float32)
    return dtype


# paddle.static.InputSpec IS the jit InputSpec in the reference; reuse it
# so jit.save/to_static accept either import path
from ..jit import InputSpec  # noqa: E402


# -- recording hook (called from core/dispatch.py) ---------------------------

def record_op(name, jax_fn, operands, num_nondiff_outputs=0, attrs=None):
    """Append an OpNode; infer output shapes with jax.eval_shape over
    ShapeDtypeStructs (the infer_meta analog: no execution)."""
    prog = None
    for o in operands:
        if isinstance(o, Variable):
            prog = o.program
            break
    assert prog is not None

    def as_sds(o):
        from ..core.tensor import Tensor
        if isinstance(o, Variable):
            return o.sds()
        if isinstance(o, Tensor):
            return jax.ShapeDtypeStruct(o._data.shape, o._data.dtype)
        arr = jnp.asarray(o)
        return jax.ShapeDtypeStruct(arr.shape, arr.dtype)

    out_shape = jax.eval_shape(jax_fn, *[as_sds(o) for o in operands])
    single = not isinstance(out_shape, (tuple, list))
    out_list = [out_shape] if single else list(out_shape)
    node = OpNode(name, jax_fn, operands, [], attrs=attrs)
    # dynamic leading dim: shape inference ran with the None batch mapped
    # to 1; if any Variable operand was dynamic on dim 0 and the output's
    # dim 0 still reads 1, keep it symbolic so user shape introspection
    # sees None, not a baked 1 (a heuristic — reshapes that consume the
    # literal batch extent still need a concrete-shape program)
    dyn_batch = any(isinstance(o, Variable) and o.ndim and
                    o.shape[0] is None for o in operands)
    outs = []
    for i, s in enumerate(out_list):
        shape = list(s.shape)
        if dyn_batch and shape and shape[0] == 1:
            shape[0] = None
        outs.append(Variable(prog, shape, s.dtype, producer=node,
                             out_idx=i))
    node.outputs = outs
    prog._add_node(node)
    return outs[0] if single else tuple(outs)


def is_recording() -> bool:
    return _STATIC_MODE[0]


# -- executor ---------------------------------------------------------------

class Executor:
    """Compiles the recorded DAG per feed signature and runs it as one XLA
    program (parity: base/executor.py Executor + _ExecutorCache:855)."""

    def __init__(self, place=None):
        del place
        self._cache: Dict = {}

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        if isinstance(program, Program) and feed is None and not fetch_list:
            return []  # startup program: params are initialized eagerly
        program = program if isinstance(program, Program) \
            else default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        from ..core.tensor import Tensor

        feed_arrays = {k: jnp.asarray(np.asarray(v)) for k, v in feed.items()}
        sig = (id(program), program._version,
               tuple(sorted((k, a.shape, str(a.dtype))
                            for k, a in feed_arrays.items())),
               tuple(id(f) for f in fetch_list))
        entry = self._cache.get(sig)
        if entry is None:
            entry = self._compile(program, feed_arrays, fetch_list)
            self._cache[sig] = entry
        fn, param_tensors, opt_pack = entry

        params = {t.name or str(i): t._data
                  for i, t in enumerate(param_tensors)}
        if opt_pack is None:
            outs = fn(feed_arrays, params)
        else:
            # optimizer state lives on the TrainNode, shared across ALL
            # compiled signatures of this program (a new batch shape must
            # not reset Adam moments)
            optimizer = opt_pack
            tn = program.train_node
            outs, new_params, new_states = fn(feed_arrays, params,
                                              tn._states,
                                              optimizer.get_lr())
            for i, t in enumerate(param_tensors):
                t._data = new_params[t.name or str(i)]
            tn._states = new_states
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]

    # -- compilation -------------------------------------------------------
    def _compile(self, program: Program, feed_arrays, fetch_list):
        from ..core.tensor import Tensor
        param_tensors = []
        seen = set()
        for n in program.nodes:
            for o in n.operands:
                if isinstance(o, Tensor) and id(o) not in seen:
                    seen.add(id(o))
                    param_tensors.append(o)
        for i, t in enumerate(param_tensors):
            if not t.name:
                t.name = f"__static_p{i}"

        def forward(feeds, params, targets):
            env: Dict[int, Any] = {}

            def resolve(o):
                if isinstance(o, Variable):
                    if o.is_input:
                        if o.name not in feeds:
                            raise KeyError(
                                f"feed missing input '{o.name}'")
                        return feeds[o.name]
                    if id(o) not in env:
                        raise KeyError(
                            f"fetch target {o.name} was not produced by "
                            "this program")
                    return env[id(o)]
                if isinstance(o, Tensor):
                    return params[o.name]
                return o

            needed = _reachable(targets)
            for node in program.nodes:
                if node not in needed:
                    continue
                vals = node.jax_fn(*[resolve(o) for o in node.operands])
                vals = vals if isinstance(vals, tuple) else (vals,)
                for var, v in zip(node.outputs, vals):
                    env[id(var)] = v
            return [resolve(t) for t in targets]

        tn = program.train_node
        if tn is None:
            def run_fn(feeds, params):
                return forward(feeds, params, list(fetch_list))
            return jax.jit(run_fn), param_tensors, None

        optimizer = tn.optimizer
        trainable = [t for t in param_tensors if not t.stop_gradient]
        if getattr(tn, "_states", None) is None:
            tn._states = optimizer.init_state_tree(
                {t.name: t._data for t in trainable})

        def train_fn(feeds, params, states, lr):
            def loss_of(tparams):
                merged = dict(params)
                merged.update(tparams)
                return forward(feeds, merged, [tn.loss])[0]

            tparams = {t.name: params[t.name] for t in trainable}
            loss, grads = jax.value_and_grad(loss_of)(tparams)
            new_t, new_states = optimizer.apply_gradients(
                tparams, grads, states, lr)
            new_params = dict(params)
            new_params.update(new_t)
            # non-loss fetches evaluate with PRE-update params, and the
            # fetched loss is the pre-update loss (reference semantics:
            # fetches observe the program state the step ran with)
            fetches = forward(feeds, params,
                              [f for f in fetch_list if f is not tn.loss])
            outs = []
            fi = iter(fetches)
            for f in fetch_list:
                outs.append(loss if f is tn.loss else next(fi))
            return outs, new_params, new_states

        return jax.jit(train_fn), param_tensors, optimizer


def _reachable(targets):
    """All OpNodes needed to materialize ``targets``."""
    out = set()
    stack = [t for t in targets if isinstance(t, Variable)]
    visited = set()
    while stack:
        v = stack.pop()
        if id(v) in visited or v.producer is None:
            visited.add(id(v))
            continue
        visited.add(id(v))
        node = v.producer
        if node in out:
            continue
        out.add(node)
        for o in node.operands:
            if isinstance(o, Variable):
                stack.append(o)
    return out


# -- misc parity shims -------------------------------------------------------

class _TensorSlot:
    """Live view of one scope entry: reads always see the current value,
    ``set`` writes back — the reference's
    ``scope.var(name).get_tensor().set(arr, place)`` idiom."""

    def __init__(self, scope, name):
        self._scope = scope
        self._name = name

    def set(self, value, place=None):
        self._scope[self._name] = np.asarray(value)

    def value(self):
        return self._scope.get(self._name)

    def __array__(self, dtype=None):
        arr = np.asarray(self._scope.get(self._name))
        return arr.astype(dtype) if dtype is not None else arr

    def shape(self):
        v = self._scope.get(self._name)
        return list(np.shape(v)) if v is not None else []


class _Scope(dict):
    """Variable-name -> value scope (parity: paddle.static.Scope — the
    C++ scope tree collapses to one dict level per scope; var/find_var
    hand out LIVE holders, never snapshots)."""

    class _Var:
        def __init__(self, scope, name):
            self._scope = scope
            self._name = name

        @property
        def name(self):
            return self._name

        def get_tensor(self):
            return _TensorSlot(self._scope, self._name)

    def var(self, name):
        self.setdefault(name, None)
        return self._Var(self, name)

    def find_var(self, name):
        if name not in self:
            return None
        return self._Var(self, name)

    def new_scope(self):
        return _Scope()


Scope = _Scope

_SCOPE = [_Scope()]


def global_scope():
    return _SCOPE[0]


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        self._saved = _SCOPE[0]
        _SCOPE[0] = self.scope
        return self

    def __exit__(self, *exc):
        _SCOPE[0] = self._saved
        return False


class name_scope:
    def __init__(self, prefix=None):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

from .extras import (append_backward, gradients, BuildStrategy,  # noqa: E402,F401
                     CompiledProgram, ExecutionStrategy, ipu_shard_guard,
                     IpuCompiledProgram, IpuStrategy, set_ipu_shard, Print,
                     py_func, WeightNormParamAttr,
                     ExponentialMovingAverage, save, load,
                     save_inference_model, load_inference_model,
                     serialize_program, serialize_persistables,
                     save_to_file, deserialize_program,
                     deserialize_persistables, load_from_file,
                     normalize_program, load_program_state,
                     set_program_state, cpu_places, cuda_places,
                     xpu_places, create_global_var, create_parameter,
                     accuracy, auc, device_guard, ctr_metric_bundle,
                     save_vars, load_vars, is_persistable)
from . import nn  # noqa: E402,F401

# path-faithful aliases: the recorded OpNode IS the reference's Operator
# (one OpDesc), and static Parameters are the nn Parameter objects the
# recorder captures (base/framework.py Operator/Parameter)
Operator = OpNode
from ..nn.parameter import Parameter  # noqa: E402,F401
from .. import amp  # noqa: E402,F401  (static.amp: same decorate/GradScaler surface)
