"""Remaining paddle.static surface (parity: python/paddle/static/
__init__.py — program serialization, grads, strategies, EMA, metrics).

The static substrate here is the recorded OpNode DAG (static/__init__.py);
"programs" serialize as pickled graphs + numpy params, and gradient APIs
delegate to the same jax.grad machinery the Executor's train path uses.
"""
from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "append_backward", "gradients", "BuildStrategy", "CompiledProgram",
    "ExecutionStrategy", "ipu_shard_guard", "IpuCompiledProgram",
    "IpuStrategy", "set_ipu_shard", "Print", "py_func",
    "WeightNormParamAttr", "ExponentialMovingAverage", "save", "load",
    "save_inference_model", "load_inference_model", "serialize_program",
    "serialize_persistables", "save_to_file", "deserialize_program",
    "deserialize_persistables", "load_from_file", "normalize_program",
    "load_program_state", "set_program_state", "cpu_places", "cuda_places",
    "xpu_places", "create_global_var", "create_parameter", "accuracy",
    "auc", "device_guard", "ctr_metric_bundle", "save_vars", "load_vars",
    "is_persistable",
]


def _prog():
    from . import default_main_program
    return default_main_program()


# -- gradients -------------------------------------------------------------

def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Symbolic grads of targets w.r.t. inputs (parity: static.gradients).
    Adds grad OpNodes producing d(sum(targets))/d(inputs)."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    # each grad is one OpNode whose jax_fn rebuilds the target subgraph
    # functionally and differentiates it with jax.grad at compile time
    return [_symbolic_grad(targets, inp, target_gradients)
            for inp in inputs]


def _symbolic_grad(targets, inp, target_gradients=None):
    from . import Variable, record_op, _reachable
    from ..core.tensor import Tensor
    prog = inp.program if isinstance(inp, Variable) else _prog()

    nodes = _reachable([t for t in targets])

    def fn(inp_arr, *leaf_arrs):
        # rebuild the forward subgraph with inp replaced by inp_arr;
        # other leaves (params AND other feed Variables) arrive in
        # leaf_arrs in registration order
        leaves = list(leaf_arrs)

        def fwd(x):
            env = {id(inp): x}
            li = iter(leaves)
            leaf_map = {}

            def resolve(o):
                if isinstance(o, Variable):
                    if id(o) in env:
                        return env[id(o)]
                    if id(o) not in leaf_map:
                        leaf_map[id(o)] = next(li)
                    return leaf_map[id(o)]
                if isinstance(o, Tensor):
                    if id(o) not in leaf_map:
                        leaf_map[id(o)] = next(li)
                    return leaf_map[id(o)]
                return o
            total = 0.0
            for node in prog.nodes:
                if node not in nodes:
                    continue
                vals = node.jax_fn(*[resolve(o) for o in node.operands])
                vals = vals if isinstance(vals, tuple) else (vals,)
                for var, v in zip(node.outputs, vals):
                    env[id(var)] = v
            for t in targets:
                total = total + jnp.sum(env[id(t)])
            return total
        return jax.grad(fwd)(inp_arr)

    # every leaf feeding the subgraph except inp itself: Tensor params
    # and other input Variables, in traversal order (matches leaf_map's
    # first-touch order inside fwd)
    leaf_ops = []
    seen = {id(inp)}
    for node in prog.nodes:
        if node not in nodes:
            continue
        for o in node.operands:
            if id(o) in seen:
                continue
            if isinstance(o, Tensor) or (isinstance(o, Variable)
                                         and o.is_input):
                seen.add(id(o))
                leaf_ops.append(o)
    return record_op(f"grad_of_{getattr(inp, 'name', 'x')}", fn,
                     (inp, *leaf_ops))


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """(parity: static.append_backward) — returns [(param, grad_var)].
    On this substrate the Executor's train path computes grads with
    jax.value_and_grad at compile time; this API materializes explicit
    grad vars for programs that want them."""
    from ..core.tensor import Tensor
    from . import _reachable
    params = parameter_list
    if params is None:
        nodes = _reachable([loss])
        params, seen = [], set()
        for node in loss.program.nodes:
            if node not in nodes:
                continue
            for o in node.operands:
                if isinstance(o, Tensor) and not o.stop_gradient \
                        and id(o) not in seen:
                    seen.add(id(o))
                    params.append(o)
    pairs = []
    for p in params:
        g = _symbolic_grad_wrt_param(loss, p)
        pairs.append((p, g))
    return pairs


def _symbolic_grad_wrt_param(loss, param):
    from ..core.tensor import Tensor
    from . import Variable, _reachable, record_op
    prog = loss.program
    nodes = _reachable([loss])

    def fn(p_arr, *rest):
        feeds = list(rest)

        def fwd(pv):
            env = {}
            fi = iter(feeds)
            fmap = {}

            def resolve(o):
                if isinstance(o, Variable):
                    if id(o) in env:
                        return env[id(o)]
                    if id(o) not in fmap:
                        fmap[id(o)] = next(fi)
                    return fmap[id(o)]
                if isinstance(o, Tensor):
                    if o is param:
                        return pv
                    if id(o) not in fmap:
                        fmap[id(o)] = next(fi)
                    return fmap[id(o)]
                return o
            for node in prog.nodes:
                if node not in nodes:
                    continue
                vals = node.jax_fn(*[resolve(o) for o in node.operands])
                vals = vals if isinstance(vals, tuple) else (vals,)
                for var, v in zip(node.outputs, vals):
                    env[id(var)] = v
            return jnp.sum(env[id(loss)])
        return jax.grad(fwd)(p_arr)

    rest_ops = []
    seen = {id(param)}
    for node in prog.nodes:
        if node not in nodes:
            continue
        for o in node.operands:
            if isinstance(o, (Tensor, Variable)) and id(o) not in seen:
                if isinstance(o, Variable) and not o.is_input:
                    continue
                seen.add(id(o))
                rest_ops.append(o)
    return record_op(f"{param.name or 'param'}@GRAD", fn,
                     (param, *rest_ops))


# -- strategies / compiled program ----------------------------------------

class BuildStrategy:
    """(parity: static.BuildStrategy — build knobs; XLA owns fusion and
    scheduling here, so the fields are recorded but the compiler decides)"""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_elewise_add_act_ops = True
        self.fuse_bn_act_ops = True
        self.memory_optimize = True
        self.build_cinn_pass = False
        self.debug_graphviz_path = ""


class ExecutionStrategy:
    """(parity: static.ExecutionStrategy)"""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 100
        self.num_iteration_per_run = 1


class CompiledProgram:
    """(parity: static.CompiledProgram — jit compilation happens in the
    Executor's signature cache; this wrapper carries the strategy)."""

    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()

    def __getattr__(self, item):
        return getattr(self.__dict__["_program"], item)


# -- IPU shims (inventoried; no IPU on this substrate) ---------------------

class IpuStrategy:
    """(parity: static.IpuStrategy — config container only; there is no
    IPU backend on the TPU substrate)"""

    def __init__(self):
        self.num_ipus = 1
        self.is_training = True
        self.micro_batch_size = 1
        self.enable_manual_shard = False

    def set_graph_config(self, num_ipus=1, is_training=True,
                         micro_batch_size=1, enable_manual_shard=False):
        self.num_ipus = num_ipus
        self.is_training = is_training
        self.micro_batch_size = micro_batch_size
        self.enable_manual_shard = enable_manual_shard


class IpuCompiledProgram:
    def __init__(self, program=None, scope=None, ipu_strategy=None):
        raise RuntimeError(
            "IPU execution is not available in the TPU build; use the "
            "Executor (XLA) directly")


class ipu_shard_guard:
    def __init__(self, index=-1, stage=-1):
        del index, stage

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def set_ipu_shard(call_func, index=-1, stage=-1):
    del index, stage
    return call_func


# -- debugging ops ---------------------------------------------------------

def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Print a var's value at run time via jax.debug.print (parity:
    static.Print op)."""
    from . import record_op
    msg = message or ""
    name = getattr(input, "name", "var")

    def fn(a):
        jax.debug.print(msg + " {name} shape={shape} value={v}",
                        name=name, shape=str(a.shape), v=a)
        return a
    return record_op("print", fn, (input,))


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-callback op (parity: static.py_func — runs a Python fn on
    host tensors inside the program via jax.pure_callback)."""
    from . import Variable, record_op
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    shapes = [jax.ShapeDtypeStruct(tuple(1 if s is None else s
                                         for s in o.shape), o.dtype)
              for o in outs]

    def fn(*arrs):
        res = jax.pure_callback(
            lambda *hs: func(*hs), shapes if len(shapes) > 1 else shapes[0],
            *arrs)
        return res
    return record_op("py_func", fn, tuple(xs))


# -- param attrs / EMA -----------------------------------------------------

from ..nn.parameter import ParamAttr  # noqa: E402


class WeightNormParamAttr(ParamAttr):
    """(parity: static.WeightNormParamAttr — weight-norm reparameterized
    parameter attribute)."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        super().__init__(name=name, initializer=initializer,
                         learning_rate=learning_rate,
                         regularizer=regularizer, trainable=trainable)
        self.dim = dim


class ExponentialMovingAverage:
    """EMA of trainable parameters (parity:
    static.ExponentialMovingAverage, python/paddle/static/nn/...).
    Eager-friendly: update() after each step; apply()/restore() swap."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._ema = {}
        self._backup = None
        self._step = 0
        self._params = None

    def _param_list(self):
        if self._params is not None:
            return self._params
        from . import default_main_program
        return default_main_program().parameters()

    def bind(self, parameters):
        self._params = list(parameters)

    def update(self):
        self._step += 1
        d = min(self._decay, (1 + self._step) / (10 + self._step))
        for p in self._param_list():
            key = p.name or str(id(p))
            prev = self._ema.get(key)
            cur = p._data
            self._ema[key] = cur if prev is None else \
                d * prev + (1 - d) * cur

    def apply(self, executor=None, need_restore=True):
        self._backup = [(p, p._data) for p in self._param_list()]
        for p in self._param_list():
            key = p.name or str(id(p))
            if key in self._ema:
                p._data = self._ema[key].astype(p._data.dtype)
        outer = self

        class _Ctx:
            def __enter__(self):
                return self

            def __exit__(self, *exc):
                if need_restore:
                    outer.restore()
                return False
        return _Ctx()

    def restore(self, executor=None):
        if self._backup:
            for p, d in self._backup:
                p._data = d
            self._backup = None


# -- serialization ---------------------------------------------------------

def _program_state(program):
    state = {}
    for i, t in enumerate(program.parameters()):
        if not t.name:
            t.name = f"__static_p{i}"
        state[t.name] = np.asarray(t._data)
    return state


def serialize_program(feed_vars, fetch_vars, program=None, **kwargs):
    """(parity: static.serialize_program) — pickled graph metadata."""
    prog = program or _prog()
    meta = {
        "inputs": [v.name for v in (feed_vars if isinstance(
            feed_vars, (list, tuple)) else [feed_vars])],
        "outputs": [getattr(v, "name", "") for v in (
            fetch_vars if isinstance(fetch_vars, (list, tuple))
            else [fetch_vars])],
        "n_nodes": len(prog.nodes),
    }
    return pickle.dumps(meta)


def serialize_persistables(feed_vars, fetch_vars, program=None, **kwargs):
    """(parity: static.serialize_persistables)"""
    prog = program or _prog()
    return pickle.dumps(_program_state(prog))


def save_to_file(path, content):
    """(parity: static.save_to_file)"""
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    """(parity: static.load_from_file)"""
    with open(path, "rb") as f:
        return f.read()


def deserialize_program(data):
    """(parity: static.deserialize_program)"""
    return pickle.loads(data)


def deserialize_persistables(program, data, executor=None):
    """(parity: static.deserialize_persistables)"""
    state = pickle.loads(data)
    set_program_state(program, state)
    return state


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """(parity: static.normalize_program — prunes to the feed->fetch
    subgraph; our executor prunes at compile time, so this is a marker)."""
    return program


def save(program, model_path, protocol=4, **configs):
    """(parity: static.save — <path>.pdparams + .pdmodel)"""
    d = os.path.dirname(model_path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(_program_state(program), f, protocol=protocol)
    with open(model_path + ".pdmodel", "wb") as f:
        f.write(serialize_program([], [], program))


def load(program, model_path, executor=None, var_list=None):
    """(parity: static.load)"""
    with open(model_path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    set_program_state(program, state)
    return state


def load_program_state(model_path, var_list=None):
    """(parity: static.load_program_state)"""
    with open(model_path + ".pdparams", "rb") as f:
        return pickle.load(f)


def set_program_state(program, state_dict):
    """(parity: static.set_program_state)"""
    for t in program.parameters():
        if t.name in state_dict:
            t._data = jnp.asarray(state_dict[t.name]).astype(t._data.dtype)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """(parity: static.save_inference_model)"""
    prog = program or _prog()
    d = os.path.dirname(path_prefix)
    if d:
        os.makedirs(d, exist_ok=True)
    save_to_file(path_prefix + ".pdmodel",
                 serialize_program(feed_vars, fetch_vars, prog))
    save_to_file(path_prefix + ".pdiparams",
                 serialize_persistables(feed_vars, fetch_vars, prog))


def load_inference_model(path_prefix, executor=None, **kwargs):
    """(parity: static.load_inference_model) — returns (program_meta,
    feed_names, fetch_names)."""
    meta = deserialize_program(load_from_file(path_prefix + ".pdmodel"))
    return meta, meta.get("inputs", []), meta.get("outputs", [])


# -- places / vars / metrics ----------------------------------------------

def cpu_places(device_count=None):
    from ..framework import CPUPlace
    n = device_count or int(os.environ.get("CPU_NUM", 1))
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    from ..framework import CUDAPlace
    ids = device_ids if device_ids is not None else [0]
    return [CUDAPlace(i) for i in ids]


def xpu_places(device_ids=None):
    raise RuntimeError("XPU devices are not available in the TPU build")


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """(parity: static.create_global_var) — a persistable Tensor the
    program references as a leaf."""
    from ..core.tensor import Tensor
    t = Tensor(jnp.full(tuple(shape), value, dtype=dtype), name=name)
    t.persistable = persistable
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """(parity: static.create_parameter)"""
    from ..nn.parameter import create_parameter as _cp
    return _cp(shape, dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """(parity: static.accuracy — same math as paddle.metric.accuracy,
    usable on Variables in a program)."""
    from . import Variable, record_op

    def fn(pred, lab):
        topk = jnp.argsort(-pred, axis=-1)[..., :k]
        lab2 = lab.reshape(lab.shape[0], -1)[:, :1]
        hit = (topk == lab2).any(axis=-1)
        return jnp.mean(hit.astype(jnp.float32))[None]
    if isinstance(input, Variable):
        return record_op("accuracy", fn, (input, label))
    from ..metric import accuracy as _acc
    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """(parity: static.auc) — batch AUC via the trapezoid over thresholded
    TPR/FPR."""
    from . import Variable, record_op

    def fn(pred, lab):
        pos_score = pred[:, 1] if pred.ndim == 2 and pred.shape[1] == 2 \
            else pred.reshape(-1)
        lab_f = lab.reshape(-1).astype(jnp.float32)
        ths = jnp.linspace(0.0, 1.0, num_thresholds)
        preds_at = pos_score[None, :] >= ths[:, None]
        tp = jnp.sum(preds_at * lab_f[None, :], axis=1)
        fp = jnp.sum(preds_at * (1 - lab_f[None, :]), axis=1)
        pos = jnp.maximum(jnp.sum(lab_f), 1e-6)
        neg = jnp.maximum(jnp.sum(1 - lab_f), 1e-6)
        tpr = tp / pos
        fpr = fp / neg
        return jnp.abs(jnp.trapezoid(tpr, fpr))[None]
    if isinstance(input, Variable):
        return record_op("auc", fn, (input, label))
    from ..core.dispatch import run_op
    return run_op("auc", fn, (input, label), out_stop_gradient=True)


class device_guard:
    """(parity: static.device_guard — XLA owns placement; context is a
    marker)."""

    def __init__(self, device=None):
        self.device = device

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def ctr_metric_bundle(input, label):
    """(parity: static.ctr_metric_bundle — abs/sq error sums for CTR)."""
    from ..core.dispatch import run_op

    def fn(pred, lab):
        lab_f = lab.astype(jnp.float32).reshape(-1)
        pr = pred.reshape(-1)
        abserr = jnp.sum(jnp.abs(pr - lab_f))
        sqrerr = jnp.sum((pr - lab_f) ** 2)
        return abserr[None], sqrerr[None], jnp.sum(pr)[None], \
            jnp.asarray([pr.shape[0]], jnp.float32)
    return run_op("ctr_metric_bundle", fn, (input, label),
                  out_stop_gradient=True)


# -- var-level save/load (parity: static.save_vars/load_vars/
# is_persistable, base/framework Operator/Parameter surface) --------------

def is_persistable(var):
    """True for vars that outlive a step (reference io_utils.py checks
    var.persistable): nn Parameters carry persistable=True, plain tensors
    and symbolic Variables default False."""
    return bool(getattr(var, "persistable", False))


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    """Save selected program vars (reference static/io.py save_vars):
    ``vars`` explicitly, else every program parameter passing
    ``predicate``."""
    import os
    import pickle
    prog = main_program or _prog()
    if vars is None:
        vars = [p for p in prog.parameters()
                if predicate is None or predicate(p)]
    state = {}
    for i, t in enumerate(vars):
        if not getattr(t, "name", None):
            t.name = f"__static_v{i}"
        state[t.name] = np.asarray(t._data)
    os.makedirs(dirname, exist_ok=True)
    if filename is not None:
        with open(os.path.join(dirname, filename), "wb") as f:
            pickle.dump(state, f)
    else:
        for name, arr in state.items():
            with open(os.path.join(dirname, name), "wb") as f:
                pickle.dump({name: arr}, f)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    """Load vars saved by save_vars back into the program's captured
    tensors (matched by name)."""
    import os
    import pickle
    import jax.numpy as jnp
    prog = main_program or _prog()
    if vars is None:
        vars = [p for p in prog.parameters()
                if predicate is None or predicate(p)]
    # mirror save_vars' fallback naming so a fresh process (params not yet
    # named) matches what was saved
    for i, t in enumerate(vars):
        if not getattr(t, "name", None):
            t.name = f"__static_v{i}"
    if filename is not None:
        with open(os.path.join(dirname, filename), "rb") as f:
            state = pickle.load(f)
    else:
        state = {}
        for t in vars:
            path = os.path.join(dirname, t.name)
            if not os.path.exists(path):
                raise FileNotFoundError(
                    f"load_vars: no saved file for var '{t.name}' under "
                    f"{dirname}")
            with open(path, "rb") as f:
                state.update(pickle.load(f))
    missing = [t.name for t in vars if t.name not in state]
    if missing:
        raise KeyError(
            f"load_vars: saved state has no entry for vars {missing}")
    for t in vars:
        t._data = jnp.asarray(state[t.name])
