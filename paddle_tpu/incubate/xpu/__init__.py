"""(parity: python/paddle/incubate/xpu/ — XPU-only fused blocks; no XPU
exists on the TPU substrate, the resnet block runs as plain XLA)"""
from . import resnet_block  # noqa: F401
