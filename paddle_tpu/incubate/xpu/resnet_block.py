"""(parity: python/paddle/incubate/xpu/resnet_block.py — the XPU fused
basic block; implemented as the equivalent XLA graph)."""
from __future__ import annotations

from ...nn.layer.layers import Layer

__all__ = ["resnet_basic_block", "ResNetBasicBlock"]


class ResNetBasicBlock(Layer):
    def __init__(self, num_channels1, num_filter1, filter1_size,
                 num_channels2=None, num_filter2=None, filter2_size=None,
                 num_channels3=None, num_filter3=None, filter3_size=None,
                 stride1=1, stride2=1, stride3=1, act="relu",
                 momentum=0.9, eps=1e-5, data_format="NCHW",
                 has_shortcut=False, use_global_stats=False,
                 is_test=False, filter_attr=None, scale_attr=None,
                 bias_attr=None, moving_mean_name=None,
                 moving_var_name=None, padding1=0, padding2=0, padding3=0,
                 trainable_statistics=False, find_conv_max=True):
        super().__init__()
        from ... import nn
        self.conv1 = nn.Conv2D(num_channels1, num_filter1, filter1_size,
                               stride=stride1, padding=padding1,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(num_filter1, momentum=momentum,
                                  epsilon=eps)
        self.relu = nn.ReLU()
        c2 = num_channels2 or num_filter1
        f2 = num_filter2 or num_filter1
        s2 = filter2_size or filter1_size
        self.conv2 = nn.Conv2D(c2, f2, s2, stride=stride2,
                               padding=padding2, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(f2, momentum=momentum, epsilon=eps)
        self.has_shortcut = has_shortcut
        if has_shortcut:
            c3 = num_channels3 or num_channels1
            f3 = num_filter3 or f2
            s3 = filter3_size or 1
            self.conv3 = nn.Conv2D(c3, f3, s3, stride=stride3,
                                   padding=padding3, bias_attr=False)
            self.bn3 = nn.BatchNorm2D(f3, momentum=momentum, epsilon=eps)

    def forward(self, x):
        h = self.relu(self.bn1(self.conv1(x)))
        h = self.bn2(self.conv2(h))
        sc = self.bn3(self.conv3(x)) if self.has_shortcut else x
        return self.relu(h + sc)


def resnet_basic_block(*args, **kwargs):
    """Functional form (parity: incubate.xpu.resnet_block
    .resnet_basic_block) — builds the block and applies it."""
    raise NotImplementedError(
        "use the ResNetBasicBlock layer; the functional form binds 20+ "
        "raw buffers in the XPU kernel layout, which has no TPU meaning")
