"""MoELayer: mixture-of-experts with expert parallelism.

Capability parity with the reference MoELayer
(python/paddle/incubate/distributed/models/moe/moe_layer.py:263) and its
dispatch machinery (MoEScatter/MoEGather PyLayers over the
global_scatter/global_gather all-to-all CUDA ops,
python/paddle/distributed/utils/moe_utils.py:20,153).

TPU-native design: experts live as STACKED parameters (E, d, f) and the
dispatch/combine are dense one-hot einsums (GShard formulation) — MXU
matmuls instead of gather/scatter. Expert parallelism is sharding, not
message passing: the stacked expert weights and the (E, C, d) dispatched
activations carry a sharding constraint on the expert dim, and GSPMD
inserts the all-to-all that global_scatter/global_gather implement by hand
on GPU. The same layer runs unsharded on one chip and EP-sharded under a
mesh without code changes.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import run_op
from ...nn.layer.layers import Layer
from .gate import GShardGate, NaiveGate, SwitchGate, compute_capacity

__all__ = ["MoELayer"]

_GATES = {"gshard": GShardGate, "switch": SwitchGate, "naive": NaiveGate}


class MoELayer(Layer):
    """Mixture of experts over stacked expert MLPs.

    Args:
        d_model: token embedding dim.
        d_hidden: expert FFN hidden dim.
        num_experts: number of experts (global, across the expert axis).
        gate: "gshard" | "switch" | "naive" | a gate instance.
        top_k: used by the naive gate (gshard=2, switch=1 fixed).
        capacity_factor: buffer slack per expert (< 1 drops tokens; the
            dropped fraction is exposed as ``self.drop_rate``).
        dispatch_mode: "einsum" materializes the dense (T, E, C) one-hot
            dispatch/combine tensors (MXU matmuls); "scatter" consumes
            the gate's ragged routing table directly via scatter-add /
            gather, bounding dispatch memory at O(T*K + E*C*d) — the
            form that survives sep x ep meshes where (T, E, C) explodes
            (VERDICT r4 #8; the reference's global_scatter/global_gather
            are the same ragged exchange done with NCCL all-to-all,
            paddle/fluid/operators/collective/global_scatter_op.cu.cc).
        mesh / expert_axis: optional jax Mesh (or ProcessMesh) + axis name
            for expert parallelism; adds sharding constraints so GSPMD
            places one expert group per axis slice.
    """

    def __init__(self, d_model: int, d_hidden: int, num_experts: int,
                 gate="gshard", top_k: int = 2, capacity_factor: float = 1.25,
                 act=None, mesh=None, expert_axis: Optional[str] = None,
                 dispatch_mode: str = "einsum", name=None):
        super().__init__()
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        if dispatch_mode not in ("einsum", "scatter"):
            raise ValueError(
                f"dispatch_mode must be 'einsum' or 'scatter', got "
                f"{dispatch_mode!r}")
        self.dispatch_mode = dispatch_mode
        if isinstance(gate, str):
            gate_cls = _GATES[gate]
            self.gate = (gate_cls(top_k) if gate_cls is NaiveGate
                         else gate_cls())
        else:
            self.gate = gate
        self._mesh = mesh
        self._expert_axis = expert_axis

        self.gate_weight = self.create_parameter(
            [d_model, num_experts],
            default_initializer=lambda shape, dtype: jnp.zeros(
                shape, dtype or jnp.float32))
        self.w1 = self.create_parameter([num_experts, d_model, d_hidden])
        self.b1 = self.create_parameter([num_experts, 1, d_hidden],
                                        is_bias=True)
        self.w2 = self.create_parameter([num_experts, d_hidden, d_model])
        self.b2 = self.create_parameter([num_experts, 1, d_model],
                                        is_bias=True)
        self._act = act if act is not None else jax.nn.gelu
        self.aux_loss = None
        self.drop_rate = None
        if mesh is not None and expert_axis is not None:
            self._shard_experts()

    def _shard_experts(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        jmesh = self._mesh if not hasattr(self._mesh, "to_jax") \
            else self._mesh.to_jax()
        self._mesh = jmesh
        ax = self._expert_axis
        for p in (self.w1, self.b1, self.w2, self.b2):
            p._data = jax.device_put(
                p._data, NamedSharding(jmesh, P(ax, None, None)))

    def _ep_constraint(self, x):
        if self._mesh is None or self._expert_axis is None:
            return x
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = (self._expert_axis,) + (None,) * (x.ndim - 1)
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self._mesh, P(*spec)))

    def forward(self, x):
        """x: [batch, seq, d_model] (or [tokens, d_model]). Returns the
        combined expert output with the same shape; the load-balance loss
        is exposed as ``self.aux_loss`` (differentiable) and the dropped
        token-slot fraction as ``self.drop_rate``."""
        shape = x.shape
        t = int(np.prod(shape[:-1]))
        e = self.num_experts
        capacity = compute_capacity(t, e, self.gate.top_k,
                                    self.capacity_factor)
        gate_obj = self.gate
        act = self._act
        ep = self._ep_constraint
        scatter = self.dispatch_mode == "scatter"

        def experts(ein, w1, b1, w2, b2):
            """(E, C, d) dispatched tokens -> (E, C, d) expert outputs."""
            ein = ep(ein)
            h = act(jnp.einsum("ecd,edf->ecf", ein,
                               w1.astype(jnp.float32))
                    + b1.astype(jnp.float32))
            eout = jnp.einsum("ecf,efd->ecd", h, w2.astype(jnp.float32)) \
                + b2.astype(jnp.float32)
            return ep(eout)

        def fn(xt, gw, w1, b1, w2, b2):
            tok = xt.reshape(t, self.d_model).astype(jnp.float32)
            logits = tok @ gw.astype(jnp.float32)
            idx, pos, gates, kept, aux = gate_obj.route(logits, capacity)
            drop = 1.0 - jnp.mean(kept)
            if scatter:
                # ragged dispatch: flat destination slot per (token, k);
                # dropped slots land on a dummy row past the buffer. The
                # scatter-add / gather pair is the TPU form of the
                # reference's global_scatter/global_gather all-to-all —
                # no (T, E, C) tensor ever materializes.
                slot = jnp.where(kept > 0.0,
                                 idx * capacity + pos,
                                 e * capacity).reshape(-1)       # (T*K,)
                src = jnp.repeat(tok, gate_obj.top_k, axis=0)    # (T*K, d)
                buf = jnp.zeros((e * capacity + 1, self.d_model),
                                jnp.float32).at[slot].add(src)
                eout = experts(buf[:e * capacity].reshape(e, capacity, -1),
                               w1, b1, w2, b2)
                eflat = jnp.concatenate(
                    [eout.reshape(e * capacity, -1),
                     jnp.zeros((1, self.d_model), jnp.float32)], axis=0)
                y = jnp.sum(eflat[slot.reshape(t, gate_obj.top_k)]
                            * gates[:, :, None], axis=1)
            else:
                from .gate import _dense_from_route
                disp, comb = _dense_from_route(idx, pos, gates, kept, e,
                                               capacity)
                # dispatch: (T,E,C) x (T,d) -> (E,C,d) — one-hot matmul
                # on MXU; under EP the sharding constraint turns this
                # into the all-to-all the reference does with
                # global_scatter
                ein = jnp.einsum("tec,td->ecd", disp, tok)
                eout = experts(ein, w1, b1, w2, b2)
                y = jnp.einsum("tec,ecd->td", comb, eout)
            return y.reshape(shape).astype(xt.dtype), aux, drop

        # drop is bookkeeping built from comparisons (gradient identically
        # zero): mark it nondiff so it detaches instead of advertising a
        # dead stop_gradient=False regularizer
        out, aux, drop = run_op("moe_forward", fn,
                                (x, self.gate_weight, self.w1, self.b1,
                                 self.w2, self.b2),
                                num_nondiff_outputs=1)
        self.aux_loss = aux
        self.drop_rate = drop
        return out
