"""MoE gates: naive top-k, Switch (top-1), GShard (top-2).

Capability parity with the reference's gate set
(python/paddle/incubate/distributed/models/moe/gate/: naive_gate.py,
switch_gate.py, gshard_gate.py). The reference gates emit integer routing
tables consumed by the global_scatter/global_gather CUDA all-to-all ops;
here each gate emits dense (tokens, experts, capacity) dispatch/combine
tensors — the GShard formulation — which XLA lowers to one-hot matmuls on
the MXU and which shard cleanly over an expert mesh axis.

All gate math is pure jnp on arrays (traced under jit); capacity is a
static python int so shapes stay static.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

__all__ = ["NaiveGate", "SwitchGate", "GShardGate", "compute_capacity"]


def compute_capacity(num_tokens: int, num_experts: int, top_k: int,
                     capacity_factor: float) -> int:
    return max(4, int(math.ceil(num_tokens * top_k / num_experts
                                * capacity_factor)))


def _one_hot(idx, n):
    return jax.nn.one_hot(idx, n, dtype=jnp.float32)


def _positions_in_expert(mask):
    """mask: (T, E) 0/1 — position of each kept token within its expert's
    buffer = exclusive cumsum along tokens."""
    return jnp.cumsum(mask, axis=0) - mask


def _aux_loss(probs, mask):
    """GShard load-balance loss: E * sum_e mean_t(probs_e) * mean_t(mask_e).
    (reference: gshard_gate.py / switch router loss)"""
    e = probs.shape[1]
    density = jnp.mean(mask, axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    return jnp.sum(density * density_proxy) * e


class _GateBase:
    """Gates are lightweight strategy objects: __call__(logits, capacity) ->
    (dispatch (T,E,C), combine (T,E,C), aux_loss scalar)."""

    top_k = 1

    def __call__(self, logits, capacity):
        raise NotImplementedError


class SwitchGate(_GateBase):
    """Top-1 routing with capacity dropping (Switch Transformer;
    reference switch_gate.py)."""

    top_k = 1

    def __call__(self, logits, capacity):
        t, e = logits.shape
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        idx1 = jnp.argmax(probs, axis=-1)
        mask1 = _one_hot(idx1, e)
        aux = _aux_loss(probs, mask1)
        pos1 = _positions_in_expert(mask1) * mask1
        keep1 = (jnp.sum(pos1, axis=1) < capacity).astype(jnp.float32)
        mask1 = mask1 * keep1[:, None]
        gate1 = jnp.sum(probs * mask1, axis=1)
        disp = mask1[:, :, None] * _one_hot(
            jnp.sum(pos1, axis=1).astype(jnp.int32), capacity)[:, None, :]
        comb = disp * gate1[:, None, None]
        return disp, comb, aux


class GShardGate(_GateBase):
    """Top-2 routing with capacity (GShard; reference gshard_gate.py)."""

    top_k = 2

    def __call__(self, logits, capacity):
        t, e = logits.shape
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        idx1 = jnp.argmax(probs, axis=-1)
        mask1 = _one_hot(idx1, e)
        probs_wo1 = probs * (1.0 - mask1)
        idx2 = jnp.argmax(probs_wo1, axis=-1)
        mask2 = _one_hot(idx2, e)

        aux = _aux_loss(probs, mask1)

        pos1 = jnp.sum(_positions_in_expert(mask1) * mask1, axis=1)
        count1 = jnp.sum(mask1, axis=0, keepdims=True)          # (1, E)
        pos2 = jnp.sum((_positions_in_expert(mask2) + count1) * mask2, axis=1)
        keep1 = (pos1 < capacity).astype(jnp.float32)
        keep2 = (pos2 < capacity).astype(jnp.float32)
        mask1 = mask1 * keep1[:, None]
        mask2 = mask2 * keep2[:, None]

        g1 = jnp.sum(probs * mask1, axis=1)
        g2 = jnp.sum(probs * mask2, axis=1)
        denom = jnp.maximum(g1 + g2, 1e-9)
        g1, g2 = g1 / denom, g2 / denom

        disp1 = mask1[:, :, None] * _one_hot(pos1.astype(jnp.int32),
                                             capacity)[:, None, :]
        disp2 = mask2[:, :, None] * _one_hot(pos2.astype(jnp.int32),
                                             capacity)[:, None, :]
        disp = jnp.maximum(disp1, disp2)
        comb = disp1 * g1[:, None, None] + disp2 * g2[:, None, None]
        return disp, comb, aux


class NaiveGate(_GateBase):
    """Top-k softmax routing without dropping (reference naive_gate.py);
    capacity is still honored to keep shapes static, but the default
    MoELayer sizes it so nothing drops (capacity_factor >= num_experts /
    top_k covers the worst case)."""

    def __init__(self, top_k=2):
        self.top_k = top_k

    def __call__(self, logits, capacity):
        t, e = logits.shape
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        disp = jnp.zeros((t, e, capacity), jnp.float32)
        comb = jnp.zeros((t, e, capacity), jnp.float32)
        remaining = probs
        count = jnp.zeros((1, e), jnp.float32)
        aux = _aux_loss(probs, _one_hot(jnp.argmax(probs, axis=-1), e))
        for _ in range(self.top_k):
            idx = jnp.argmax(remaining, axis=-1)
            mask = _one_hot(idx, e)
            pos = jnp.sum((_positions_in_expert(mask) + count) * mask, axis=1)
            keep = (pos < capacity).astype(jnp.float32)
            mask_k = mask * keep[:, None]
            g = jnp.sum(probs * mask_k, axis=1)
            d = mask_k[:, :, None] * _one_hot(pos.astype(jnp.int32),
                                              capacity)[:, None, :]
            disp = jnp.maximum(disp, d)
            comb = comb + d * g[:, None, None]
            count = count + jnp.sum(mask, axis=0, keepdims=True)
            remaining = remaining * (1.0 - mask)
        return disp, comb, aux
