"""MoE gates: naive top-k, Switch (top-1), GShard (top-2).

Capability parity with the reference's gate set
(python/paddle/incubate/distributed/models/moe/gate/: naive_gate.py,
switch_gate.py, gshard_gate.py). The reference gates emit integer routing
tables consumed by the global_scatter/global_gather CUDA all-to-all ops;
here each gate emits dense (tokens, experts, capacity) dispatch/combine
tensors — the GShard formulation — which XLA lowers to one-hot matmuls on
the MXU and which shard cleanly over an expert mesh axis.

All gate math is pure jnp on arrays (traced under jit); capacity is a
static python int so shapes stay static.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

__all__ = ["NaiveGate", "SwitchGate", "GShardGate", "compute_capacity"]


def compute_capacity(num_tokens: int, num_experts: int, top_k: int,
                     capacity_factor: float) -> int:
    return max(4, int(math.ceil(num_tokens * top_k / num_experts
                                * capacity_factor)))


def _one_hot(idx, n):
    return jax.nn.one_hot(idx, n, dtype=jnp.float32)


def _positions_in_expert(mask):
    """mask: (T, E) 0/1 — position of each kept token within its expert's
    buffer = exclusive cumsum along tokens."""
    return jnp.cumsum(mask, axis=0) - mask


def _aux_loss(probs, mask):
    """GShard load-balance loss: E * sum_e mean_t(probs_e) * mean_t(mask_e).
    (reference: gshard_gate.py / switch router loss)"""
    e = probs.shape[1]
    density = jnp.mean(mask, axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    return jnp.sum(density * density_proxy) * e


def _dense_from_route(idx, pos, gates, kept, e, capacity):
    """Materialize the dense GShard (T,E,C) dispatch/combine tensors from
    a ragged routing table. Out-of-range pos one-hots to zeros, so dropped
    (t, k) slots vanish even before the ``kept`` mask. Accumulated one k
    at a time so peak memory stays O(T*E*C), not O(T*K*E*C)."""
    k = idx.shape[1]
    disp = comb = None
    for i in range(k):
        d_i = (_one_hot(idx[:, i], e)[:, :, None]
               * _one_hot(pos[:, i], capacity)[:, None, :]
               * kept[:, i, None, None])                    # (T, E, C)
        c_i = d_i * gates[:, i, None, None]
        disp = d_i if disp is None else jnp.maximum(disp, d_i)
        comb = c_i if comb is None else comb + c_i
    return disp, comb


class _GateBase:
    """Gates are lightweight strategy objects. ``route(logits, capacity)``
    is the primitive: a RAGGED routing table
    (idx (T,K) i32, pos (T,K) i32, gates (T,K) f32 — zeroed where dropped,
    kept (T,K) f32, aux scalar) with K = top_k. ``__call__`` derives the
    dense (T,E,C) dispatch/combine tensors from it (the einsum path);
    MoELayer's scatter path consumes the table directly so dispatch
    memory stays O(T*K + E*C*d) where sep x ep meshes make (T,E,C)
    explode (VERDICT r4 #8)."""

    top_k = 1

    def route(self, logits, capacity):
        raise NotImplementedError

    def __call__(self, logits, capacity):
        idx, pos, gates, kept, aux = self.route(logits, capacity)
        disp, comb = _dense_from_route(idx, pos, gates, kept,
                                       logits.shape[1], capacity)
        return disp, comb, aux


class SwitchGate(_GateBase):
    """Top-1 routing with capacity dropping (Switch Transformer;
    reference switch_gate.py)."""

    top_k = 1

    def route(self, logits, capacity):
        t, e = logits.shape
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        idx1 = jnp.argmax(probs, axis=-1)
        mask1 = _one_hot(idx1, e)
        aux = _aux_loss(probs, mask1)
        pos1 = jnp.sum(_positions_in_expert(mask1) * mask1, axis=1)
        keep1 = (pos1 < capacity).astype(jnp.float32)
        gate1 = jnp.sum(probs * mask1, axis=1) * keep1
        return (idx1[:, None].astype(jnp.int32),
                pos1[:, None].astype(jnp.int32),
                gate1[:, None], keep1[:, None], aux)


class GShardGate(_GateBase):
    """Top-2 routing with capacity (GShard; reference gshard_gate.py)."""

    top_k = 2

    def route(self, logits, capacity):
        t, e = logits.shape
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        idx1 = jnp.argmax(probs, axis=-1)
        mask1 = _one_hot(idx1, e)
        probs_wo1 = probs * (1.0 - mask1)
        idx2 = jnp.argmax(probs_wo1, axis=-1)
        mask2 = _one_hot(idx2, e)

        aux = _aux_loss(probs, mask1)

        pos1 = jnp.sum(_positions_in_expert(mask1) * mask1, axis=1)
        count1 = jnp.sum(mask1, axis=0, keepdims=True)          # (1, E)
        pos2 = jnp.sum((_positions_in_expert(mask2) + count1) * mask2,
                       axis=1)
        keep1 = (pos1 < capacity).astype(jnp.float32)
        keep2 = (pos2 < capacity).astype(jnp.float32)

        g1 = jnp.sum(probs * mask1, axis=1) * keep1
        g2 = jnp.sum(probs * mask2, axis=1) * keep2
        denom = jnp.maximum(g1 + g2, 1e-9)
        g1, g2 = g1 / denom, g2 / denom

        idx = jnp.stack([idx1, idx2], axis=1).astype(jnp.int32)
        pos = jnp.stack([pos1, pos2], axis=1).astype(jnp.int32)
        gates = jnp.stack([g1, g2], axis=1)
        kept = jnp.stack([keep1, keep2], axis=1)
        return idx, pos, gates, kept, aux


class NaiveGate(_GateBase):
    """Top-k softmax routing without dropping (reference naive_gate.py);
    capacity is still honored to keep shapes static, but the default
    MoELayer sizes it so nothing drops (capacity_factor >= num_experts /
    top_k covers the worst case)."""

    def __init__(self, top_k=2):
        self.top_k = top_k

    def route(self, logits, capacity):
        t, e = logits.shape
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        remaining = probs
        count = jnp.zeros((1, e), jnp.float32)
        aux = _aux_loss(probs, _one_hot(jnp.argmax(probs, axis=-1), e))
        idxs, poss, gs, keeps = [], [], [], []
        for _ in range(self.top_k):
            idx = jnp.argmax(remaining, axis=-1)
            mask = _one_hot(idx, e)
            pos = jnp.sum((_positions_in_expert(mask) + count) * mask,
                          axis=1)
            keep = (pos < capacity).astype(jnp.float32)
            g = jnp.sum(probs * mask, axis=1) * keep
            idxs.append(idx)
            poss.append(pos)
            gs.append(g)
            keeps.append(keep)
            count = count + jnp.sum(mask, axis=0, keepdims=True)
            remaining = remaining * (1.0 - mask)
        return (jnp.stack(idxs, axis=1).astype(jnp.int32),
                jnp.stack(poss, axis=1).astype(jnp.int32),
                jnp.stack(gs, axis=1), jnp.stack(keeps, axis=1), aux)
