"""paddle.incubate.multiprocessing (parity: python/paddle/incubate/
multiprocessing/ — tensor-aware reductions for mp queues; __all__ is
empty in the reference). Tensors cross process boundaries as numpy
payloads here (jax arrays are not shareable cross-process)."""
from __future__ import annotations

import multiprocessing
from multiprocessing import *  # noqa: F401,F403

__all__ = []


def _reduce_tensor(t):
    import numpy as np
    return (_rebuild_tensor, (np.asarray(t._data), t.stop_gradient,
                              t.name, t.persistable, t.trainable))


def _rebuild_tensor(arr, stop_gradient, name="", persistable=False,
                    trainable=None):
    import jax.numpy as jnp
    from ...core.tensor import Tensor
    out = Tensor(jnp.asarray(arr), stop_gradient=stop_gradient)
    out.name = name
    out.persistable = persistable
    if trainable is not None:
        out.trainable = trainable
    return out


def _install_reductions():
    import copyreg
    from ...core.tensor import Tensor
    copyreg.pickle(Tensor, _reduce_tensor)


_install_reductions()
