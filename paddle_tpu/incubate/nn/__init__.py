"""incubate.nn (parity: python/paddle/incubate/nn/)."""
from . import functional  # noqa: F401
