"""incubate.nn (parity: python/paddle/incubate/nn/)."""
from . import functional  # noqa: F401
from .layer import (FusedMultiHeadAttention, FusedFeedForward,  # noqa: F401
                    FusedTransformerEncoderLayer, FusedMultiTransformer,
                    FusedLinear, FusedBiasDropoutResidualLayerNorm,
                    FusedDropoutAdd, FusedEcMoe)
