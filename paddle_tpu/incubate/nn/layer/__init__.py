"""Fused transformer layers (parity: python/paddle/incubate/nn/layer/
fused_transformer.py — FusedMultiHeadAttention :196, FusedFeedForward
:502, FusedMultiTransformer :1025 — plus FusedLinear,
FusedBiasDropoutResidualLayerNorm, FusedDropoutAdd, FusedEcMoe).

The reference backs these with monolithic CUDA kernels
(fused_attention_op.cu, fused_feedforward_op.cu); here each layer calls
the incubate functional ops, which XLA fuses per block — one compiled
region per layer, the MXU doing the matmuls.
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from ....nn import functional as F
from ....nn.layer.layers import Layer
from ... import nn as _inc_nn

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer", "FusedMultiTransformer",
           "FusedLinear", "FusedBiasDropoutResidualLayerNorm",
           "FusedDropoutAdd", "FusedEcMoe"]


class FusedLinear(Layer):
    """(parity: paddle.incubate.nn.FusedLinear — gemm+bias epilogue)"""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        shape = [out_features, in_features] if transpose_weight \
            else [in_features, out_features]
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)
        self._transpose = transpose_weight

    def forward(self, x):
        return _inc_nn.functional.fused_linear(
            x, self.weight, self.bias, transpose_weight=self._transpose)


class FusedDropoutAdd(Layer):
    """(parity: paddle.incubate.nn.FusedDropoutAdd)"""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return _inc_nn.functional.fused_dropout_add(
            x, y, p=self.p, training=self.training, mode=self.mode)


class FusedBiasDropoutResidualLayerNorm(Layer):
    """out = LayerNorm(residual + dropout(x + bias)) (parity:
    paddle.incubate.nn.FusedBiasDropoutResidualLayerNorm)."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        from ....nn.initializer import Constant
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=weight_attr,
            default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)

    def forward(self, x, residual):
        h = x + self.linear_bias
        h = F.dropout(h, p=self.dropout_rate, training=self.training)
        h = residual + h
        return F.layer_norm(h, [self.embed_dim], weight=self.ln_scale,
                            bias=self.ln_bias, epsilon=self.epsilon)


class FusedMultiHeadAttention(Layer):
    """Pre/post-LN attention block with fused qkv (parity:
    paddle.incubate.nn.FusedMultiHeadAttention,
    fused_transformer.py:196)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, transpose_qkv_wb=False, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.epsilon = epsilon
        from ....nn.initializer import Constant, XavierUniform
        # fused qkv weight: (3, heads, head_dim, embed) like the reference
        self.qkv_weight = self.create_parameter(
            [3, num_heads, self.head_dim, embed_dim],
            attr=qkv_weight_attr, default_initializer=XavierUniform())
        self.qkv_bias = None if qkv_bias_attr is False else \
            self.create_parameter([3, num_heads, self.head_dim],
                                  attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter(
            [embed_dim, embed_dim], attr=linear_weight_attr,
            default_initializer=XavierUniform())
        self.linear_bias = None if linear_bias_attr is False else \
            self.create_parameter([embed_dim], attr=linear_bias_attr,
                                  is_bias=True)
        self.pre_ln_scale = self.create_parameter(
            [embed_dim], attr=pre_ln_scale_attr,
            default_initializer=Constant(1.0))
        self.pre_ln_bias = self.create_parameter([embed_dim], is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=ln_scale_attr,
            default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        """Delegates to the functional (ONE implementation of the fused
        block, incl. cache_kv incremental decode — returns (out, cache)
        when ``cache`` is given, the reference Cache contract)."""
        from .. import functional as IF
        return IF.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            pre_ln_epsilon=self.epsilon, qkv_bias=self.qkv_bias,
            linear_bias=self.linear_bias, cache_kv=cache,
            attn_mask=attn_mask, dropout_rate=self.dropout_rate,
            attn_dropout_rate=self.attn_dropout_rate,
            ln_epsilon=self.epsilon, training=self.training)


class FusedFeedForward(Layer):
    """Pre/post-LN MLP block (parity: paddle.incubate.nn.FusedFeedForward,
    fused_transformer.py:502)."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        from ....nn.initializer import Constant, XavierUniform
        self.d_model = d_model
        self.normalize_before = normalize_before
        self.activation = activation
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = act_dropout_rate \
            if act_dropout_rate is not None else dropout_rate
        self.epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], attr=linear1_weight_attr,
            default_initializer=XavierUniform())
        self.linear1_bias = self.create_parameter(
            [dim_feedforward], attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], attr=linear2_weight_attr,
            default_initializer=XavierUniform())
        self.linear2_bias = self.create_parameter(
            [d_model], attr=linear2_bias_attr, is_bias=True)
        self.ln1_scale = self.create_parameter(
            [d_model], attr=ln1_scale_attr,
            default_initializer=Constant(1.0))
        self.ln1_bias = self.create_parameter([d_model], is_bias=True)
        self.ln2_scale = self.create_parameter(
            [d_model], attr=ln2_scale_attr,
            default_initializer=Constant(1.0))
        self.ln2_bias = self.create_parameter([d_model], is_bias=True)

    def forward(self, src, cache=None):
        residual = src
        x = src
        if self.normalize_before:
            x = F.layer_norm(x, [self.d_model], weight=self.ln1_scale,
                             bias=self.ln1_bias, epsilon=self.epsilon)
        x = _inc_nn.functional.fused_bias_act(
            F.linear(x, self.linear1_weight), self.linear1_bias,
            act_method=self.activation)
        x = F.dropout(x, p=self.act_dropout_rate, training=self.training)
        x = F.linear(x, self.linear2_weight, self.linear2_bias)
        x = F.dropout(x, p=self.dropout_rate, training=self.training)
        x = residual + x
        if not self.normalize_before:
            x = F.layer_norm(x, [self.d_model], weight=self.ln2_scale,
                             bias=self.ln2_bias, epsilon=self.epsilon)
        return x


class FusedTransformerEncoderLayer(Layer):
    """(parity: paddle.incubate.nn.FusedTransformerEncoderLayer)"""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate if attn_dropout_rate
            is not None else dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        if cache is not None:
            attn, cache_out = self.fused_attn(src, attn_mask=src_mask,
                                              cache=cache)
            return self.ffn(attn), cache_out
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


class FusedMultiTransformer(Layer):
    """Stacked fused transformer layers for generation (parity:
    paddle.incubate.nn.FusedMultiTransformer,
    fused_transformer.py:1025)."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu",
                 normalize_before=True, num_layers=1, nranks=1,
                 ring_id=-1, name=None, **kw):
        super().__init__()
        attr_kwargs = {k: v for k, v in kw.items()
                       if k.endswith(("_attrs", "_attr")) and v is not None}
        if attr_kwargs:
            raise NotImplementedError(
                "FusedMultiTransformer per-layer weight attrs are not "
                f"supported yet: {sorted(attr_kwargs)}; load weights via "
                "set_state_dict instead")
        from ....nn.layer.container import LayerList
        self.layers = LayerList([
            FusedTransformerEncoderLayer(
                embed_dim, num_heads, dim_feedforward,
                dropout_rate=dropout_rate, activation=activation,
                normalize_before=normalize_before)
            for _ in range(num_layers)])

    def forward(self, src, attn_mask=None, caches=None, **kw):
        """Generation decode: per-layer ``caches`` of (2, B, H, T, D)
        grow each step; returns (out, cache_outs) when given (the
        reference's decode contract, fused_transformer.py:1025).
        Preallocated-cache time_step decode is not supported (raises)."""
        extra = {k: v for k, v in kw.items() if v is not None}
        if extra:
            raise NotImplementedError(
                "FusedMultiTransformer.forward: unsupported kwargs "
                f"{sorted(extra)} — silently dropping decode parameters "
                "(time_step/rotary_embs/pre_caches/seq_lens) would give "
                "wrong outputs; only growing `caches` decode is supported")
        h = src
        if caches is not None:
            if len(caches) != len(self.layers):
                raise ValueError(
                    f"caches has {len(caches)} entries for "
                    f"{len(self.layers)} layers")
            outs = []
            for lyr, cache in zip(self.layers, caches):
                h, c = lyr(h, src_mask=attn_mask, cache=cache)
                outs.append(c)
            return h, outs
        for lyr in self.layers:
            h = lyr(h, src_mask=attn_mask)
        return h


class FusedEcMoe(Layer):
    """Expert-choice MoE layer (parity: paddle.incubate.nn.FusedEcMoe —
    the reference's fused expert-choice gating + expert ffn kernel).
    Experts pick tokens (capacity = S*B/E * cap) instead of tokens
    picking experts; dense einsum over the expert axis."""

    def __init__(self, hidden_size, inter_size, num_experts,
                 act_type="gelu", weight_attr=None, bias_attr=None):
        super().__init__()
        from ....nn.initializer import XavierUniform
        self.hidden_size = hidden_size
        self.num_experts = num_experts
        self.act_type = act_type
        self.gate = self.create_parameter(
            [hidden_size, num_experts], attr=weight_attr,
            default_initializer=XavierUniform())
        self.w1 = self.create_parameter(
            [num_experts, hidden_size, inter_size], attr=weight_attr,
            default_initializer=XavierUniform())
        self.b1 = self.create_parameter([num_experts, inter_size],
                                        attr=bias_attr, is_bias=True)
        self.w2 = self.create_parameter(
            [num_experts, inter_size, hidden_size], attr=weight_attr,
            default_initializer=XavierUniform())
        self.b2 = self.create_parameter([num_experts, hidden_size],
                                        attr=bias_attr, is_bias=True)

    def forward(self, x, gate=None):
        from ....core.dispatch import run_op
        import jax

        use_ext_gate = gate is not None

        def fn(a, g_w, w1, b1, w2, b2, *ext):
            b, s, h = a.shape
            e = self.num_experts
            tokens = a.reshape(b * s, h)
            if ext:  # externally computed gate logits (reference contract)
                logits = ext[0].reshape(b * s, e)
            else:
                logits = tokens @ g_w                   # (T, E)
            probs = jax.nn.softmax(logits, axis=-1)
            cap = max((b * s) // e, 1)
            # expert-choice: each expert takes its top-cap tokens
            gval, gidx = jax.lax.top_k(probs.T, cap)    # (E, cap)
            picked = tokens[gidx]                       # (E, cap, H)
            hmid = jnp.einsum("ech,ehi->eci", picked, w1) + b1[:, None]
            act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu}[self.act_type]
            hmid = act(hmid)
            hout = jnp.einsum("eci,eih->ech", hmid, w2) + b2[:, None]
            hout = hout * gval[..., None]
            out = jnp.zeros_like(tokens)
            out = out.at[gidx.reshape(-1)].add(
                hout.reshape(-1, h))
            return out.reshape(b, s, h)
        ops = [x, self.gate, self.w1, self.b1, self.w2, self.b2]
        if use_ext_gate:
            ops.append(gate)
        return run_op("fused_ec_moe", fn, tuple(ops))
