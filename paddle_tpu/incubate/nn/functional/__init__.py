"""incubate.nn.functional fused ops (parity:
python/paddle/incubate/nn/functional/ — fused_rotary_position_embedding,
fused_rms_norm, fused_layer_norm, fused_dropout_add, swiglu).

TPU-native note: "fused" here means fused-in-the-compiled-program. The
norms route through the Pallas kernels (ops/pallas/norms.py); RoPE,
dropout+add, and swiglu are XLA composites that the compiler fuses into
neighboring ops — hand kernels would only re-derive what XLA already
does for elementwise chains (see ops/pallas/norms.py docstring).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....core.dispatch import run_op
from ....nn import functional as F

__all__ = ["fused_rotary_position_embedding", "fused_rms_norm",
           "fused_layer_norm", "fused_dropout_add", "swiglu",
           "fused_linear", "fused_bias_act",
           "masked_multihead_attention", "block_multihead_attention"]


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True,
                                    time_major=False,
                                    rotary_emb_base=10000.0):
    """Parity: incubate fused_rope (fusion/gpu/fused_rope). q/k/v are
    [B, S, H, D] ([S, B, H, D] when time_major); sin/cos accept [S, D/2],
    [S, D], or paddle's [1, S, 1, D]; omitted tables are computed from
    ``rotary_emb_base``."""
    if time_major:
        def _tm(t):
            return None if t is None else t.transpose([1, 0, 2, 3])
        q, k, v = _tm(q), _tm(k), _tm(v)
        out = fused_rotary_position_embedding(
            q, k, v, sin=sin, cos=cos, position_ids=position_ids,
            use_neox_rotary_style=use_neox_rotary_style, time_major=False,
            rotary_emb_base=rotary_emb_base)
        return tuple(_tm(o) for o in out)
    if sin is None or cos is None:
        import numpy as np
        seq, d = q.shape[1], q.shape[-1]
        inv = 1.0 / (rotary_emb_base ** (np.arange(0, d, 2) / d))
        freqs = np.outer(np.arange(seq), inv)  # [S, D/2]
        cos = jnp.asarray(np.cos(freqs), jnp.float32)
        sin = jnp.asarray(np.sin(freqs), jnp.float32)

    def rope(x_arr, cos_arr, sin_arr):
        d = x_arr.shape[-1]

        def table(t):
            # accept [S, D/2], [S, D], or paddle's [1, S, 1, D]
            t2 = jnp.reshape(t, (t.shape[-3] if t.ndim == 4 else t.shape[0],
                                 t.shape[-1]))
            if t2.shape[-1] == d:  # full-width table: one entry per freq
                return t2[..., : d // 2] if use_neox_rotary_style \
                    else t2[..., ::2]
            return t2
        c, s = table(cos_arr), table(sin_arr)
        if position_ids is not None:
            pid = position_ids._data if hasattr(position_ids, "_data") \
                else jnp.asarray(position_ids)
            c = c[pid]  # [B, S, D/2]
            s = s[pid]
            c = c[:, :, None, :]
            s = s[:, :, None, :]
        else:
            c = c[None, :, None, :]
            s = s[None, :, None, :]
        if use_neox_rotary_style:
            half = x_arr.shape[-1] // 2
            x1, x2 = x_arr[..., :half], x_arr[..., half:]
            return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s],
                                   axis=-1)
        x1, x2 = x_arr[..., ::2], x_arr[..., 1::2]
        o1 = x1 * c - x2 * s
        o2 = x2 * c + x1 * s
        return jnp.stack([o1, o2], axis=-1).reshape(x_arr.shape)

    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue
        outs.append(run_op("fused_rope",
                           lambda a, c, s: rope(a, c, s), (t, cos, sin)))
    return tuple(outs)


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kwargs):
    """Parity: incubate fused_rms_norm -> (out, invvar).
    Routes to the Pallas rms_norm kernel. Multi-axis normalization
    (begin_norm_axis < ndim-1) flattens the trailing axes first."""
    del kwargs
    ndim = x.ndim
    axis = begin_norm_axis % ndim if begin_norm_axis != -1 else ndim - 1
    if axis != ndim - 1:
        shape = list(x.shape)
        flat = x.reshape(shape[:axis] + [-1])
        w_flat = norm_weight.reshape([-1])
        out_flat, invvar = fused_rms_norm(flat, w_flat, None, epsilon)
        out = out_flat.reshape(shape)
        if norm_bias is not None:
            out = out + norm_bias
        return out, invvar
    out = F.rms_norm(x, weight=norm_weight, epsilon=epsilon)
    if norm_bias is not None:
        out = out + norm_bias
    # under jit XLA CSEs this with the kernel's internal mean-of-squares;
    # eager callers needing only `out` can use F.rms_norm directly
    invvar = run_op(
        "rms_invvar",
        lambda a: jax.lax.rsqrt(
            jnp.mean(jnp.square(a.astype(jnp.float32)), axis=-1) + epsilon),
        (x,))
    return out, invvar


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, **kwargs):
    del kwargs
    shape = x.shape[begin_norm_axis:] if begin_norm_axis != -1 \
        else x.shape[-1:]
    return F.layer_norm(x, shape, weight=norm_weight, bias=norm_bias,
                        epsilon=epsilon)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """Parity: incubate fused_dropout_add — dropout(x) + y in one program."""
    del name
    return F.dropout(x, p=p, training=training, mode=mode) + y


def swiglu(x, y=None, name=None):
    """Parity: incubate swiglu: silu(x) * y (y defaults to the second half
    of x split on the last axis)."""
    del name
    if y is not None:
        return run_op("swiglu", lambda a, b: _silu(a) * b, (x, y))

    def fn(a):
        h = a.shape[-1] // 2
        return _silu(a[..., :h]) * a[..., h:]
    return run_op("swiglu", fn, (x,))


_silu = jax.nn.silu


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """Parity: incubate fused_linear (fused_gemm_epilogue): XLA fuses the
    bias epilogue into the MXU matmul."""
    del name

    def fn(a, w, *rest):
        ww = w.T if transpose_weight else w
        out = jnp.matmul(a, ww)
        if rest:
            out = out + rest[0]
        return out
    ops = (x, weight) if bias is None else (x, weight, bias)
    return run_op("fused_linear", fn, ops)


def fused_bias_act(x, bias=None, act_method="gelu", name=None):
    """Parity: fused_bias_act (fusion/gpu/fused_bias_act)."""
    del name
    acts = {"gelu": jax.nn.gelu, "relu": jax.nn.relu, "silu": _silu,
            "swiglu": lambda a: _silu(a[..., :a.shape[-1] // 2])
            * a[..., a.shape[-1] // 2:]}
    if act_method not in acts:
        raise ValueError(f"unsupported act_method {act_method}")

    def fn(a, *rest):
        if rest:
            a = a + rest[0]
        return acts[act_method](a)
    ops = (x,) if bias is None else (x, bias)
    return run_op("fused_bias_act", fn, ops)


# -- inference-decode attention (the reference's serving kernel class) -------

def masked_multihead_attention(x, cache_kv, src_mask=None, seq_lens=None,
                               num_heads=None, name=None):
    """Single-step decode attention with a contiguous KV cache (parity:
    paddle/phi/kernels/fusion/gpu/masked_multihead_attention.cu via
    incubate.nn.functional.masked_multihead_attention).

    x         [B, 3*H*D]  — the new token's fused qkv
    cache_kv  [2, B, H, S_max, D] — rolling cache; the new k/v are written
              at position ``seq_lens`` and attention runs over the prefix
    seq_lens  [B] int32 — tokens already in the cache per sequence
    -> (out [B, H*D], updated cache_kv)

    TPU-native: one XLA program — dynamic_update_slice writes the cache,
    an iota mask closes the future; decode is HBM-bound so XLA's fusion
    is the right lowering (no hand kernel needed)."""
    from ....core.tensor import Tensor
    if num_heads is None:
        h = cache_kv.shape[2] if not isinstance(cache_kv, Tensor) \
            else cache_kv._data.shape[2]
    else:
        h = num_heads

    def fn(*args):
        if src_mask is not None:
            xa, cache, lens, mask = args
        else:
            (xa, cache, lens), mask = args, None
        b = xa.shape[0]
        d = cache.shape[-1]
        smax = cache.shape[3]
        qkv = xa.reshape(b, 3, h, d)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]   # [B, H, D]

        def upd(cache_b, k_b, v_b, n):
            z = jnp.int32(0)  # index dtypes must match under x64
            ck = jax.lax.dynamic_update_slice(cache_b[0], k_b[:, None, :],
                                              (z, n, z))
            cv = jax.lax.dynamic_update_slice(cache_b[1], v_b[:, None, :],
                                              (z, n, z))
            return jnp.stack([ck, cv])

        # cache [2,B,H,S,D] -> per-batch [2,H,S,D]
        cache_b = jnp.moveaxis(cache, 1, 0)          # [B,2,H,S,D]
        new_cache_b = jax.vmap(upd)(cache_b, k, v,
                                    lens.astype(jnp.int32))
        new_cache = jnp.moveaxis(new_cache_b, 0, 1)  # [2,B,H,S,D]

        keys, vals = new_cache[0], new_cache[1]      # [B,H,S,D]
        scores = jnp.einsum("bhd,bhsd->bhs", q, keys) * (d ** -0.5)
        pos = jnp.arange(smax)[None, None, :]
        valid = pos <= lens.astype(jnp.int32)[:, None, None]
        scores = jnp.where(valid, scores, -jnp.inf)
        if mask is not None:
            # additive mask over cache positions (reference applies it to
            # the scores): accept [B, S], [B, 1, S] or [B, H, S]
            m = mask.reshape(b, -1, mask.shape[-1])
            scores = scores + m.astype(scores.dtype)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        out = jnp.einsum("bhs,bhsd->bhd", probs.astype(vals.dtype), vals)
        return out.reshape(b, h * d), new_cache

    ops = (x, cache_kv, seq_lens) if src_mask is None \
        else (x, cache_kv, seq_lens, src_mask)
    return run_op("masked_multihead_attention", fn, ops)


def block_multihead_attention(q, k, v, key_cache, value_cache, block_tables,
                              seq_lens, block_size=None, name=None):
    """Paged-KV decode attention (parity:
    paddle/phi/kernels/fusion/gpu/block_multi_head_attention.cu — the
    vLLM-style paged attention the reference serves with).

    q, k, v      [B, H, D]    — the new token per sequence
    key_cache /
    value_cache  [num_blocks, H, block_size, D] — the shared block pool
    block_tables [B, max_blocks_per_seq] int32  — logical->physical blocks
    seq_lens     [B] int32    — tokens already stored per sequence
    -> (out [B, H, D], new_key_cache, new_value_cache)

    TPU-native: block gather is one XLA gather over the pool; the scatter
    of the new token hits exactly one (block, slot) per sequence. Gather +
    batched matmul keeps the MXU busy; no CUDA-style warp choreography."""

    def fn(qa, ka, va, kc, vc, tables, lens):
        b, h, d = qa.shape
        bs = kc.shape[2] if block_size is None else block_size
        max_blocks = tables.shape[1]
        lens = lens.astype(jnp.int32)
        if not isinstance(lens, jax.core.Tracer):
            # eager path: catch the append-without-free-slot contract
            # violation that a traced run would silently clamp
            if bool((lens >= max_blocks * bs).any()):
                raise ValueError(
                    "block_multihead_attention: a sequence's block table "
                    f"is full (len >= {max_blocks * bs}); allocate a new "
                    "block before appending (the reference's block "
                    "manager contract)")
        # scatter the new k/v into (physical block, slot)
        blk_idx = lens // bs
        slot = lens % bs
        phys = jnp.take_along_axis(tables, blk_idx[:, None], 1)[:, 0]

        def write(cache, token):
            def one(cache, i):
                z = jnp.int32(0)
                return jax.lax.dynamic_update_slice(
                    cache, token[i][None, :, None, :].astype(cache.dtype),
                    (phys[i].astype(jnp.int32), z,
                     slot[i].astype(jnp.int32), z))
            for i in range(b):  # b is small at decode time; unrolled scatter
                cache = one(cache, i)
            return cache

        new_kc = write(kc, ka)
        new_vc = write(vc, va)

        # gather each sequence's blocks: [B, max_blocks, H, bs, D]
        gk = new_kc[tables]
        gv = new_vc[tables]
        # -> [B, H, max_blocks*bs, D]
        gk = jnp.moveaxis(gk, 2, 1).reshape(b, h, max_blocks * bs, d)
        gv = jnp.moveaxis(gv, 2, 1).reshape(b, h, max_blocks * bs, d)
        scores = jnp.einsum("bhd,bhsd->bhs", qa, gk) * (d ** -0.5)
        pos = jnp.arange(max_blocks * bs)[None, None, :]
        valid = pos <= lens[:, None, None]
        scores = jnp.where(valid, scores, -jnp.inf)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        out = jnp.einsum("bhs,bhsd->bhd", probs.astype(gv.dtype), gv)
        return out, new_kc, new_vc

    return run_op("block_multihead_attention", fn,
                  (q, k, v, key_cache, value_cache, block_tables, seq_lens))
